"""SI units: parsing, arithmetic, and printing.

Host-side analogue of the reference's `InterfaceDynamicQuantities.jl`
(/root/reference/src/InterfaceDynamicQuantities.jl:55-89): user unit specs
(strings like ``"m/s^2"``, ``"kg*m"``) are parsed into a 7-exponent SI
dimension vector plus a scale factor. Only the *dimensions* participate in
dimensional analysis (matching DynamicQuantities semantics — magnitudes are
not used to rescale data).

The device-side dimensional check consumes :func:`dims_to_array` vectors;
see :mod:`..ops.dims_eval`.
"""

from __future__ import annotations

import dataclasses
import re
from fractions import Fraction
from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "Dimensions",
    "Quantity",
    "parse_unit",
    "dims_to_array",
    "pretty_dims",
    "DIMENSIONLESS",
    "N_DIMS",
]

# Base dimension order: length, mass, time, current, temperature,
# luminosity, amount (DynamicQuantities' canonical order).
N_DIMS = 7
_DIM_NAMES = ("m", "kg", "s", "A", "K", "cd", "mol")


@dataclasses.dataclass(frozen=True)
class Dimensions:
    """Rational exponents over the 7 SI base dimensions."""

    exps: Tuple[Fraction, ...] = (Fraction(0),) * N_DIMS

    def __post_init__(self):
        assert len(self.exps) == N_DIMS

    @staticmethod
    def base(i: int, exp=1) -> "Dimensions":
        e = [Fraction(0)] * N_DIMS
        e[i] = Fraction(exp)
        return Dimensions(tuple(e))

    def __mul__(self, other: "Dimensions") -> "Dimensions":
        return Dimensions(tuple(a + b for a, b in zip(self.exps, other.exps)))

    def __truediv__(self, other: "Dimensions") -> "Dimensions":
        return Dimensions(tuple(a - b for a, b in zip(self.exps, other.exps)))

    def __pow__(self, p) -> "Dimensions":
        p = Fraction(p).limit_denominator(1000) if not isinstance(p, Fraction) else p
        return Dimensions(tuple(a * p for a in self.exps))

    @property
    def is_dimensionless(self) -> bool:
        return all(e == 0 for e in self.exps)

    def __str__(self) -> str:
        return pretty_dims(self)


DIMENSIONLESS = Dimensions()


@dataclasses.dataclass(frozen=True)
class Quantity:
    """A scale factor times SI dimensions (e.g. km = 1000 * m)."""

    scale: float = 1.0
    dims: Dimensions = DIMENSIONLESS

    def __mul__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.scale * other.scale, self.dims * other.dims)

    def __truediv__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.scale / other.scale, self.dims / other.dims)

    def __pow__(self, p) -> "Quantity":
        return Quantity(self.scale ** float(p), self.dims ** p)


def _q(scale: float, **dims) -> Quantity:
    idx = {n: i for i, n in enumerate(_DIM_NAMES)}
    e = [Fraction(0)] * N_DIMS
    for name, exp in dims.items():
        e[idx[name]] = Fraction(exp)
    return Quantity(scale, Dimensions(tuple(e)))


# SI base + common derived units. Mass base is kg; "g" carries scale 1e-3.
_UNIT_TABLE: Dict[str, Quantity] = {
    "": Quantity(),
    "1": Quantity(),
    "m": _q(1, m=1),
    "g": _q(1e-3, kg=1),
    "s": _q(1, s=1),
    "A": _q(1, A=1),
    "K": _q(1, K=1),
    "cd": _q(1, cd=1),
    "mol": _q(1, mol=1),
    # Derived
    "Hz": _q(1, s=-1),
    "N": _q(1, kg=1, m=1, s=-2),
    "Pa": _q(1, kg=1, m=-1, s=-2),
    "J": _q(1, kg=1, m=2, s=-2),
    "W": _q(1, kg=1, m=2, s=-3),
    "C": _q(1, A=1, s=1),
    "V": _q(1, kg=1, m=2, s=-3, A=-1),
    "F": _q(1, kg=-1, m=-2, s=4, A=2),
    "Ohm": _q(1, kg=1, m=2, s=-3, A=-2),
    "S": _q(1, kg=-1, m=-2, s=3, A=2),
    "Wb": _q(1, kg=1, m=2, s=-2, A=-1),
    "T": _q(1, kg=1, s=-2, A=-1),
    "H": _q(1, kg=1, m=2, s=-2, A=-2),
    "L": _q(1e-3, m=3),
    "bar": _q(1e5, kg=1, m=-1, s=-2),
    "eV": _q(1.602176634e-19, kg=1, m=2, s=-2),
    "min": _q(60, s=1),
    "h": _q(3600, s=1),
    "hr": _q(3600, s=1),
    "day": _q(86400, s=1),
    "rad": Quantity(),
    "sr": Quantity(),
    "deg": Quantity(np.pi / 180),
    "percent": Quantity(0.01),
}

_PREFIXES: Dict[str, float] = {
    "y": 1e-24, "z": 1e-21, "a": 1e-18, "f": 1e-15, "p": 1e-12,
    "n": 1e-9, "u": 1e-6, "µ": 1e-6, "μ": 1e-6, "m": 1e-3, "c": 1e-2,
    "d": 1e-1, "da": 1e1, "h": 1e2, "k": 1e3, "M": 1e6, "G": 1e9,
    "T": 1e12, "P": 1e15, "E": 1e18, "Z": 1e21, "Y": 1e24,
}

_EXP_RE = re.compile(r"^(?P<unit>[^\^]+?)(?:\^(?P<exp>-?\d+(?:\.\d+)?(?://\d+)?))?$")


def _lookup_unit(token: str) -> Quantity:
    if token in _UNIT_TABLE:
        return _UNIT_TABLE[token]
    # Prefix split: longest prefix first ("da" before "d").
    for plen in (2, 1):
        if len(token) > plen:
            pre, rest = token[:plen], token[plen:]
            if pre in _PREFIXES and rest in _UNIT_TABLE:
                base = _UNIT_TABLE[rest]
                return Quantity(base.scale * _PREFIXES[pre], base.dims)
    raise ValueError(f"Unknown unit {token!r}")


def _parse_factor(token: str) -> Quantity:
    m = _EXP_RE.match(token)
    if m is None:
        raise ValueError(f"Cannot parse unit factor {token!r}")
    q = _lookup_unit(m.group("unit").strip())
    exp_s = m.group("exp")
    if exp_s is None:
        return q
    if "//" in exp_s:
        num, den = exp_s.split("//")
        exp: Union[Fraction, float] = Fraction(int(num), int(den))
    elif "." in exp_s:
        exp = float(exp_s)
    else:
        exp = Fraction(int(exp_s))
    return q ** exp


def parse_unit(spec) -> Quantity:
    """Parse a unit spec into a :class:`Quantity`.

    Accepts: ``None``/``""``/``"1"`` (dimensionless), strings like
    ``"m/s^2"``, ``"kg*m^2/s^2"``, ``"m s^-1"`` (space = multiply), a
    :class:`Quantity`/:class:`Dimensions`, or a 7-sequence of exponents.
    """
    if spec is None:
        return Quantity()
    if isinstance(spec, Quantity):
        return spec
    if isinstance(spec, Dimensions):
        return Quantity(1.0, spec)
    if isinstance(spec, (list, tuple, np.ndarray)) and len(spec) == N_DIMS:
        return Quantity(
            1.0,
            Dimensions(
                tuple(Fraction(float(e)).limit_denominator(1000) for e in spec)
            ),
        )
    s = str(spec).strip()
    if s in ("", "1"):
        return Quantity()
    # Tokenize factors and '*'/'/' dividers. The factor pattern consumes a
    # whole `unit^exp` including rational `//` exponents (`m^1//2`), so the
    # exponent's slashes are never mistaken for division.
    token_re = re.compile(
        r"[^\s*/^]+(?:\^-?[0-9]+(?://[0-9]+|\.[0-9]+)?)?|[*/]"
    )
    q = Quantity()
    divide = False
    pos = 0
    for m in token_re.finditer(s):
        if s[pos:m.start()].strip():
            raise ValueError(f"Cannot parse unit spec {spec!r}")
        pos = m.end()
        part = m.group(0)
        if part == "*":
            divide = False
            continue
        if part == "/":
            divide = True
            continue
        factor = _parse_factor(part)
        q = q / factor if divide else q * factor
        # After a '/', only the immediately following factor is divided
        # when separated by spaces; '/' binds to the next single factor.
        divide = False
    if s[pos:].strip():
        raise ValueError(f"Cannot parse unit spec {spec!r}")
    return q


def dims_to_array(dims: Dimensions) -> np.ndarray:
    """[7] float32 exponent vector for the device-side check."""
    return np.asarray([float(e) for e in dims.exps], np.float32)


_SUP = str.maketrans("0123456789-./", "⁰¹²³⁴⁵⁶⁷⁸⁹⁻·ᐟ")


def pretty_dims(dims: Dimensions) -> str:
    """Render dimensions like ``m s⁻²`` (empty string if dimensionless)."""
    parts = []
    for name, e in zip(_DIM_NAMES, dims.exps):
        if e == 0:
            continue
        if e == 1:
            parts.append(name)
        else:
            parts.append(name + str(e).translate(_SUP))
    return " ".join(parts)


def units_to_dims_arrays(
    X_units: Optional[Sequence], nfeatures: int, y_units=None
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Parse per-feature / output unit specs into dims arrays.

    Returns ``(x_dims[nfeatures, 7], y_dims[7])``. ``x_dims`` is None only
    when no units were given at all; unspecified feature units default to
    dimensionless. ``y_dims`` is None whenever ``y_units`` was not given —
    the output-dimension check is then skipped entirely (matching the
    reference, src/DimensionalAnalysis.jl:250-255: a missing y unit
    accepts any output dims).
    """
    if X_units is None and y_units is None:
        return None, None
    if X_units is None:
        x_dims = np.zeros((nfeatures, N_DIMS), np.float32)
    else:
        if len(X_units) != nfeatures:
            raise ValueError(
                f"X_units has {len(X_units)} entries for {nfeatures} features"
            )
        x_dims = np.stack([dims_to_array(parse_unit(u).dims) for u in X_units])
    y_dims = None if y_units is None else dims_to_array(parse_unit(y_units).dims)
    return x_dims, y_dims


class QuantityArray(np.ndarray):
    """A numpy array carrying a unit specification string.

    The Python face of the reference's unit-typed MLJ predictions
    (src/MLJInterface.jl:366-380): predictions echo the ``y_units``
    given at fit time via ``.unit`` while behaving as plain arrays
    everywhere else.
    """

    def __new__(cls, values, unit):
        obj = np.asarray(values).view(cls)
        obj.unit = unit
        return obj

    def __array_finalize__(self, obj):
        if obj is None:
            return
        self.unit = getattr(obj, "unit", None)
