"""Search options (TPU analogue of src/OptionsStruct.jl + src/Options.jl).

`Options` carries every search hyperparameter of the reference's ~65-field
struct (/root/reference/src/OptionsStruct.jl:177-259) with the v2 default
hyperparameter set (/root/reference/src/Options.jl:1161-1208). Runtime
execution parameters (parallelism, niterations, verbosity) live in
`RuntimeOptions` in the api layer, mirroring the reference's two-tier
config split (src/SearchUtils.jl:79-234).

`Options` instances are treated as *static* (hashable) in jitted code;
device-side constant tables (complexity mapping, constraint tables,
mutation-weight vectors) are derived once per search.
"""

from __future__ import annotations

import dataclasses
from typing import (Any, Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple, Union)

import numpy as np

from ..ops.operators import DEFAULT_BINARY, DEFAULT_UNARY, Op, OperatorSet

__all__ = ["MutationWeights", "ComplexityMapping", "Options", "MUTATION_KINDS",
           "EvalGeometry", "KERNEL_TREE_BLOCK", "KERNEL_TILE_ROWS"]

# Candidate-eval kernel launch-geometry defaults (ops/fused_eval.py's
# fused_cost/fused_loss wrappers). These are THE defaults: every layer
# that needs resolved geometry goes through Options.eval_geometry()
# instead of re-spelling a `x if x is not None else N` fallback chain.
KERNEL_TREE_BLOCK = 8
KERNEL_TILE_ROWS = 16384


class EvalGeometry(NamedTuple):
    """Resolved candidate-eval kernel launch geometry.

    The single source of the kernel-geometry fallback (tree_block=8,
    tile_rows=16384): evolve/step.py, evolve/engine.py and the bench
    provenance all resolve unset Options knobs through
    :meth:`Options.eval_geometry` rather than forking their own
    `getattr(...) or default` chains."""

    tree_block: int = KERNEL_TREE_BLOCK
    tile_rows: int = KERNEL_TILE_ROWS


# Order matters: it defines the integer encoding of mutation kinds used on
# device (mirrors `fieldnames(MutationWeights)`,
# /root/reference/src/MutationWeights.jl:103-120).
MUTATION_KINDS = (
    "mutate_constant",
    "mutate_operator",
    "mutate_feature",
    "swap_operands",
    "rotate_tree",
    "add_node",
    "insert_node",
    "delete_node",
    "simplify",
    "randomize",
    "do_nothing",
    "optimize",
    "form_connection",
    "break_connection",
)


@dataclasses.dataclass
class MutationWeights:
    """Relative frequencies of each mutation (src/MutationWeights.jl:103-118).

    Defaults are the v2 tuned values from `default_options()`
    (/root/reference/src/Options.jl:1174-1188).
    """

    mutate_constant: float = 0.0346
    mutate_operator: float = 0.293
    mutate_feature: float = 0.1
    swap_operands: float = 0.198
    rotate_tree: float = 4.26
    add_node: float = 2.47
    insert_node: float = 0.0112
    delete_node: float = 0.870
    simplify: float = 0.00209
    randomize: float = 0.000502
    do_nothing: float = 0.273
    optimize: float = 0.0
    form_connection: float = 0.5
    break_connection: float = 0.1

    def as_vector(self) -> np.ndarray:
        return np.array([getattr(self, k) for k in MUTATION_KINDS], np.float64)

    @staticmethod
    def struct_defaults() -> "MutationWeights":
        """The struct-level defaults (src/MutationWeights.jl:103-118)."""
        return MutationWeights(
            mutate_constant=0.0353,
            mutate_operator=3.63,
            mutate_feature=0.1,
            swap_operands=0.00608,
            rotate_tree=1.42,
            add_node=0.0771,
            insert_node=2.44,
            delete_node=0.369,
            simplify=0.00148,
            randomize=0.00695,
            do_nothing=0.431,
            optimize=0.0,
            form_connection=0.5,
            break_connection=0.1,
        )


@dataclasses.dataclass
class ComplexityMapping:
    """Per-op / per-variable / per-constant complexity weights
    (src/OptionsStruct.jl:22-27). `use=False` => plain node count."""

    use: bool = False
    # op_complexities[arity] -> list of weights (1-based arity key)
    op_complexities: Dict[int, List[float]] = dataclasses.field(default_factory=dict)
    variable_complexity: Union[float, List[float]] = 1.0
    constant_complexity: float = 1.0


def _build_complexity_mapping(
    complexity_of_operators, complexity_of_constants, complexity_of_variables,
    operators: OperatorSet,
) -> ComplexityMapping:
    use = any(
        x is not None
        for x in (complexity_of_operators, complexity_of_constants, complexity_of_variables)
    )
    op_complexities = {
        d: [1.0] * len(ops) for d, ops in operators.ops.items()
    }
    if complexity_of_operators:
        for spec, w in dict(complexity_of_operators).items():
            found = False
            for d, ops in operators.ops.items():
                for i, op in enumerate(ops):
                    target_name = spec if isinstance(spec, str) else getattr(spec, "name", getattr(spec, "__name__", None))
                    if op.name == target_name or op.display == target_name:
                        op_complexities[d][i] = float(w)
                        found = True
            if not found:
                raise ValueError(f"complexity_of_operators key {spec!r} not in operator set")
    vc: Union[float, List[float]] = 1.0
    if complexity_of_variables is not None:
        if np.ndim(complexity_of_variables) > 0:
            vc = [float(v) for v in complexity_of_variables]
        else:
            vc = float(complexity_of_variables)
    cc = 1.0 if complexity_of_constants is None else float(complexity_of_constants)
    return ComplexityMapping(
        use=use, op_complexities=op_complexities, variable_complexity=vc,
        constant_complexity=cc,
    )


def _resolve_op_key(operators: OperatorSet, key) -> Tuple[int, int]:
    """Find (arity, index) for a constraint key (name or Op)."""
    name = key if isinstance(key, str) else getattr(key, "name", getattr(key, "__name__", None))
    from ..ops.operators import _ALIASES  # canonicalize "pow" -> "^" etc.

    name = _ALIASES.get(name, name)
    for d, ops in operators.ops.items():
        for i, op in enumerate(ops):
            if op.name == name or op.display == name:
                return d, i
    raise ValueError(f"Constraint key {key!r} not in operator set")


def _build_op_constraints(constraints, operators: OperatorSet) -> Dict[int, List[Tuple[int, ...]]]:
    """constraints: {op: int | tuple-per-arg}; -1 = unconstrained.

    Result: per arity, per op-index, a tuple of per-argument max subtree
    complexities (src/Options.jl:51-99).
    """
    out = {
        d: [tuple([-1] * d) for _ in ops] for d, ops in operators.ops.items()
    }
    if constraints:
        for key, val in dict(constraints).items():
            d, i = _resolve_op_key(operators, key)
            if isinstance(val, (int, float)):
                if d == 1:
                    out[d][i] = (int(val),)
                else:
                    raise ValueError(
                        f"Constraint for arity-{d} op {key!r} must be a tuple of {d} ints"
                    )
            else:
                tup = tuple(int(v) for v in val)
                if len(tup) != d:
                    raise ValueError(
                        f"Constraint tuple for {key!r} must have {d} entries, got {len(tup)}"
                    )
                out[d][i] = tup
    return out


def _build_nested_constraints(nested_constraints, operators: OperatorSet):
    """[(op, {inner_op: max_nestedness})] -> [(d,i,[(nd,ni,max)])]
    (src/Options.jl:101-180)."""
    if not nested_constraints:
        return []
    items = (
        nested_constraints.items()
        if isinstance(nested_constraints, dict)
        else nested_constraints
    )
    out = []
    for outer, inner_spec in items:
        d, i = _resolve_op_key(operators, outer)
        inner_items = (
            inner_spec.items() if isinstance(inner_spec, dict) else inner_spec
        )
        inners = []
        for inner, max_nest in inner_items:
            nd, ni = _resolve_op_key(operators, inner)
            inners.append((nd, ni, int(max_nest)))
        out.append((d, i, inners))
    return out


_V1_DEFAULTS = dict(  # default_options(v"0.24.5"), src/Options.jl:1112-1159
    maxsize=20, populations=15, population_size=33, ncycles_per_iteration=550,
    parsimony=0.0032, warmup_maxsize_by=0.0, adaptive_parsimony_scaling=20.0,
    crossover_probability=0.066, annealing=False, alpha=0.1,
    perturbation_factor=0.076, probability_negate_constant=0.01,
    tournament_selection_n=12, tournament_selection_p=0.86,
    fraction_replaced=0.00036, fraction_replaced_hof=0.035,
    fraction_replaced_guesses=0.001, topn=12, batching=False, batch_size=50,
    mutation_weights=dict(
        mutate_constant=0.048, mutate_operator=0.47, swap_operands=0.1,
        rotate_tree=0.0, add_node=0.79, insert_node=5.1, delete_node=1.7,
        simplify=0.0020, randomize=0.00023, do_nothing=0.21, optimize=0.0,
        form_connection=0.5, break_connection=0.1,
    ),
)

_V2_DEFAULTS = dict(  # default_options(), src/Options.jl:1161-1208
    maxsize=30, populations=31, population_size=27, ncycles_per_iteration=380,
    parsimony=0.0, warmup_maxsize_by=0.0, adaptive_parsimony_scaling=1040.0,
    crossover_probability=0.0259, annealing=True, alpha=3.17,
    perturbation_factor=0.129, probability_negate_constant=0.00743,
    tournament_selection_n=15, tournament_selection_p=0.982,
    fraction_replaced=0.00036, fraction_replaced_hof=0.0614,
    fraction_replaced_guesses=0.001, topn=12, batching=False, batch_size=50,
    mutation_weights=dict(
        mutate_constant=0.0346, mutate_operator=0.293, swap_operands=0.198,
        rotate_tree=4.26, add_node=2.47, insert_node=0.0112, delete_node=0.870,
        simplify=0.00209, randomize=0.000502, do_nothing=0.273, optimize=0.0,
        form_connection=0.5, break_connection=0.1,
    ),
)


class Options:
    """Search hyperparameters. Hashable by identity (static under jit)."""

    def __init__(
        self,
        *,
        defaults: Optional[str] = None,
        # 1. Search space
        binary_operators: Sequence = None,
        unary_operators: Sequence = None,
        operators: Optional[OperatorSet] = None,
        maxsize: Optional[int] = None,
        maxdepth: Optional[int] = None,
        expression_spec=None,
        # 2. Search size
        populations: Optional[int] = None,
        population_size: Optional[int] = None,
        ncycles_per_iteration: Optional[int] = None,
        # 3. Objective
        elementwise_loss: Union[str, Callable, None] = None,
        loss_function: Optional[Callable] = None,
        loss_function_expression: Optional[Callable] = None,
        loss_scale: str = "log",
        dimensional_constraint_penalty: Optional[float] = None,
        dimensionless_constants_only: bool = False,
        # 4. Complexity
        parsimony: Optional[float] = None,
        constraints=None,
        nested_constraints=None,
        complexity_of_operators=None,
        complexity_of_constants=None,
        complexity_of_variables=None,
        warmup_maxsize_by: Optional[float] = None,
        use_frequency: bool = True,
        use_frequency_in_tournament: bool = True,
        adaptive_parsimony_scaling: Optional[float] = None,
        should_simplify: Optional[bool] = None,
        # 5. Mutations
        mutation_weights: Union[MutationWeights, dict, None] = None,
        crossover_probability: Optional[float] = None,
        annealing: Optional[bool] = None,
        alpha: Optional[float] = None,
        perturbation_factor: Optional[float] = None,
        probability_negate_constant: Optional[float] = None,
        skip_mutation_failures: bool = True,
        # 6. Tournament
        tournament_selection_n: Optional[int] = None,
        tournament_selection_p: Optional[float] = None,
        # 7. Constant optimization
        optimizer_algorithm: str = "BFGS",
        optimizer_nrestarts: int = 2,
        optimizer_probability: float = 0.14,
        optimizer_iterations: Optional[int] = None,
        optimizer_f_calls_limit: Optional[int] = None,
        should_optimize_constants: bool = True,
        # bfloat16 line-search evals on the fused TPU path (step-size
        # selection only; accepted points re-verified at f32). Doubles
        # the variants-per-dispatch of the optimizer's dominant kernel,
        # but every step pays a bf16<->f32 relayout on v5e (bf16 (16,128)
        # vs f32 (8,128) tiling), which measured as a NET loss on the
        # bench — off by default; the f32 single-chunk line search
        # (fused_loss_multi's chunk planner) captures the dispatch
        # amortization without the conversions.
        optimizer_bf16_linesearch: bool = False,
        # 8. Migration
        migration: bool = True,
        hof_migration: bool = True,
        fraction_replaced: Optional[float] = None,
        fraction_replaced_hof: Optional[float] = None,
        fraction_replaced_guesses: Optional[float] = None,
        topn: Optional[int] = None,
        # 10. Stopping
        early_stop_condition: Union[float, Callable, None] = None,
        timeout_in_seconds: Optional[float] = None,
        max_evals: Optional[int] = None,
        # 11. Performance
        batching: Optional[bool] = None,
        batch_size: Optional[int] = None,
        turbo: Optional[bool] = None,  # None = auto: fused Pallas kernel on TPU
        # Candidate-eval kernel launch geometry (the fused Pallas path):
        # trees per kernel block / row-tile cap. None = kernel defaults
        # (8 / 16384). The per-island tree_block knob from the round-6
        # cycle attribution (profiling/cycle_attrib.py).
        eval_tree_block: Optional[int] = None,
        eval_tile_rows: Optional[int] = None,
        # Fuse the loss->cost epilogue (mean, validity->inf, baseline
        # normalization, parsimony penalty) into the candidate-eval
        # kernel's final grid step. None = auto: on whenever turbo is
        # on; False keeps the materializing post-kernel arithmetic
        # (A/B profiling — profiling/cycle_attrib.py).
        fuse_cost_epilogue: Optional[bool] = None,
        # graftstage (docs/PRECISION.md): the two engine modes that trade
        # exactness for throughput, both default OFF — the f32/full path
        # is bit-identical with them off.
        # `eval_precision`: "f32" (exact) or "bf16" (candidate evals run
        # the kernel's bfloat16 row tiles with an f32 reduction spine for
        # the loss/cost epilogue; quality-gated, not bit-exact).
        eval_precision: str = "f32",
        # Staged sample-then-rescore candidate evaluation: screen every
        # candidate on a deterministic strided row sample, then re-score
        # only the top `rescore_fraction` on the full dataset; candidates
        # outside the rescore set are rejected (parents kept), so
        # acceptance, HoF updates, and finalize consume only
        # fully-rescored costs. `staged_sample_rows` pins the sample
        # size; None derives it as `staged_sample_fraction` of the
        # dataset (floored at 64 rows, capped by eval_tile_rows — the
        # shield degrade ladder keeps that cap as it steps tiles down).
        staged_eval: bool = False,
        staged_sample_rows: Optional[int] = None,
        staged_sample_fraction: float = 0.125,
        rescore_fraction: float = 0.25,
        bumper: bool = False,  # accepted for API parity (no allocator to tune)
        autodiff_backend=None,  # ignored: gradients always via jax.grad
        # 12. Determinism
        deterministic: bool = False,
        seed: Optional[int] = None,
        # 13. Monitoring
        verbosity: Optional[int] = None,
        print_precision: int = 5,
        progress: Optional[bool] = None,
        # graftscope telemetry (telemetry/ package, docs/OBSERVABILITY.md):
        # device-side counters ride the evolve scan carry (0 extra
        # dispatches/transfers/retraces in the hot loop) and the host hub
        # emits schema-versioned JSONL (`graftscope.v1`) merging them
        # with timings and jax.monitoring compile events. `telemetry`
        # turns the JSONL stream on; the counters themselves are
        # collected whenever it is set. `telemetry_file` is relative to
        # the run's output directory unless absolute;
        # `telemetry_interval` emits one `iteration` event per N
        # iterations (counters summed across the interval).
        telemetry: bool = False,
        telemetry_file: str = "telemetry.jsonl",
        telemetry_interval: int = 1,
        # Interactive 'q'-to-quit stdin watcher: engaged only when this
        # is True AND sys.stdin is a real TTY (or an explicit
        # RuntimeOptions.input_stream is injected). Headless/server
        # deployments (graftserve) set False so a long-lived process
        # never spawns a stdin-reading thread or flips terminal modes
        # per request (docs/SERVING.md).
        interactive_quit: bool = True,
        # graftshield fault tolerance (shield/ package, docs/ROBUSTNESS.md):
        # `shield` arms the whole supervision layer in equation_search —
        # SIGTERM/SIGINT → graceful stop + emergency checkpoint at the
        # next iteration boundary, transient-failure retries, and (when
        # island_quarantine is on) NaN-storm island reseeding. The
        # watchdog deadlines are opt-in per budget: `iteration_deadline`
        # bounds a warm device iteration, `compile_budget` bounds
        # compile-bearing dispatches (first use of a program); on expiry
        # the watchdog aborts with a thread-stack diagnostic dump
        # instead of hanging until an external timeout (rc=124).
        shield: bool = True,
        iteration_deadline: Optional[float] = None,
        compile_budget: Optional[float] = None,
        # Rolling checkpoint depth: search_state.pkl plus the previous
        # (checkpoint_keep - 1) generations, digest-verified; resume
        # falls back to the newest valid one on corruption.
        checkpoint_keep: int = 3,
        # Transient-failure policy: bounded exponential backoff
        # (retry_backoff * 2^k seconds, capped at 30) for max_retries
        # attempts, then eval-tile-rows degradation on OOM-shaped
        # failures, then raise.
        max_retries: int = 3,
        retry_backoff: float = 0.5,
        # Island quarantine: islands whose non-finite member fraction
        # reaches quarantine_invalid_fraction are reseeded from the hall
        # of fame in-graph. The 1.0 default only fires on a FULLY
        # collapsed island, so healthy searches are bit-identical with
        # the feature on or off until a genuine NaN storm hits.
        island_quarantine: bool = True,
        quarantine_invalid_fraction: float = 1.0,
        # Run the graftlint runtime auditor (lint/runtime.py
        # validate_programs) over every engine state: postfix-encoding
        # invariants are re-checked after init and after each iteration's
        # mutation/crossover/migration output. Debug tier — each check
        # pulls the population tables to host.
        debug_checks: bool = False,
        # 15. Export
        output_directory: Optional[str] = None,
        save_to_file: bool = True,
        use_recorder: bool = False,
        recorder_file: str = "recorder.json",
        # 1: accepted events + per-kind aggregate rejection counts;
        # >=2: every rejected candidate becomes its own event with its
        # reason (constraint / invalid / annealing), matching the
        # reference's per-mutation tmp_recorder detail
        # (src/RegularizedEvolution.jl:47-75, src/Mutate.jl:270-355).
        recorder_verbosity: int = 1,
        # TPU-specific extensions:
        eval_dtype: str = "float32",
        mutation_attempts: int = 5,  # speculative batch width (reference's
        # sequential retry cap is 10, src/Mutate.jl:201; expected successes
        # land in the first few, and each attempt costs real TPU time)
    ):
        d = _V2_DEFAULTS
        if defaults is not None:
            ver = tuple(int(p) for p in str(defaults).split(".")[:1])
            if ver and ver[0] < 1:
                d = _V1_DEFAULTS

        if operators is None:
            operators = OperatorSet(
                binary_operators=(
                    DEFAULT_BINARY if binary_operators is None else binary_operators
                ),
                unary_operators=(
                    DEFAULT_UNARY if unary_operators is None else unary_operators
                ),
            )
        self.operators = operators
        self.maxsize = int(maxsize if maxsize is not None else d["maxsize"])
        self.maxdepth = int(maxdepth if maxdepth is not None else self.maxsize)
        self.expression_spec = expression_spec
        self.populations = int(populations if populations is not None else d["populations"])
        self.population_size = int(
            population_size if population_size is not None else d["population_size"]
        )
        self.ncycles_per_iteration = int(
            ncycles_per_iteration
            if ncycles_per_iteration is not None
            else d["ncycles_per_iteration"]
        )
        from .losses import resolve_loss

        if sum(x is not None for x in (elementwise_loss, loss_function, loss_function_expression)) > 1:
            raise ValueError(
                "Specify at most one of elementwise_loss / loss_function / "
                "loss_function_expression"
            )
        self.elementwise_loss = resolve_loss(elementwise_loss)
        self.loss_function = loss_function
        self.loss_function_expression = loss_function_expression
        if loss_scale not in ("log", "linear"):
            raise ValueError("`loss_scale` must be 'log' or 'linear'")
        self.loss_scale = loss_scale
        self.dimensional_constraint_penalty = dimensional_constraint_penalty
        self.dimensionless_constants_only = bool(dimensionless_constants_only)

        self.parsimony = float(parsimony if parsimony is not None else d["parsimony"])
        self.constraints = constraints
        self.op_constraints = _build_op_constraints(constraints, operators)
        self.nested_constraints = _build_nested_constraints(nested_constraints, operators)
        self.complexity_mapping = _build_complexity_mapping(
            complexity_of_operators, complexity_of_constants, complexity_of_variables,
            operators,
        )
        self.warmup_maxsize_by = float(
            warmup_maxsize_by if warmup_maxsize_by is not None else d["warmup_maxsize_by"]
        )
        self.use_frequency = bool(use_frequency)
        self.use_frequency_in_tournament = bool(use_frequency_in_tournament)
        self.adaptive_parsimony_scaling = float(
            adaptive_parsimony_scaling
            if adaptive_parsimony_scaling is not None
            else d["adaptive_parsimony_scaling"]
        )
        if should_simplify is None:
            # src/Options.jl:813-821
            should_simplify = (
                loss_function is None
                and nested_constraints is None
                and constraints is None
            )
        self.should_simplify = bool(should_simplify)

        if mutation_weights is None:
            mutation_weights = MutationWeights(**d["mutation_weights"])
        elif isinstance(mutation_weights, dict):
            mutation_weights = MutationWeights(**mutation_weights)
        self.mutation_weights = mutation_weights
        self.crossover_probability = float(
            crossover_probability
            if crossover_probability is not None
            else d["crossover_probability"]
        )
        self.annealing = bool(annealing if annealing is not None else d["annealing"])
        self.alpha = float(alpha if alpha is not None else d["alpha"])
        self.perturbation_factor = float(
            perturbation_factor
            if perturbation_factor is not None
            else d["perturbation_factor"]
        )
        self.probability_negate_constant = float(
            probability_negate_constant
            if probability_negate_constant is not None
            else d["probability_negate_constant"]
        )
        self.skip_mutation_failures = bool(skip_mutation_failures)

        self.tournament_selection_n = int(
            tournament_selection_n
            if tournament_selection_n is not None
            else d["tournament_selection_n"]
        )
        self.tournament_selection_p = float(
            tournament_selection_p
            if tournament_selection_p is not None
            else d["tournament_selection_p"]
        )

        self.optimizer_algorithm = optimizer_algorithm
        self.optimizer_nrestarts = int(optimizer_nrestarts)
        self.optimizer_bf16_linesearch = bool(optimizer_bf16_linesearch)
        self.optimizer_probability = float(optimizer_probability)
        self.optimizer_iterations = int(
            optimizer_iterations if optimizer_iterations is not None else 8
        )
        self.optimizer_f_calls_limit = int(
            optimizer_f_calls_limit if optimizer_f_calls_limit is not None else 10_000
        )
        self.should_optimize_constants = bool(should_optimize_constants)

        self.migration = bool(migration)
        self.hof_migration = bool(hof_migration)
        self.fraction_replaced = float(
            fraction_replaced if fraction_replaced is not None else d["fraction_replaced"]
        )
        self.fraction_replaced_hof = float(
            fraction_replaced_hof
            if fraction_replaced_hof is not None
            else d["fraction_replaced_hof"]
        )
        self.fraction_replaced_guesses = float(
            fraction_replaced_guesses
            if fraction_replaced_guesses is not None
            else d["fraction_replaced_guesses"]
        )
        self.topn = int(topn if topn is not None else d["topn"])

        if isinstance(early_stop_condition, (int, float)):
            threshold = float(early_stop_condition)
            early_stop_condition = lambda loss, complexity: loss < threshold  # noqa: E731
        self.early_stop_condition = early_stop_condition
        self.timeout_in_seconds = timeout_in_seconds
        self.max_evals = max_evals

        self.batching = bool(batching if batching is not None else d["batching"])
        self.batch_size = int(batch_size if batch_size is not None else d["batch_size"])
        self.turbo = turbo  # tri-state: None=auto / True / False
        self.eval_tree_block = (
            None if eval_tree_block is None else int(eval_tree_block)
        )
        self.eval_tile_rows = (
            None if eval_tile_rows is None else int(eval_tile_rows)
        )
        self.fuse_cost_epilogue = fuse_cost_epilogue  # tri-state
        self.eval_precision = str(eval_precision)
        self.staged_eval = bool(staged_eval)
        self.staged_sample_rows = (
            None if staged_sample_rows is None else int(staged_sample_rows)
        )
        self.staged_sample_fraction = float(staged_sample_fraction)
        self.rescore_fraction = float(rescore_fraction)
        self.bumper = bool(bumper)
        self.autodiff_backend = autodiff_backend

        self.deterministic = bool(deterministic)
        self.seed = seed
        self.verbosity = verbosity
        self.telemetry = bool(telemetry)
        self.telemetry_file = str(telemetry_file)
        self.telemetry_interval = int(telemetry_interval)
        self.interactive_quit = bool(interactive_quit)
        self.shield = bool(shield)
        self.iteration_deadline = (
            None if iteration_deadline is None else float(iteration_deadline)
        )
        self.compile_budget = (
            None if compile_budget is None else float(compile_budget)
        )
        self.checkpoint_keep = int(checkpoint_keep)
        self.max_retries = int(max_retries)
        self.retry_backoff = float(retry_backoff)
        self.island_quarantine = bool(island_quarantine)
        self.quarantine_invalid_fraction = float(quarantine_invalid_fraction)
        self.debug_checks = bool(debug_checks)
        self.print_precision = int(print_precision)
        self.progress = progress
        self.output_directory = output_directory
        self.save_to_file = bool(save_to_file)
        self.use_recorder = bool(use_recorder)
        self.recorder_file = recorder_file
        self.recorder_verbosity = int(recorder_verbosity)

        self.eval_dtype = eval_dtype
        self.mutation_attempts = int(mutation_attempts)

        # Validation (src/Options.jl:823-826)
        if self.maxsize <= 3:
            raise ValueError("maxsize must be > 3")
        if self.warmup_maxsize_by < 0:
            raise ValueError("warmup_maxsize_by must be >= 0")
        if self.tournament_selection_n >= self.population_size:
            raise ValueError(
                "tournament_selection_n must be less than population_size"
            )
        if self.eval_tree_block is not None and self.eval_tree_block <= 0:
            raise ValueError("eval_tree_block must be positive")
        if self.eval_tile_rows is not None and self.eval_tile_rows <= 0:
            raise ValueError("eval_tile_rows must be positive")
        if self.eval_precision not in ("f32", "bf16"):
            raise ValueError('eval_precision must be "f32" or "bf16"')
        if (self.staged_sample_rows is not None
                and self.staged_sample_rows <= 0):
            raise ValueError("staged_sample_rows must be positive (or None)")
        if not (0.0 < self.staged_sample_fraction <= 1.0):
            raise ValueError("staged_sample_fraction must be in (0, 1]")
        if not (0.0 < self.rescore_fraction <= 1.0):
            raise ValueError("rescore_fraction must be in (0, 1]")
        if self.telemetry_interval < 1:
            raise ValueError("telemetry_interval must be >= 1")
        if self.checkpoint_keep < 1:
            raise ValueError("checkpoint_keep must be >= 1")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.retry_backoff < 0:
            raise ValueError("retry_backoff must be >= 0")
        if not (0.0 < self.quarantine_invalid_fraction <= 1.0):
            raise ValueError(
                "quarantine_invalid_fraction must be in (0, 1]"
            )
        for name in ("iteration_deadline", "compile_budget"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be positive (or None)")

    def eval_geometry(self) -> EvalGeometry:
        """Candidate-eval kernel launch geometry with the kernel defaults
        resolved — the one fallback chain for `eval_tree_block` /
        `eval_tile_rows` (see :class:`EvalGeometry`)."""
        return EvalGeometry(
            tree_block=(self.eval_tree_block
                        if self.eval_tree_block else KERNEL_TREE_BLOCK),
            tile_rows=(self.eval_tile_rows
                       if self.eval_tile_rows else KERNEL_TILE_ROWS),
        )

    @property
    def nops(self):
        return self.operators.nops

    @property
    def resolved_loss_function(self):
        """The custom whole-prediction loss hook, if any (loss_function
        takes precedence over loss_function_expression, matching the
        reference's dispatch order, src/LossFunctions.jl:139-159)."""
        return self.loss_function or self.loss_function_expression

    # Warm-start option compatibility (check_warm_start_compatibility,
    # /root/reference/src/OptionsStruct.jl:314-336).
    _WARM_START_FIELDS = (
        "maxsize", "maxdepth", "loss_scale", "parsimony",
        "dimensional_constraint_penalty", "batching", "batch_size",
        "population_size", "populations", "expression_spec",
    )

    def check_warm_start_compatibility(self, other: "Options") -> List[str]:
        issues = []
        if self.operators != other.operators:
            issues.append("operators")
        for f in self._WARM_START_FIELDS:
            if getattr(self, f) != getattr(other, f):
                issues.append(f)
        return issues

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Options(maxsize={self.maxsize}, populations={self.populations}, "
            f"population_size={self.population_size}, "
            f"ncycles_per_iteration={self.ncycles_per_iteration}, "
            f"operators={self.operators})"
        )
