"""Losses and cost computation (TPU analogue of src/LossFunctions.jl).

`elementwise_loss` takes ``(prediction, target)`` (or ``(prediction,
target, weight)`` for user functions that consume weights directly) and
returns elementwise values. The framework aggregates:
unweighted = mean; weighted = sum(loss * w) / sum(w)
(/root/reference/src/LossFunctions.jl:38-58). Invalid evaluation =>
``inf`` loss (:96-99). `loss_to_cost` adds baseline normalization and the
parsimony complexity penalty (:170-190).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax.numpy as jnp

__all__ = ["resolve_loss", "aggregate_loss", "loss_to_cost", "LOSS_REGISTRY"]


def l2_dist_loss(pred, target):
    d = pred - target
    return d * d


def l1_dist_loss(pred, target):
    return jnp.abs(pred - target)


def huber_loss(delta: float = 1.0):
    def f(pred, target):
        a = jnp.abs(pred - target)
        return jnp.where(a <= delta, 0.5 * a * a, delta * (a - 0.5 * delta))

    return f


def log_cosh_loss(pred, target):
    return jnp.logaddexp(pred - target, target - pred) - jnp.log(2.0).astype(pred.dtype)


def logit_dist_loss(pred, target):
    # LossFunctions.jl LogitDistLoss(d) = -log(4 e^d / (1+e^d)^2) = 2 log(cosh(d/2))
    d = pred - target
    return 2.0 * (jnp.logaddexp(d / 2, -d / 2) - jnp.log(2.0).astype(d.dtype))


def sigmoid_cross_entropy_loss(pred, target):
    # target in {0,1}; pred is a logit
    return jnp.maximum(pred, 0) - pred * target + jnp.log1p(jnp.exp(-jnp.abs(pred)))


def periodic_l2_loss(c: float = 2 * 3.141592653589793):
    def f(pred, target):
        d = jnp.mod(pred - target + c / 2, c) - c / 2
        return d * d

    return f


LOSS_REGISTRY = {
    # LossFunctions.jl-compatible names (the reference's default is
    # L2DistLoss(), src/Options.jl:772):
    "L2DistLoss": l2_dist_loss,
    "L1DistLoss": l1_dist_loss,
    "LogitDistLoss": logit_dist_loss,
    "HuberLoss": huber_loss(1.0),
    # Friendly names:
    "mse": l2_dist_loss,
    "l2": l2_dist_loss,
    "mae": l1_dist_loss,
    "l1": l1_dist_loss,
    "huber": huber_loss(1.0),
    "logcosh": log_cosh_loss,
}


def resolve_loss(spec: Union[str, Callable, None]) -> Callable:
    if spec is None:
        return l2_dist_loss
    if callable(spec):
        return spec
    name = str(spec).replace("()", "")
    if name in LOSS_REGISTRY:
        return LOSS_REGISTRY[name]
    raise ValueError(f"Unknown loss {spec!r}; pass a callable (pred, target) -> elementwise loss")


def aggregate_loss(
    elementwise: Callable,
    pred: jnp.ndarray,  # [..., n]
    target: jnp.ndarray,  # [n]
    valid,  # bool [...]
    weights: Optional[jnp.ndarray] = None,  # [n]
    row_mask: Optional[jnp.ndarray] = None,  # bool [n] (for padded/batched rows)
):
    """Mean (or weighted-mean) loss with invalid -> inf.

    ``row_mask`` allows evaluating on a masked subset of rows (used by
    minibatching where batches are gathered index subsets).
    """
    vals = elementwise(pred, target)
    # Guard against NaN*0: zero out masked rows explicitly.
    if weights is None and row_mask is None:
        loss = jnp.mean(vals, axis=-1)
    else:
        w = jnp.ones_like(target) if weights is None else weights
        if row_mask is not None:
            w = w * row_mask.astype(w.dtype)
        vals = jnp.where(jnp.isfinite(vals), vals, jnp.inf)
        vals = jnp.where(w > 0, vals, 0.0)
        loss = jnp.sum(vals * w, axis=-1) / jnp.sum(w)
    inf = jnp.array(jnp.inf, dtype=loss.dtype)
    loss = jnp.where(valid, loss, inf)
    # NaN losses are treated as rejections downstream (src/Mutate.jl:273);
    # normalize them to inf so cost ordering is well-defined.
    return jnp.where(jnp.isnan(loss), inf, loss)


def baseline_normalization(baseline_loss, use_baseline, dtype):
    """max(baseline, 0.01) with the 0.01 floor when the baseline is
    unusable (/root/reference/src/LossFunctions.jl:170-190). Shared by
    `loss_to_cost` and the fused kernel's in-kernel cost epilogue
    (ops.fused_eval.fused_cost_program) so the two paths cannot drift."""
    return jnp.where(
        use_baseline & (baseline_loss >= 0.01), baseline_loss,
        jnp.asarray(0.01, dtype=dtype)
    )


def loss_to_cost(
    loss,
    baseline_loss,
    use_baseline,
    complexity,
    parsimony: float,
):
    """cost = loss / max(baseline, 0.01) + parsimony * complexity.

    Mirrors /root/reference/src/LossFunctions.jl:170-190 (normalization
    floor of 0.01 when the baseline is unusable).
    """
    normalization = baseline_normalization(baseline_loss, use_baseline,
                                           loss.dtype)
    return loss / normalization + parsimony * complexity.astype(loss.dtype)
