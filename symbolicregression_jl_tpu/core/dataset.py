"""Device-resident datasets (TPU analogue of src/Dataset.jl).

The full dataset lives in HBM for the whole search; minibatching
(`SubDataset`, /root/reference/src/Dataset.jl:90-115) becomes gathered
index subsets produced inside the jitted generation step, so the eval
kernel always sees static shapes.

Public layout is sklearn-style ``X: (n, nfeatures)``; internally we store
the transpose ``Xt: (nfeatures, n)`` so the interpreter's feature lookup is
a contiguous row gather.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Dataset", "make_dataset"]


def _subscriptify(i: int) -> str:
    subs = "₀₁₂₃₄₅₆₇₈₉"
    return "".join(subs[int(c)] for c in str(i))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceData:
    """The pytree part of a Dataset (device arrays)."""

    Xt: jax.Array  # [nfeatures, n]
    y: Optional[jax.Array]  # [n]
    weights: Optional[jax.Array]  # [n] or None
    class_idx: Optional[jax.Array]  # [n] int32 or None (parametric expressions)
    baseline_loss: jax.Array  # scalar
    use_baseline: jax.Array  # bool scalar
    # Dimensional analysis (None when the dataset has no units): SI
    # exponent vectors consumed by ops.dims_eval.
    x_dims: Optional[jax.Array] = None  # [nfeatures, 7] float32
    y_dims: Optional[jax.Array] = None  # [7] float32


@dataclasses.dataclass
class Dataset:
    """Host wrapper: device data + metadata.

    Mirrors `BasicDataset` fields (/root/reference/src/Dataset.jl:53-82):
    variable names, units, average y, baseline loss. ``extra`` carries
    additional columns (e.g. ``class`` for ParametricExpression).
    """

    data: DeviceData
    n: int
    nfeatures: int
    index: int = 1
    avg_y: Optional[float] = None
    variable_names: Sequence[str] = ()
    display_variable_names: Sequence[str] = ()
    y_variable_name: str = "y"
    X_units: Optional[Sequence[str]] = None
    y_units: Optional[str] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def X(self):
        return self.data.Xt.T

    @property
    def y(self):
        return self.data.y

    @property
    def weights(self):
        return self.data.weights

    @property
    def is_weighted(self) -> bool:
        return self.data.weights is not None

    @property
    def has_units(self) -> bool:
        return self.X_units is not None or self.y_units is not None

    @property
    def n_classes(self) -> int:
        if self.data.class_idx is None:
            return 0
        return int(np.asarray(self.data.class_idx).max()) + 1

    def update_baseline_loss(self, elementwise_loss) -> None:
        """Evaluate the constant (avg-y) predictor to set the baseline
        (update_baseline_loss!, /root/reference/src/LossFunctions.jl:219-234)."""
        from .losses import aggregate_loss

        if self.data.y is None or self.avg_y is None:
            return
        pred = jnp.full_like(self.data.y, jnp.asarray(self.avg_y, self.data.y.dtype))
        loss = aggregate_loss(
            elementwise_loss, pred, self.data.y, jnp.bool_(True), self.data.weights
        )
        loss_f = float(loss)
        if np.isfinite(loss_f):
            self.data = dataclasses.replace(
                self.data,
                baseline_loss=jnp.asarray(loss_f, self.data.baseline_loss.dtype),
                use_baseline=jnp.bool_(True),
            )
        else:
            self.data = dataclasses.replace(
                self.data,
                baseline_loss=jnp.ones_like(self.data.baseline_loss),
                use_baseline=jnp.bool_(False),
            )


def make_dataset(
    X,
    y=None,
    *,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    display_variable_names: Optional[Sequence[str]] = None,
    y_variable_name: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
    X_units=None,
    y_units=None,
    index: int = 1,
    dtype=None,
) -> Dataset:
    """Construct a Dataset from ``X: (n, nfeatures)`` and ``y: (n,)``.

    (Note the transposed convention vs the reference's ``(nfeatures, n)`` —
    this follows sklearn/PySR's user-facing layout.)
    """
    X = np.asarray(X)
    if X.ndim != 2:
        raise ValueError(f"X must be 2D (n, nfeatures); got shape {X.shape}")
    if dtype is None:
        dtype = X.dtype if X.dtype in (np.float32, np.float64) else np.float32
    if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
        dtype = np.float32  # avoid jnp's silent-truncation warning per array
    n, nfeatures = X.shape
    y_arr = None if y is None else np.asarray(y, dtype).reshape(-1)
    if y_arr is not None and y_arr.shape[0] != n:
        raise ValueError(f"y has {y_arr.shape[0]} rows but X has {n}")
    w_arr = None if weights is None else np.asarray(weights, dtype).reshape(-1)
    if w_arr is not None and w_arr.shape[0] != n:
        raise ValueError(f"weights has {w_arr.shape[0]} rows but X has {n}")
    extra = dict(extra or {})
    class_idx = None
    if "class" in extra or "classes" in extra:
        cls = np.asarray(extra.get("class", extra.get("classes"))).reshape(-1)
        uniq = np.unique(cls)
        class_idx = jnp.asarray(np.searchsorted(uniq, cls).astype(np.int32))
        extra["class"] = cls

    variable_names = list(
        variable_names or [f"x{i + 1}" for i in range(nfeatures)]
    )
    display_variable_names = list(
        display_variable_names
        or (
            variable_names
            if variable_names != [f"x{i + 1}" for i in range(nfeatures)]
            else [f"x{_subscriptify(i + 1)}" for i in range(nfeatures)]
        )
    )
    if X_units is not None and display_variable_names is not None:
        # Unit-annotated printing (the reference annotates variables with
        # their units when printing trees,
        # /root/reference/src/InterfaceDynamicExpressions.jl:199-317).
        # Only plain string specs annotate; exponent-vector/Quantity forms
        # have no compact display syntax.
        display_variable_names = [
            f"{name}[{u}]" if isinstance(u, str) and u not in ("", "1") else name
            for name, u in zip(display_variable_names, X_units)
        ]
    if y_variable_name is None:
        y_variable_name = "y" if "y" not in variable_names else "target"

    avg_y = None
    if y_arr is not None:
        if w_arr is not None:
            avg_y = float(np.sum(y_arr * w_arr) / np.sum(w_arr))
        else:
            avg_y = float(np.mean(y_arr))

    from .units import units_to_dims_arrays

    x_dims_np, y_dims_np = units_to_dims_arrays(X_units, nfeatures, y_units)
    data = DeviceData(
        Xt=jnp.asarray(X.T.astype(dtype)),
        y=None if y_arr is None else jnp.asarray(y_arr),
        weights=None if w_arr is None else jnp.asarray(w_arr),
        class_idx=class_idx,
        baseline_loss=jnp.asarray(1.0, dtype),
        use_baseline=jnp.bool_(True),
        x_dims=None if x_dims_np is None else jnp.asarray(x_dims_np),
        y_dims=None if y_dims_np is None else jnp.asarray(y_dims_np),
    )
    return Dataset(
        data=data,
        n=n,
        nfeatures=nfeatures,
        index=index,
        avg_y=avg_y,
        variable_names=variable_names,
        display_variable_names=display_variable_names,
        y_variable_name=y_variable_name,
        X_units=list(X_units) if X_units is not None else None,
        y_units=y_units,
        extra=extra,
    )
