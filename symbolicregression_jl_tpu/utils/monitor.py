"""Resource monitor: host-overhead fraction of the search loop.

TPU analogue of the reference's ResourceMonitor
(/root/reference/src/SearchUtils.jl:411-438): the reference estimates
head-node occupancy from the fraction of worker polls that found results
waiting; in the synchronous SPMD design the analogous quantity is the
fraction of wall time the host spends *outside* the device iteration
(HoF decode, CSV/checkpoint writes, logging). A high fraction means the
host bookkeeping — not the TPU — is pacing the search, mirroring the
reference's "head node occupied" warning (:485-489).
"""

from __future__ import annotations

import sys
import threading
import traceback
from collections import deque
from typing import Deque, Tuple

__all__ = ["ResourceMonitor", "thread_dump"]


def thread_dump() -> str:
    """Python stacks of every live thread, main thread first — the
    graftshield watchdog's diagnostic payload (shield/watchdog.py). A
    dispatch hung inside the XLA runtime shows up as the main thread
    blocked in ``block_until_ready`` (or a specific jitted call), which
    is exactly the attribution an external ``timeout`` kill loses."""
    names = {t.ident: t.name for t in threading.enumerate()}
    main_id = threading.main_thread().ident
    frames = sys._current_frames()
    order = sorted(frames, key=lambda tid: (tid != main_id, tid))
    chunks = []
    for tid in order:
        name = names.get(tid, "?")
        tag = " (main)" if tid == main_id else ""
        stack = "".join(traceback.format_stack(frames[tid]))
        chunks.append(f"--- thread {name}{tag} [{tid}] ---\n{stack}")
    return "".join(chunks)


class ResourceMonitor:
    """Sliding-window tracker of device vs host time per iteration."""

    def __init__(self, window: int = 20, warn_fraction: float = 0.2):
        self.samples: Deque[Tuple[float, float]] = deque(maxlen=window)
        self.warn_fraction = warn_fraction
        self._warned = False

    def record(self, device_seconds: float, host_seconds: float) -> None:
        self.samples.append((float(device_seconds), float(host_seconds)))

    def estimate_work_fraction(self) -> float:
        """Fraction of loop time spent on host bookkeeping
        (estimate_work_fraction, src/SearchUtils.jl:432-438)."""
        dev = sum(d for d, _ in self.samples)
        host = sum(h for _, h in self.samples)
        total = dev + host
        return host / total if total > 0 else 0.0

    def check_and_warn(self, verbosity: int = 1) -> bool:
        """Warn when host overhead paces the search (the reference warns
        at 10s head occupancy estimates >= ~0.X).

        The warning is edge-triggered, not one-shot: it re-arms when the
        fraction drops back below the threshold (with a recovery note),
        so a host-overhead regression AFTER a recovery is not silent —
        the old latch never reset and swallowed every later excursion.
        """
        if len(self.samples) < self.samples.maxlen:
            return False
        frac = self.estimate_work_fraction()
        if frac > self.warn_fraction:
            if self._warned:
                return False
            self._warned = True
            if verbosity >= 1:
                print(
                    f"Warning: host bookkeeping is {frac:.0%} of loop time "
                    "— consider raising checkpoint_every_n / log_every_n or "
                    "reducing verbosity."
                )
            return True
        if self._warned:
            self._warned = False  # re-arm for the next excursion
            if verbosity >= 1:
                print(
                    f"Host bookkeeping recovered to {frac:.0%} of loop time."
                )
        return False
