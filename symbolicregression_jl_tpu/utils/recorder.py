"""Search genealogy recorder.

The Recorder analogue (/root/reference/src/Recorder.jl +
ext/SymbolicRegressionJSON3Ext.jl): when ``options.use_recorder`` is set,
the search accumulates a JSON-serializable record of the run and writes it
to ``options.recorder_file`` at teardown
(src/SymbolicRegression.jl:1231).

Granularity note: the reference logs every mutation/death event from its
sequential per-member loop (src/RegularizedEvolution.jl:47-149). Here the
whole generation runs inside one XLA program, so per-event host logging
would serialize the device; instead the recorder snapshots the lineage
state (ref/parent ids, costs, losses, complexities) of every island member
once per iteration — the ref/parent chains reconstruct the same genealogy
DAG — plus the full hall of fame with equation strings.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ops.tree import string_tree

__all__ = ["Recorder"]


def _sanitize(v):
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return str(v)
    return v


class Recorder:
    """Accumulates RecordType-style nested dicts (src/ProgramConstants.jl)."""

    def __init__(self, options) -> None:
        self.record: Dict[str, Any] = {
            "options": repr(options),
            "iterations": [],
            "final_state": {},
        }

    def record_iteration(
        self,
        iteration: int,
        out_idx: int,
        state,
        hof,
        num_evals: float,
        variable_names: Optional[Sequence[str]] = None,
    ) -> None:
        pops = state.pops
        ref = np.asarray(pops.ref)
        parent = np.asarray(pops.parent)
        cost = np.asarray(pops.cost, np.float64)
        loss = np.asarray(pops.loss, np.float64)
        cx = np.asarray(pops.complexity)
        birth = np.asarray(pops.birth)
        islands: List[Dict[str, Any]] = []
        for i in range(ref.shape[0]):
            islands.append(
                {
                    "ref": ref[i].tolist(),
                    "parent": parent[i].tolist(),
                    "cost": [_sanitize(float(c)) for c in cost[i]],
                    "loss": [_sanitize(float(c)) for c in loss[i]],
                    "complexity": cx[i].tolist(),
                    "birth": birth[i].tolist(),
                }
            )
        self.record["iterations"].append(
            {
                "iteration": iteration,
                "out": out_idx + 1,
                "num_evals": float(num_evals),
                "islands": islands,
                "hall_of_fame": [
                    {
                        "complexity": int(e.complexity),
                        "loss": _sanitize(float(e.loss)),
                        "equation": e.equation_string(
                            variable_names=variable_names
                        ),
                    }
                    for e in hof.entries
                ],
            }
        )

    def record_final(self, key: str, value: Any) -> None:
        self.record["final_state"][key] = value

    def write(self, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.record, f)
