"""Search genealogy recorder.

The Recorder analogue (/root/reference/src/Recorder.jl +
ext/SymbolicRegressionJSON3Ext.jl): when ``options.use_recorder`` is set,
the search accumulates a JSON-serializable record of the run and writes it
to ``options.recorder_file`` at teardown
(src/SymbolicRegression.jl:1231).

Granularity: the reference logs every mutation/death event from its
sequential per-member loop (src/RegularizedEvolution.jl:47-149). Here the
whole generation runs inside one XLA program; per-event host callbacks
would serialize the device, so the generation step instead emits
`CycleEvents` — int32/f32 side arrays (kind, parent/child/died refs,
accept flag, cost delta) per candidate baby per cycle — and the host
recorder assembles them into the reference-style event stream
("mutation"/"crossover" with parents, child, the member that died, and
the accept decision), alongside the per-iteration lineage snapshots and
the hall of fame with equation strings.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ops.tree import string_tree

__all__ = ["Recorder"]


def _sanitize(v):
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return str(v)
    return v


class Recorder:
    """Accumulates RecordType-style nested dicts (src/ProgramConstants.jl).

    ``stream_path``: when given AND ``recorder_verbosity >= 2`` (the
    per-event rejection mode, whose host dicts dominate memory — see
    ``_assemble_events``), each iteration's record is spilled to that
    path as one JSONL line the moment it is assembled, instead of
    holding every iteration in memory until teardown; ``write()`` merges
    the spilled stream back so the on-disk JSON layout is identical to
    the in-memory path, and removes the stream file.
    """

    def __init__(self, options, stream_path: Optional[str] = None) -> None:
        self.verbosity = int(getattr(options, "recorder_verbosity", 1))
        self._stream_path = stream_path if self.verbosity >= 2 else None
        if self._stream_path is not None:
            d = os.path.dirname(self._stream_path)
            if d:
                os.makedirs(d, exist_ok=True)
            open(self._stream_path, "w").close()  # truncate stale stream
        self.record: Dict[str, Any] = {
            "options": repr(options),
            "iterations": [],
            "final_state": {},
        }

    def record_iteration(
        self,
        iteration: int,
        out_idx: int,
        state,
        hof,
        num_evals: float,
        variable_names: Optional[Sequence[str]] = None,
        events=None,
    ) -> None:
        pops = state.pops
        ref = np.asarray(pops.ref)
        parent = np.asarray(pops.parent)
        cost = np.asarray(pops.cost, np.float64)
        loss = np.asarray(pops.loss, np.float64)
        cx = np.asarray(pops.complexity)
        birth = np.asarray(pops.birth)
        islands: List[Dict[str, Any]] = []
        for i in range(ref.shape[0]):
            islands.append(
                {
                    "ref": ref[i].tolist(),
                    "parent": parent[i].tolist(),
                    "cost": [_sanitize(float(c)) for c in cost[i]],
                    "loss": [_sanitize(float(c)) for c in loss[i]],
                    "complexity": cx[i].tolist(),
                    "birth": birth[i].tolist(),
                }
            )
        event_log = None
        if events is not None:
            event_log = self._assemble_events(events)
        rec = {
            "iteration": iteration,
            "out": out_idx + 1,
            "num_evals": float(num_evals),
            "events": event_log,
            "islands": islands,
            "hall_of_fame": [
                {
                    "complexity": int(e.complexity),
                    "loss": _sanitize(float(e.loss)),
                    "equation": e.equation_string(
                        variable_names=variable_names
                    ),
                }
                for e in hof.entries
            ],
        }
        if self._stream_path is not None:
            # Spill now, free now: verbosity-2 event logs are ~2M dicts
            # per iteration at the bench config; holding a whole run's
            # worth until write() was the memory cliff.
            with open(self._stream_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        else:
            self.record["iterations"].append(rec)

    _REASONS = ("none", "constraint", "invalid", "annealing")

    def _assemble_events(self, events) -> List[Dict[str, Any]]:
        """CycleEvents [I, ncycles, 2B] device arrays -> the
        reference-style per-mutation log (accepted events expanded with
        kind names — src/RegularizedEvolution.jl:47-75 records both
        accepts and rejects). Rejections: per-(kind, reason) aggregate
        counts at ``recorder_verbosity`` 1 (default); every rejected
        candidate becomes its own event (kind, parent, reason) at >= 2.
        Cost note: at the bench config (512 islands x ~40 candidate
        rows x 100 cycles) verbosity 2 assembles ~2M more host dicts
        per iteration — see BASELINE.md."""
        from ..core.options import MUTATION_KINDS

        kind = np.asarray(events.kind)
        parent = np.asarray(events.parent_ref)
        parent2 = np.asarray(events.parent2_ref)
        child = np.asarray(events.child_ref)
        died = np.asarray(events.died_ref)
        accepted = np.asarray(events.accepted)
        delta = np.asarray(events.cost_delta, np.float64)
        reason = np.asarray(events.reject_reason)
        names = list(MUTATION_KINDS) + ["crossover"]
        I, C, NB = kind.shape
        # An accepted row must carry a real kind: phantom slot-2 rows
        # (kind == -1) never replace by construction — names[-1] would
        # silently mislabel one as "crossover" if that ever regressed.
        assert (kind[accepted] >= 0).all(), "accepted event with kind=-1"
        # Bulk-extract every column once (one vectorized gather +
        # .tolist() each) instead of 7 scalar fancy-indexes per event:
        # ~6x less host time at the bench config's ~0.5M accepted
        # events/iteration, where assembly — not the device — bounds
        # recorder-enabled wall-clock (BASELINE.md).
        acc_idx = np.nonzero(accepted)
        cols = [acc_idx[0].tolist(), acc_idx[1].tolist()]  # slot unused
        cols += [a[acc_idx].tolist()
                 for a in (kind, parent, parent2, child, died, delta,
                           reason)]
        out: List[Dict[str, Any]] = []
        rejects: Dict[str, int] = {}
        for isl, cyc, kk, par, p2, ch, dd, dl, r in zip(*cols):
            k = names[kk]
            ev = {
                "island": isl,
                "cycle": cyc,
                "type": k,
                "parent": par,
                "child": ch,
                "died": dd,
                "cost_delta": _sanitize(dl),
            }
            if k == "crossover" and p2 >= 0:
                ev["parent2"] = p2
            if r > 0:  # kept-parent fallback: accepted AND rejected-why
                ev["reject_reason"] = self._REASONS[r]
            out.append(ev)
        rej_mask = ~accepted & (kind >= 0)
        pairs, pair_counts = np.unique(
            np.stack([kind[rej_mask], reason[rej_mask]]),
            axis=1, return_counts=True)
        rejects = {
            f"{names[int(k)]}:{self._REASONS[int(r)]}": int(c)
            for (k, r), c in zip(pairs.T, pair_counts)
        }
        result = {"accepted": out, "rejected_counts": rejects}
        if self.verbosity >= 2:
            rej_idx = np.nonzero(rej_mask)
            rcols = [rej_idx[0].tolist(), rej_idx[1].tolist()]  # slot unused
            rcols += [a[rej_idx].tolist() for a in (kind, parent, reason)]
            result["rejected"] = [
                {
                    "island": isl,
                    "cycle": cyc,
                    "type": names[kk],
                    "parent": par,
                    "reason": self._REASONS[r],
                }
                for isl, cyc, kk, par, r in zip(*rcols)
            ]
        return [result]

    def record_final(self, key: str, value: Any) -> None:
        self.record["final_state"][key] = value

    def write(self, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self._stream_path is None or not os.path.exists(self._stream_path):
            with open(path, "w") as f:
                json.dump(self.record, f)
            return
        # End-of-run merge: splice the spilled per-iteration records
        # (in arrival order, already serialized JSON objects) straight
        # into the output's "iterations" array line by line — loading
        # them all back first would re-materialize the exact event-dict
        # volume the streaming exists to cap. Same JSON layout as the
        # in-memory path (json.dump default separators).
        with open(path, "w") as out:
            out.write('{"options": ' + json.dumps(self.record["options"])
                      + ', "iterations": [')
            first = True
            with open(self._stream_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    out.write(("" if first else ", ") + line)
                    first = False
            for rec in self.record["iterations"]:
                out.write(("" if first else ", ") + json.dumps(rec))
                first = False
            out.write('], "final_state": '
                      + json.dumps(self.record["final_state"]) + "}")
        os.remove(self._stream_path)
