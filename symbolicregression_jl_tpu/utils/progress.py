"""Terminal progress bar for the search loop.

The WrappedProgressBar analogue (/root/reference/src/ProgressBars.jl:9-58):
a single-line bar with a live hall-of-fame postfix (best loss, eval rate),
redirected to devnull in test environments
(src/ProgressBars.jl:16-20 semantics via SYMBOLIC_REGRESSION_IS_TESTING).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional, TextIO

__all__ = ["ProgressBar"]


class ProgressBar:
    def __init__(self, total: int, width: int = 30,
                 stream: Optional[TextIO] = None):
        self.total = max(int(total), 1)
        self.width = width
        if stream is None:
            stream = (
                open(os.devnull, "w")
                if os.environ.get("SYMBOLIC_REGRESSION_IS_TESTING")
                else sys.stderr
            )
        self.stream = stream
        self.start = time.time()
        self.count = 0

    def update(self, count: int, best_loss: float = float("nan"),
               evals_per_sec: float = float("nan"),
               host_fraction: Optional[float] = None) -> None:
        self.count = count
        frac = min(count / self.total, 1.0)
        filled = int(frac * self.width)
        bar = "█" * filled + "░" * (self.width - filled)
        elapsed = time.time() - self.start
        eta = elapsed / frac - elapsed if frac > 0 else float("inf")
        host = "" if host_fraction is None else f"  host {host_fraction:.0%}"
        postfix = (
            f"best_loss={best_loss:.4g}  {evals_per_sec:,.0f} evals/s  "
            f"eta {eta:,.0f}s{host}"
        )
        self.stream.write(f"\r{bar} {count}/{self.total}  {postfix}   ")
        self.stream.flush()

    def close(self) -> None:
        if self.count:
            self.stream.write("\n")
        self.stream.flush()
        if self.stream not in (sys.stderr, sys.stdout):
            self.stream.close()
