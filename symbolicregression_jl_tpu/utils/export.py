"""Expression export: LaTeX, SymPy, and python callables.

Fills the role of the reference's SymbolicUtils extension
(/root/reference/ext/SymbolicRegressionSymbolicUtilsExt.jl) plus PySR's
latex/sympy export surface, host-side only.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from ..ops.tree import Node

__all__ = ["to_latex", "to_sympy", "to_callable", "template_to_latex"]


_LATEX_UNARY = {
    "sin": r"\sin", "cos": r"\cos", "tan": r"\tan", "sinh": r"\sinh",
    "cosh": r"\cosh", "tanh": r"\tanh", "exp": r"\exp", "log": r"\log",
    "safe_log": r"\log", "abs": None, "sqrt": None, "safe_sqrt": None,
    "neg": None, "square": None, "cube": None, "inv": None,
}


def _varname(i: int, variable_names: Optional[Sequence[str]]) -> str:
    if variable_names is not None and i < len(variable_names):
        return variable_names[i]
    return f"x_{{{i + 1}}}"


def to_latex(
    tree: Node, variable_names: Optional[Sequence[str]] = None,
    precision: int = 4,
) -> str:
    """Render a tree as LaTeX."""

    def fmt(v: float) -> str:
        s = f"{v:.{precision}g}"
        if "e" in s:
            mant, exp = s.split("e")
            return f"{mant} \\cdot 10^{{{int(exp)}}}"
        return s

    def go(n: Node) -> str:
        if n.degree == 0:
            if n.is_parameter:
                return f"p_{{{n.parameter + 1}}}"
            if n.constant:
                return fmt(n.val)
            return _varname(n.feature, variable_names)
        name = n.op.name
        if n.degree == 2:
            a, b = (go(c) for c in n.children)
            if name == "+":
                return f"{a} + {b}"
            if name == "-":
                return f"{a} - \\left({b}\\right)" if _needs_paren(n.children[1]) else f"{a} - {b}"
            if name == "*":
                return f"{_paren(n.children[0], a)} {_paren(n.children[1], b)}"
            if name == "/":
                return f"\\frac{{{a}}}{{{b}}}"
            if name in ("^", "pow", "safe_pow"):
                return f"{_paren(n.children[0], a)}^{{{b}}}"
            return f"\\mathrm{{{name}}}\\left({a}, {b}\\right)"
        (a,) = (go(c) for c in n.children)
        if name in ("sqrt", "safe_sqrt"):
            return f"\\sqrt{{{a}}}"
        if name == "abs":
            return f"\\left|{a}\\right|"
        if name == "neg":
            return f"-{_paren(n.children[0], a)}"
        if name == "square":
            return f"{_paren(n.children[0], a)}^{{2}}"
        if name == "cube":
            return f"{_paren(n.children[0], a)}^{{3}}"
        if name == "inv":
            return f"\\frac{{1}}{{{a}}}"
        mapped = _LATEX_UNARY.get(name)
        if mapped:
            return f"{mapped}\\left({a}\\right)"
        return f"\\mathrm{{{name.replace('safe_', '')}}}\\left({a}\\right)"

    def _needs_paren(n: Node) -> bool:
        return n.degree == 2 and n.op.name in ("+", "-")

    def _paren(n: Node, s: str) -> str:
        if n.degree == 2 and n.op.name in ("+", "-"):
            return f"\\left({s}\\right)"
        return s

    return go(tree)


_SYMPY_NAMES = {
    "+": lambda sp, a, b: a + b,
    "-": lambda sp, a, b: a - b,
    "*": lambda sp, a, b: a * b,
    "/": lambda sp, a, b: a / b,
    "^": lambda sp, a, b: a**b,
    "safe_pow": lambda sp, a, b: a**b,
    "pow": lambda sp, a, b: a**b,
    "max": lambda sp, a, b: sp.Max(a, b),
    "min": lambda sp, a, b: sp.Min(a, b),
    "mod": lambda sp, a, b: sp.Mod(a, b),
    "atan2": lambda sp, a, b: sp.atan2(a, b),
    "sin": lambda sp, a: sp.sin(a),
    "cos": lambda sp, a: sp.cos(a),
    "tan": lambda sp, a: sp.tan(a),
    "sinh": lambda sp, a: sp.sinh(a),
    "cosh": lambda sp, a: sp.cosh(a),
    "tanh": lambda sp, a: sp.tanh(a),
    "asin": lambda sp, a: sp.asin(a),
    "acos": lambda sp, a: sp.acos(a),
    "atan": lambda sp, a: sp.atan(a),
    "exp": lambda sp, a: sp.exp(a),
    "log": lambda sp, a: sp.log(a),
    "safe_log": lambda sp, a: sp.log(a),
    "safe_log2": lambda sp, a: sp.log(a, 2),
    "safe_log10": lambda sp, a: sp.log(a, 10),
    "safe_log1p": lambda sp, a: sp.log(a + 1),
    "sqrt": lambda sp, a: sp.sqrt(a),
    "safe_sqrt": lambda sp, a: sp.sqrt(a),
    "safe_asin": lambda sp, a: sp.asin(a),
    "safe_acos": lambda sp, a: sp.acos(a),
    "safe_acosh": lambda sp, a: sp.acosh(a),
    "safe_atanh": lambda sp, a: sp.atanh(a),
    "abs": lambda sp, a: sp.Abs(a),
    "neg": lambda sp, a: -a,
    "square": lambda sp, a: a**2,
    "cube": lambda sp, a: a**3,
    "inv": lambda sp, a: 1 / a,
    "sign": lambda sp, a: sp.sign(a),
    "gamma": lambda sp, a: sp.gamma(a),
    "erf": lambda sp, a: sp.erf(a),
    "erfc": lambda sp, a: sp.erfc(a),
    "relu": lambda sp, a: sp.Max(a, 0),
}


def to_sympy(tree: Node, variable_names: Optional[Sequence[str]] = None):
    """Convert a tree into a SymPy expression (requires sympy installed)."""
    try:
        import sympy as sp
    except ImportError as e:  # pragma: no cover
        raise ImportError("to_sympy requires the `sympy` package") from e

    def var(i: int):
        name = (
            variable_names[i]
            if variable_names is not None and i < len(variable_names)
            else f"x{i + 1}"
        )
        return sp.Symbol(name, real=True)

    def go(n: Node):
        if n.degree == 0:
            if n.is_parameter:
                return sp.Symbol(f"p{n.parameter + 1}", real=True)
            if n.constant:
                return sp.Float(n.val)
            return var(n.feature)
        args = [go(c) for c in n.children]
        fn = _SYMPY_NAMES.get(n.op.name)
        if fn is None:
            f = sp.Function(n.op.name.replace("safe_", ""))
            return f(*args)
        return fn(sp, *args)

    return go(tree)


def to_callable(
    tree: Node, variable_names: Optional[Sequence[str]] = None
) -> Callable:
    """Build a vectorized callable ``f(X: (n, nfeatures), params=None) -> (n,)``.

    Computation runs through the operator table's JAX functions (float32,
    the framework's eval precision). Parameter leaves read from ``params``
    (a 1D vector); calling a parametric tree without ``params`` raises.
    """

    def f(X, params=None):
        X = np.asarray(X, dtype=np.float32)

        def go(n: Node):
            if n.degree == 0:
                if n.is_parameter:
                    if params is None:
                        raise ValueError(
                            "Tree contains parameter leaves; pass `params`."
                        )
                    return np.full(X.shape[0], params[n.parameter], np.float32)
                if n.constant:
                    return np.full(X.shape[0], n.val, np.float32)
                return X[:, n.feature]
            args = [go(c) for c in n.children]
            with np.errstate(all="ignore"):
                return np.asarray(n.op.fn(*args), dtype=np.float32)

        return go(tree)

    return f


def template_to_latex(template_expr, precision: int = 4) -> str:
    """LaTeX for a HostTemplateExpression: aligned per-component lines
    (subexpression arguments render as ``\\#i``; parameter vectors as
    row matrices)."""
    st = template_expr.structure
    lines = []
    for k, key in enumerate(st.expr_keys):
        names = [f"\\#{i + 1}" for i in range(st.num_features[k])]
        body = to_latex(template_expr.trees[key], variable_names=names,
                        precision=precision)
        lines.append(f"{key} &= {body}")
    if st.has_params and template_expr.params is not None:
        for key, off, cnt in zip(st.param_keys, st.param_offsets,
                                 st.num_params):
            vals = ", ".join(
                f"{float(v):.{precision}g}"
                for v in template_expr.params[off:off + cnt]
            )
            lines.append(f"{key} &= [{vals}]")
    sep = " \\\\\n"
    return "\\begin{aligned}\n" + sep.join(lines) + "\n\\end{aligned}"
