"""Search observability: SRLogger payloads incl. pareto volume.

TPU analogue of /root/reference/src/Logging.jl: wraps any backend with a
`log_interval`, and emits per-iteration payloads containing population
complexity histograms, the pareto front (equations + losses), num_evals,
and the **pareto volume** — the area under the convex hull in
(log complexity, log loss) space computed by gift-wrapping
(pareto_volume/convex_hull, src/Logging.jl:157-215).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SRLogger", "pareto_volume", "convex_hull"]


def convex_hull(xy: np.ndarray) -> np.ndarray:
    """Gift-wrapping (Jarvis march) convex hull of 2D points
    (src/Logging.jl:157-179)."""
    xy = np.asarray(xy, dtype=float)
    n = xy.shape[0]
    if n < 3:
        return xy
    # leftmost point
    start = int(np.argmin(xy[:, 0]))
    hull: List[int] = []
    p = start
    while True:
        hull.append(p)
        q = (p + 1) % n
        for r in range(n):
            cross = (xy[q, 0] - xy[p, 0]) * (xy[r, 1] - xy[p, 1]) - (
                xy[q, 1] - xy[p, 1]
            ) * (xy[r, 0] - xy[p, 0])
            if cross < 0:
                q = r
        p = q
        if p == start or len(hull) > n:
            break
    return xy[hull]


def pareto_volume(
    losses: Sequence[float], complexities: Sequence[int], maxsize: int,
    use_linear_scaling: bool = False,
) -> float:
    """Area under the pareto curve in scaled (log complexity, log loss)
    space (src/Logging.jl:181-215): hull closed with corner points at
    (log(maxsize+1), max log-loss)."""
    losses = np.asarray(losses, dtype=float)
    complexities = np.asarray(complexities, dtype=float)
    keep = np.isfinite(losses) & (losses > 0 if not use_linear_scaling else True)
    losses, complexities = losses[keep], complexities[keep]
    if len(losses) == 0:
        return 0.0
    y = losses if use_linear_scaling else np.log10(losses + 1e-150)
    x = np.log10(complexities)
    max_y, min_y = float(np.max(y)), float(np.min(y))
    if max_y == min_y:
        max_y = min_y + 1.0
    # close the curve with the corner (log(maxsize+1), max_y) and
    # (min x, max_y) so the area is bounded:
    x_top = math.log10(maxsize + 1)
    xs = np.concatenate([x, [x_top, float(np.min(x))]])
    ys = np.concatenate([y, [max_y, max_y]])
    hull = convex_hull(np.stack([xs, ys], axis=1))
    # shoelace (hull is in order from gift wrapping)
    area = 0.0
    for i in range(len(hull)):
        x1, y1 = hull[i]
        x2, y2 = hull[(i + 1) % len(hull)]
        area += x1 * y2 - x2 * y1
    return abs(area) / 2.0


@dataclasses.dataclass
class SRLogger:
    """Interval logger (src/Logging.jl:39-55).

    ``backend`` is any callable ``(payload: dict) -> None``; e.g. print,
    a TensorBoard writer wrapper, or a JSONL file sink. Payload structure
    mirrors the reference's nested dict of complexity histograms, pareto
    front, pareto volume, num_evals.
    """

    backend: Optional[Callable[[Dict[str, Any]], None]] = None
    log_interval: int = 1
    jsonl_path: Optional[str] = None
    _records: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    _count: int = 0

    def log_iteration(self, *, iteration, hofs, states, options, num_evals,
                      elapsed, host_fraction: Optional[float] = None) -> None:
        self._count += 1
        if self._count % max(self.log_interval, 1) != 0:
            return
        payload: Dict[str, Any] = {
            "iteration": int(iteration),
            "num_evals": float(num_evals),
            "elapsed_s": float(elapsed),
            "evals_per_sec": float(num_evals) / max(float(elapsed), 1e-9),
            "outputs": [],
        }
        if host_fraction is not None:
            # Host-pacing share of loop time (ResourceMonitor) — the
            # telemetry hub passes it so logger backends can alert on
            # host-bound searches without scraping stdout.
            payload["host_fraction"] = float(host_fraction)
        for j, (hof, state) in enumerate(zip(hofs, states)):
            frontier = hof.pareto_frontier()
            losses = [e.loss for e in frontier]
            complexities = [e.complexity for e in frontier]
            sizes = np.asarray(state.pops.complexity).reshape(-1)
            hist, _ = np.histogram(
                sizes, bins=np.arange(0.5, options.maxsize + 1.5)
            )
            payload["outputs"].append(
                {
                    "output": j + 1,
                    "min_loss": float(min(losses)) if losses else None,
                    "pareto_volume": pareto_volume(
                        losses, complexities, options.maxsize,
                        use_linear_scaling=(options.loss_scale == "linear"),
                    ),
                    "frontier": [
                        {"complexity": int(c), "loss": float(l)}
                        for c, l in zip(complexities, losses)
                    ],
                    "complexity_histogram": hist.tolist(),
                }
            )
        self._records.append(payload)
        if self.backend is not None:
            self.backend(payload)
        if self.jsonl_path is not None:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(payload) + "\n")

    @property
    def records(self) -> List[Dict[str, Any]]:
        return self._records
