"""Interactive quit: watch stdin for 'q' (or ctrl-d) during a search.

TPU analogue of the reference's StdinReader/watch_stream/
check_for_user_quit (/root/reference/src/SearchUtils.jl:336-385): a
daemon thread reads the input stream; the host loop polls ``quit`` once
per early-stop check and ends the search gracefully, keeping all results
produced so far.
"""

from __future__ import annotations

import sys
import threading
import weakref
from typing import Optional, TextIO

__all__ = ["StdinQuitWatcher"]


def _watch_loop(watcher_ref) -> None:
    """Thread body holding only a weakref: when the owning search frame
    dies (return OR exception), the watcher is collected and the thread
    exits at the next poll — no stdin-consuming thread can outlive its
    search."""
    while True:
        w = watcher_ref()
        if w is None or w.quit or w._stopped:
            return
        try:
            if not w._readable(0.2):
                continue
            ch = w.stream.read(1)
            if w._stopped:
                return
            if ch == "" or ch.lower() == "q":  # EOF (ctrl-d) or quit
                w.quit = True
                return
        except (ValueError, OSError):  # stream closed mid-search
            return
        finally:
            del w  # don't pin the watcher across the poll sleep


class StdinQuitWatcher:
    """Reads characters off ``stream`` on a daemon thread; sets ``quit``
    when a 'q' (or end-of-stream ctrl-d) arrives.

    Only engages when the stream is an interactive TTY (tests and batch
    jobs are unaffected) unless ``force=True`` (used with injected
    streams in tests).
    """

    @classmethod
    def disabled(cls) -> "StdinQuitWatcher":
        """A watcher that never engages and never touches ``sys.stdin``
        — the headless/server form (``Options(interactive_quit=False)``
        or a non-TTY stdin). No thread, no termios, ``check()`` is
        always False; a long-lived multi-tenant server must not spawn a
        stdin-consuming thread (or flip terminal modes) per request."""
        w = cls.__new__(cls)
        w.stream = None
        w.quit = False
        w._stopped = True
        w._thread = None
        w._saved_termios = None
        w.active = False
        return w

    def __init__(self, stream: Optional[TextIO] = None, force: bool = False):
        self.stream = stream if stream is not None else sys.stdin
        self.quit = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._saved_termios = None
        try:
            interactive = force or self.stream.isatty()
        except (AttributeError, ValueError):
            interactive = False
        self.active = bool(interactive)
        if self.active:
            self._enter_cbreak()
            self._thread = threading.Thread(
                target=_watch_loop, args=(weakref.ref(self),), daemon=True
            )
            self._thread.start()

    def _enter_cbreak(self) -> None:
        """Disable line buffering on a real TTY so a bare 'q' registers
        without Enter (the reference switches its terminal to raw mode,
        src/SearchUtils.jl:342-349); restored by stop(). Injected test
        streams and pipes have no termios and are left alone."""
        try:
            import termios
            import tty

            fd = self.stream.fileno()
            if not self.stream.isatty():
                return
            self._saved_termios = (fd, termios.tcgetattr(fd))
            tty.setcbreak(fd)
        except Exception:  # no tty/termios: stay line-buffered
            self._saved_termios = None

    def _restore_tty(self) -> None:
        if self._saved_termios is None:
            return
        fd, attrs = self._saved_termios
        self._saved_termios = None
        try:
            import termios

            termios.tcsetattr(fd, termios.TCSADRAIN, attrs)
        except Exception:
            pass

    def _readable(self, timeout: float) -> bool:
        """Poll the stream for input so the thread can exit on stop();
        streams without a selectable fd (StringIO) are always readable."""
        import select

        try:
            fd = self.stream.fileno()
        except (AttributeError, OSError, ValueError):
            return True
        try:
            r, _, _ = select.select([fd], [], [], timeout)
        except (OSError, ValueError):
            return False
        return bool(r)

    def stop(self) -> None:
        """End the watcher thread (called when the search finishes —
        otherwise a stale thread would keep consuming stdin characters
        meant for a later search) and restore the terminal mode."""
        self._stopped = True
        self._restore_tty()

    def __del__(self):  # backstop for exception paths
        self._stopped = True
        self._restore_tty()

    def check(self) -> bool:
        """True when the user asked to quit (check_for_user_quit,
        src/SearchUtils.jl:372-377)."""
        return self.active and self.quit
