"""Headroom model + proactive degrade (graftgauge, part d).

Two capacity consumers of the footprint ledger and the live sampler:

- :class:`HeadroomModel` answers the admission-time question "does a
  request of this shape fit?" from fingerprint/geometry-keyed ledger
  history. Its answer is ADVISORY: the serve
  :class:`~..serve.admission.AdmissionController` attaches it to the
  decision (and the journaled accept record) but never rejects on it —
  the model is a floor estimate from observed programs, and a wrong
  "no" would be a false outage. Operators alert on the advisory;
  the shield still catches a real OOM.

- :class:`ProactiveDegrader` steps ``eval_tile_rows`` down BEFORE an
  OOM: when the per-iteration memory watermark crosses
  ``headroom_fraction`` of the known byte limit, it invokes the same
  ``Engine.degrade_eval_tile_rows`` ladder the shield uses reactively
  (docs/ROBUSTNESS.md) and emits a ``fault`` event (kind
  ``proactive_degrade``) — which also triggers the flight-recorder
  bundle dump, so the evidence of WHY the shape shrank is on disk.
  Default-off (``RuntimeOptions(gauge_headroom_fraction=None)``):
  stepping the launch geometry down changes results by design, so the
  knob must be an explicit opt-in to keep the default-config A/B
  bit-identity contract.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .footprint import FootprintLedger, global_ledger
from .sampler import device_memory_stats

__all__ = ["HeadroomModel", "ProactiveDegrader"]


class HeadroomModel:
    """Predict prospective footprints from ledger history."""

    def __init__(self, ledger: Optional[FootprintLedger] = None) -> None:
        self.ledger = ledger if ledger is not None else global_ledger()

    def predict_bytes(self, *, rows: Optional[int] = None,
                      nfeatures: Optional[int] = None,
                      fingerprint: Optional[str] = None
                      ) -> Optional[int]:
        """Largest known ``total_bytes`` among matching ledger entries
        (a floor — see FootprintLedger.predict_bytes), or None when the
        ledger has no history for the shape yet."""
        return self.ledger.predict_bytes(
            rows=rows, nfeatures=nfeatures, fingerprint=fingerprint)

    def advise(self, *, bucket, limit_bytes: Optional[int] = None,
               in_use_bytes: Optional[int] = None
               ) -> Optional[Dict[str, Any]]:
        """Admission advisory for one shape bucket ``(rows, nfeatures,
        nout)``: predicted program bytes vs the device byte budget.

        ``limit_bytes`` defaults to the backend allocator's
        ``bytes_limit`` (None on CPU — the advisory then reports the
        prediction with ``fits: None``, unknowable rather than
        fabricated). Returns None when the ledger knows nothing about
        the shape (no advisory beats a made-up one)."""
        rows, nfeatures = int(bucket[0]), int(bucket[1])
        predicted = self.predict_bytes(rows=rows, nfeatures=nfeatures)
        if predicted is None:
            return None
        stats = device_memory_stats()
        if limit_bytes is None and stats is not None:
            limit_bytes = stats.get("bytes_limit")
        if in_use_bytes is None and stats is not None:
            in_use_bytes = stats.get("bytes_in_use")
        out: Dict[str, Any] = {
            "predicted_bytes": int(predicted),
            "limit_bytes": (int(limit_bytes)
                            if limit_bytes is not None else None),
            "in_use_bytes": (int(in_use_bytes)
                             if in_use_bytes is not None else None),
            "headroom_bytes": None,
            "fits": None,
        }
        if limit_bytes:
            headroom = int(limit_bytes) - int(in_use_bytes or 0)
            out["headroom_bytes"] = headroom
            out["fits"] = bool(predicted <= headroom)
        return out


class ProactiveDegrader:
    """Watermark-driven ``eval_tile_rows`` step-down; see module
    docstring. Driven per iteration by the MemorySampler."""

    def __init__(
        self,
        degrade: Callable[[], Optional[int]],
        *,
        headroom_fraction: float,
        limit_bytes: Optional[int] = None,
        hub=None,
        cooldown: int = 2,
    ) -> None:
        if not (0.0 < float(headroom_fraction) <= 1.0):
            raise ValueError("headroom_fraction must be in (0, 1]")
        self.degrade = degrade
        self.headroom_fraction = float(headroom_fraction)
        # explicit budget (RuntimeOptions(gauge_limit_bytes) — the only
        # path on CPU); the per-check allocator limit overrides it when
        # the backend reports one
        self.limit_bytes = limit_bytes
        self.hub = hub
        # iterations to wait after a step-down before re-evaluating:
        # the smaller launch geometry needs at least one iteration to
        # show up in the watermark, and without the cooldown a single
        # excursion would ladder straight to the floor
        self.cooldown = max(int(cooldown), 0)
        self._cooldown_until = -1
        self.exhausted = False
        self.degrades = 0

    def check(self, iteration: int, *, watermark_bytes: int,
              limit_bytes: Optional[int] = None) -> bool:
        """Evaluate one iteration's watermark; returns True when a
        step-down happened. Never raises into the loop."""
        limit = limit_bytes if limit_bytes is not None else self.limit_bytes
        if limit is None or self.exhausted:
            return False
        if iteration < self._cooldown_until:
            return False
        threshold = self.headroom_fraction * float(limit)
        if float(watermark_bytes) <= threshold:
            return False
        try:
            new_rows = self.degrade()
        except Exception:  # noqa: BLE001 - protection must not crash
            return False
        self._cooldown_until = iteration + 1 + self.cooldown
        if new_rows is None:
            # already at the floor: record the exhaustion once, then go
            # quiet — the reactive shield path owns whatever comes next
            self.exhausted = True
        else:
            self.degrades += 1
        if self.hub is not None:
            try:
                self.hub.fault(
                    "proactive_degrade", iteration=int(iteration),
                    watermark_bytes=int(watermark_bytes),
                    limit_bytes=int(limit),
                    headroom_fraction=self.headroom_fraction,
                    eval_tile_rows=new_rows,
                    exhausted=self.exhausted or None,
                )
            except Exception:  # noqa: BLE001 - audit is best-effort
                pass
        return new_rows is not None
