"""Compiled-executable footprint ledger (graftgauge, part a).

``mesh/aot.py`` has computed ``memory_analysis()`` on every compiled
executable since PR 8 and nobody read it; the serve layer admits
requests on queue depth alone; ROADMAP item 1 wants N tenants packed
into one device program. This module is the missing bookkeeping: every
place the stack produces a ``jax.stages.Compiled`` — mesh AOT
executables, the opt-in fused-eval probe, a loaded AOT replica's
stamped envelope — summarizes the backend's static analysis into a
plain dict and records it in a process-wide ledger keyed by the
canonical ``options_fingerprint`` plus the launch geometry.

Consumers:

- the serve :class:`~..serve.admission.AdmissionController` asks the
  :class:`~.capacity.HeadroomModel` "does a request of this shape
  fit?", which answers from this ledger's history;
- the serve ``ExecutableCache`` stamps known footprints onto its
  cache_hit/cache_miss telemetry details;
- ``/metrics`` renders one ``footprint_bytes`` gauge per ledger entry
  (serve/metrics.py ``render_gauge_metrics``);
- ``equation_search`` emits each new entry as a ``gauge`` event
  (kind ``footprint``) into the graftscope stream.

Everything here is host-side bookkeeping over analyses XLA already
performed at compile time — no device work, no extra transfers, and
(like pulse/ledger) bit-neutral to the search by construction.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FootprintLedger",
    "geometry_key",
    "global_ledger",
    "probe_engine_iteration",
    "summarize_compiled",
]

# memory_analysis() attributes worth keeping, in stable order. Backends
# differ in which they expose; absent ones are simply omitted.
_MEMORY_FIELDS = (
    "temp_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def geometry_key(*, rows: int, nfeatures: int, nout: int = 1) -> str:
    """Canonical geometry label: the axes that change a program's
    footprint (dataset rows, features, outputs). Matches the admission
    shape-bucket axes so ledger history answers bucket queries."""
    return f"r{int(rows)}xf{int(nfeatures)}xo{int(nout)}"


def _analysis_dict(obj) -> Optional[Dict[str, Any]]:
    """cost_analysis() returns a dict on current jax, a 1-list of dicts
    on some older versions, or raises on backends without HLO cost
    modeling — normalize all of that to a flat dict or None."""
    if obj is None:
        return None
    if isinstance(obj, (list, tuple)):
        obj = obj[0] if obj else None
    return dict(obj) if isinstance(obj, dict) else None


def summarize_compiled(compiled) -> Optional[Dict[str, Any]]:
    """Flatten one ``jax.stages.Compiled``'s static analyses into a
    JSON-able summary dict, or None when the backend exposes neither
    analysis (both are optional in the jax API contract)."""
    out: Dict[str, Any] = {}
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-optional introspection
        mem = None
    if mem is not None:
        for field in _MEMORY_FIELDS:
            v = getattr(mem, field, None)
            if v is not None:
                try:
                    out[field] = int(v)
                except (TypeError, ValueError):
                    pass
    try:
        cost = _analysis_dict(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 - backend-optional introspection
        cost = None
    if cost is not None:
        flops = cost.get("flops")
        if flops is not None:
            try:
                out["flops"] = float(flops)
            except (TypeError, ValueError):
                pass
        ba = cost.get("bytes accessed")
        if ba is not None:
            try:
                out["bytes_accessed"] = float(ba)
            except (TypeError, ValueError):
                pass
    if not out:
        return None
    out["total_bytes"] = sum(
        int(out.get(f, 0)) for f in _MEMORY_FIELDS)
    return out


class FootprintLedger:
    """Thread-safe (fingerprint, geometry) -> footprint-summary table.

    One entry per distinct compiled program the process has seen;
    re-recording an existing key refreshes the summary and bumps its
    compile count (the geometry was recompiled — e.g. after a shield
    degrade rebuilt the jits at a smaller launch shape).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def record(self, fingerprint: Optional[str], geometry: str,
               summary: Optional[Dict[str, Any]], *,
               source: str = "unknown",
               rows: Optional[int] = None,
               nfeatures: Optional[int] = None,
               nout: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Record one compiled program's footprint; returns the stored
        entry (None when there was nothing to store)."""
        if not summary:
            return None
        key = (fingerprint or "", str(geometry))
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = {
                    "fingerprint": fingerprint,
                    "geometry": str(geometry),
                    "source": str(source),
                    "compiles": 0,
                    "rows": rows,
                    "nfeatures": nfeatures,
                    "nout": nout,
                }
                self._entries[key] = entry
            entry["compiles"] += 1
            entry["summary"] = dict(summary)
            return dict(entry)

    def known(self, fingerprint: Optional[str], geometry: str) -> bool:
        with self._lock:
            return (fingerprint or "", str(geometry)) in self._entries

    def lookup(self, fingerprint: Optional[str],
               geometry: Optional[str] = None
               ) -> Optional[Dict[str, Any]]:
        """The entry for an exact (fingerprint, geometry) key, or —
        with geometry None — the largest-footprint entry recorded for
        the fingerprint at any geometry (the conservative answer for
        "what does this config cost")."""
        with self._lock:
            if geometry is not None:
                e = self._entries.get((fingerprint or "", str(geometry)))
                return dict(e) if e is not None else None
            best = None
            for (fp, _), e in self._entries.items():
                if fp != (fingerprint or ""):
                    continue
                if best is None or (
                        e["summary"].get("total_bytes", 0)
                        > best["summary"].get("total_bytes", 0)):
                    best = e
            return dict(best) if best is not None else None

    def predict_bytes(self, *, rows: Optional[int] = None,
                      nfeatures: Optional[int] = None,
                      fingerprint: Optional[str] = None
                      ) -> Optional[int]:
        """Footprint estimate for a prospective program: the largest
        ``total_bytes`` among entries matching the given axes (None
        axes match everything; rows matches entries at or below the
        requested count — a bigger dataset can only cost more, so the
        estimate is a floor, reported as such by the headroom model)."""
        with self._lock:
            best: Optional[int] = None
            for e in self._entries.values():
                if fingerprint is not None and \
                        e.get("fingerprint") != fingerprint:
                    continue
                if nfeatures is not None and \
                        e.get("nfeatures") not in (None, int(nfeatures)):
                    continue
                if rows is not None and e.get("rows") is not None \
                        and int(e["rows"]) > int(rows):
                    continue
                total = e["summary"].get("total_bytes")
                if total and (best is None or int(total) > best):
                    best = int(total)
            return best

    def entries(self) -> List[Dict[str, Any]]:
        """Stable-ordered snapshot (the /metrics render and `telemetry
        report` iterate this)."""
        with self._lock:
            return [dict(e) for _, e in sorted(self._entries.items())]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# Process-wide ledger: compile sites (mesh/aot.py, the search-loop
# probe) record into it from wherever compilation happens, and the
# serve layer's /metrics + admission advisor read it without threading
# a handle through every constructor. Append-only bookkeeping guarded
# by its own lock; never nested with any other lock.
_GLOBAL = FootprintLedger()


def global_ledger() -> FootprintLedger:
    return _GLOBAL


def probe_engine_iteration(engine, state, data, cur_maxsize=None,
                           *, ledger: Optional[FootprintLedger] = None,
                           source: str = "probe"
                           ) -> Optional[Dict[str, Any]]:
    """AOT-compile the engine's iteration program purely to harvest its
    footprint (the fused-eval launch path has no public handle on the
    executables its ``jax.jit`` wrappers cache, so the probe lowers the
    same program explicitly — an extra XLA compile, which is why the
    search loop gates it behind ``RuntimeOptions(gauge_footprint)`` and
    skips geometries the ledger already knows).

    Returns the recorded ledger entry, or None when the probe could not
    compile/summarize (never raises — observability must not take down
    the search it measures).
    """
    led = ledger if ledger is not None else _GLOBAL
    try:
        from ..api.checkpoint import options_fingerprint
        from ..mesh.aot import compile_iteration

        fp = options_fingerprint(engine.options)
        rows = int(data.y.shape[0])
        geom = geometry_key(rows=rows, nfeatures=int(engine.nfeatures))
        if led.known(fp, geom):
            return led.lookup(fp, geom)
        # compile_iteration records its own harvest into the global
        # ledger (source "mesh_aot"); prefer that entry and only record
        # directly when the AOT-side harvest came up empty
        ex = compile_iteration(engine, state, data, cur_maxsize)
        entry = led.lookup(fp, geom)
        if entry is not None:
            return entry
        return led.record(
            fp, geom, summarize_compiled(ex.compiled), source=source,
            rows=rows, nfeatures=int(engine.nfeatures), nout=1,
        )
    except Exception:  # noqa: BLE001 - probe is best-effort by contract
        return None
