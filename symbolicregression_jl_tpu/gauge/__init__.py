"""graftgauge: device capacity observability (docs/OBSERVABILITY.md,
"Capacity & memory").

Four parts, wired by api/search.py and the serve layer:

- footprint.py — compiled-executable memory/cost analysis harvested
  into a process-wide fingerprint+geometry-keyed ledger;
- sampler.py — per-iteration live-memory accounting
  (``jax.live_arrays()`` + backend-guarded ``memory_stats()``) with
  watermarks, the pulse leak tripwire, and bundle snapshots;
- latency.py — log-bucketed host-side dispatch-latency histograms,
  rendered on ``/metrics`` and in ``telemetry report``;
- capacity.py — the headroom model behind the serve layer's advisory
  memory-aware admission and the proactive ``eval_tile_rows``
  step-down (degrade BEFORE the OOM, not after).

Everything is host-side and — at the default knobs — bit-neutral to
the search (on/off HoF A/B pinned in tests/test_gauge.py, the same
contract pulse and ledger carry).
"""

from .capacity import HeadroomModel, ProactiveDegrader
from .footprint import (
    FootprintLedger,
    geometry_key,
    global_ledger,
    probe_engine_iteration,
    summarize_compiled,
)
from .latency import DEFAULT_LE_BOUNDS, DispatchLatency, global_latency
from .sampler import MemorySampler, device_memory_stats, process_peak_bytes

__all__ = [
    "DEFAULT_LE_BOUNDS",
    "DispatchLatency",
    "FootprintLedger",
    "HeadroomModel",
    "MemorySampler",
    "ProactiveDegrader",
    "device_memory_stats",
    "geometry_key",
    "global_latency",
    "global_ledger",
    "probe_engine_iteration",
    "process_peak_bytes",
    "summarize_compiled",
]
