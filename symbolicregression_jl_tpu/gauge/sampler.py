"""Live memory sampling at iteration boundaries (graftgauge, part b).

A telemetry-hub sink that, once per iteration boundary, accounts the
process' live device memory two ways:

- ``jax.live_arrays()`` byte totals — works on EVERY backend (it walks
  the host-side array registry; no device call), and is the portable
  signal the leak tripwire and the bundle snapshot use;
- ``device.memory_stats()`` — allocator truth (bytes_in_use /
  peak_bytes_in_use / bytes_limit) where the backend exposes it. The
  CPU backend does NOT (returns None or raises, jax-version dependent);
  the sampler degrades to the live-arrays path with ``stats: None``
  rather than failing — pinned by tests/test_gauge.py.

Per-iteration results feed four consumers, all host-side:

1. a ``gauge`` event (kind ``memory``) into the graftscope stream;
2. the graftpulse :class:`~..pulse.anomaly.AnomalyDetector` leak
   tripwire (``observe_live_bytes`` — monotonic growth over K
   iterations fires a ``live_bytes_growth`` anomaly, which also
   triggers a flight-recorder bundle dump);
3. the flight recorder's deterministic per-iteration view, as a
   BASELINE-RELATIVE delta: absolute live bytes include whatever else
   the process holds (a previous run's returned state, test fixtures),
   so the bundle records growth since run start — the part that is
   reproducible across identical runs — keeping the bundle
   byte-stability contract intact;
4. the proactive headroom degrader (capacity.py), handed the
   watermark so it can step ``eval_tile_rows`` down BEFORE an OOM.

Per-phase watermarks ride the host-span observer chain (the same
``(name, seconds)`` callback the cost ledger uses): the peak sampled
live bytes attributed to each named host phase's completion (the
latest iteration sample — spans do not re-walk the registry),
summarized into the run-end ``gauge`` event.

Reads only; never touches state, keys, or options — bit-neutral, with
the on/off HoF A/B pinned like pulse/ledger.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

__all__ = ["MemorySampler", "device_memory_stats", "process_peak_bytes"]

# Process-wide peak of live-array bytes observed by ANY sampler, for
# the serve /metrics surface (concurrent tenants share one device; the
# per-process peak is the capacity-relevant number).
_peak_lock = threading.Lock()
_process_peak = 0


def process_peak_bytes() -> int:
    with _peak_lock:
        return _process_peak


def _note_process_peak(live_bytes: int) -> None:
    global _process_peak
    with _peak_lock:
        if live_bytes > _process_peak:
            _process_peak = live_bytes


def live_array_bytes() -> Dict[str, int]:
    """Total bytes + count of live jax arrays (host-side registry walk;
    no device traffic). Never raises."""
    try:
        import jax

        arrays = jax.live_arrays()
        total = 0
        for a in arrays:
            try:
                total += int(a.nbytes)
            except Exception:  # deleted/donated buffers mid-walk
                pass
        return {"live_bytes": total, "live_arrays": len(arrays)}
    except Exception:  # noqa: BLE001 - sampling must never break the loop
        return {"live_bytes": 0, "live_arrays": 0}


def device_memory_stats() -> Optional[Dict[str, int]]:
    """Allocator stats from device 0, normalized to the three fields
    the capacity layer uses — or None where the backend has no
    allocator introspection (CPU: ``memory_stats()`` is absent, returns
    None, or raises depending on jax version; all degrade here)."""
    try:
        import jax

        dev = jax.devices()[0]
        fn = getattr(dev, "memory_stats", None)
        if fn is None:
            return None
        stats = fn()
        if not stats:
            return None
        out = {}
        for ours, theirs in (("bytes_in_use", "bytes_in_use"),
                             ("peak_bytes_in_use", "peak_bytes_in_use"),
                             ("bytes_limit", "bytes_limit")):
            v = stats.get(theirs)
            if v is not None:
                out[ours] = int(v)
        return out or None
    except Exception:  # noqa: BLE001 - backend-optional introspection
        return None


class MemorySampler:
    """Telemetry-hub sink; see module docstring."""

    def __init__(self, hub, *, detector=None, recorder=None,
                 degrader=None, emit_every: int = 1) -> None:
        self.hub = hub
        self.detector = detector
        self.degrader = degrader
        self.emit_every = max(int(emit_every), 1)
        base = live_array_bytes()
        # run-start baseline: the deterministic bundle view records
        # growth relative to this (absolute totals include unrelated
        # allocations the process already held)
        self.baseline_bytes = int(base["live_bytes"])
        self.baseline_arrays = int(base["live_arrays"])
        self.peak_live_bytes = self.baseline_bytes
        self.last: Optional[Dict[str, Any]] = None
        self._det_snapshot: Optional[Dict[str, int]] = None
        self.phase_peaks: Dict[str, int] = {}
        if recorder is not None:
            # recorder pulls the deterministic snapshot per iteration;
            # attribute hookup (not an import) keeps pulse free of any
            # gauge dependency
            recorder.memory_provider = self.deterministic_snapshot

    # -- host-span observer chain --------------------------------------
    def note_phase(self, name: str, seconds: float) -> None:
        """Per-phase live-bytes watermark; rides the same (name,
        seconds) span-observer callback as the cost ledger. Reuses the
        latest iteration sample rather than re-walking the registry —
        ``jax.live_arrays()`` is O(live arrays) and spans fire several
        times per iteration, so a fresh walk here would multiply the
        sampler's cost by the span count (prohibitive in array-heavy
        long-lived processes)."""
        b = (self.last or {}).get("live_bytes", self.baseline_bytes)
        if b > self.phase_peaks.get(name, -1):
            self.phase_peaks[name] = b

    # -- recorder hookup -----------------------------------------------
    def deterministic_snapshot(self) -> Optional[Dict[str, int]]:
        """The baseline-relative part of the latest sample (what the
        flight-recorder bundle keeps in its deterministic view)."""
        return self._det_snapshot

    # -- hub sink protocol ---------------------------------------------
    def on_iteration(self, ctx) -> None:
        it = int(ctx.iteration)
        live = live_array_bytes()
        live_bytes = int(live["live_bytes"])
        stats = device_memory_stats()
        self.peak_live_bytes = max(self.peak_live_bytes, live_bytes)
        _note_process_peak(live_bytes)
        self._det_snapshot = {
            "live_bytes_delta": live_bytes - self.baseline_bytes,
            "live_arrays_delta": (int(live["live_arrays"])
                                  - self.baseline_arrays),
        }
        sample: Dict[str, Any] = {
            "live_bytes": live_bytes,
            "live_arrays": int(live["live_arrays"]),
            "peak_live_bytes": self.peak_live_bytes,
            "stats": stats,
        }
        self.last = sample
        if self.detector is not None:
            observe = getattr(self.detector, "observe_live_bytes", None)
            if observe is not None:
                observe(it, live_bytes)
        if self.degrader is not None:
            # allocator watermark where the backend has one (that is
            # what actually OOMs); live-array bytes otherwise
            watermark = (stats or {}).get("bytes_in_use", live_bytes)
            limit = (stats or {}).get("bytes_limit")
            self.degrader.check(it, watermark_bytes=watermark,
                                limit_bytes=limit)
        if it % self.emit_every == 0:
            self.hub.gauge(
                "memory", iteration=it, live_bytes=live_bytes,
                live_arrays=int(live["live_arrays"]),
                peak_live_bytes=self.peak_live_bytes,
                bytes_in_use=(stats or {}).get("bytes_in_use"),
                peak_bytes_in_use=(stats or {}).get("peak_bytes_in_use"),
                bytes_limit=(stats or {}).get("bytes_limit"),
            )

    def emit_final(self, iteration: int = 0) -> None:
        # final watermark summary; the search loop calls this right
        # before hub.finish() so the event lands BEFORE run_end (the
        # timeline exporter and tail follower read streams in order)
        self.hub.gauge(
            "watermark", iteration=int(iteration),
            peak_live_bytes=self.peak_live_bytes,
            baseline_bytes=self.baseline_bytes,
            phase_peaks=(dict(self.phase_peaks)
                         if self.phase_peaks else None),
        )
