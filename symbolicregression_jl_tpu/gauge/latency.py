"""Dispatch-latency histograms (graftgauge, part c).

The round-5/6/7 dispatch-floor analysis (profiling/RESULTS.md) showed
per-launch host cost is a first-order axis at small geometries — and
nothing measured it continuously. This module is the continuous
measurement: a log-bucketed host-side histogram of the wall-clock time
each candidate-eval launch spends in the dispatch path (the per-engine
``one()`` closure in the search loop: enqueueing the iteration's device
work, NOT the device execution itself — the blocking sync is timed
separately by the loop's existing device_s accounting).

Bit-neutral by the same contract pulse/ledger pinned: the timer wraps
calls the loop already makes, reads only the wall clock, and feeds
nothing back into the search (tests/test_gauge.py pins the on/off HoF
A/B). Rendered via ``PromText.histogram()`` on ``/metrics`` (both the
per-run instance and the process-wide aggregate a serve scrape sees)
and summarized by ``telemetry report`` from the end-of-run ``gauge``
event (kind ``dispatch_latency``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..pulse.metrics import histogram_quantile

__all__ = ["DispatchLatency", "DEFAULT_LE_BOUNDS", "global_latency"]

# Log-spaced upper bounds (seconds): 0.25 ms .. ~131 s, one octave per
# bucket. Covers a warm CPU-test dispatch (~ms) through a device-scale
# compile-bearing launch (~minutes land in +Inf, which is fine — they
# are outliers by definition).
DEFAULT_LE_BOUNDS = tuple(0.00025 * (2.0 ** i) for i in range(20))


class DispatchLatency:
    """Thread-safe log-bucketed latency accumulator.

    ``counts`` carries one slot per bound plus the +Inf overflow slot —
    exactly the shape ``PromText.histogram`` renders (cumulative
    buckets, ``_count``/``_sum``).
    """

    def __init__(self, le_bounds=DEFAULT_LE_BOUNDS) -> None:
        self.le_bounds = tuple(float(b) for b in le_bounds)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.le_bounds) + 1)
        self._sum = 0.0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        i = 0
        for i, le in enumerate(self.le_bounds):
            if s <= le:
                break
        else:
            i = len(self.le_bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += s
            self._max = max(self._max, s)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    def snapshot(self) -> Dict[str, Any]:
        """Consistent copy: ``{"le", "counts", "count", "sum_s",
        "max_s", "p50_s", "p99_s"}`` (quantiles are bucket-upper-bound
        estimates, None while empty)."""
        with self._lock:
            counts = list(self._counts)
            total = sum(counts)
            sum_s = self._sum
            max_s = self._max
        def _q(q: float) -> Optional[float]:
            v = histogram_quantile(self.le_bounds, counts, q)
            if v is None:
                return None
            # a bucket-upper-bound estimate can exceed the true max
            # when few samples land in a wide bucket; clamp so the
            # report never shows p50 > max
            return min(v, max_s) if total else v

        return {
            "le": list(self.le_bounds),
            "counts": counts,
            "count": total,
            "sum_s": sum_s,
            "max_s": max_s if total else None,
            "p50_s": _q(0.5),
            "p99_s": _q(0.99),
        }

    def to_detail(self) -> Dict[str, Any]:
        """Compact JSON-able summary for the end-of-run ``gauge`` event
        (kind ``dispatch_latency``): scalars plus only the NONZERO
        buckets (the full 21-slot vector is /metrics' job)."""
        snap = self.snapshot()
        return {
            "count": snap["count"],
            "sum_s": round(snap["sum_s"], 6),
            "max_s": (round(snap["max_s"], 6)
                      if snap["max_s"] is not None else None),
            "p50_s": snap["p50_s"],
            "p99_s": snap["p99_s"],
            "buckets": {
                ("inf" if i == len(self.le_bounds)
                 else repr(self.le_bounds[i])): n
                for i, n in enumerate(snap["counts"]) if n
            },
        }

    def render(self, p, *, name: str = "dispatch_latency_seconds",
               help_text: str = ("Host-side candidate-eval dispatch "
                                 "latency (log-bucketed)"),
               labels: Optional[Dict[str, str]] = None) -> None:
        """Append this histogram to a ``PromText`` builder (no-op while
        empty — a scrape before the first dispatch shows no family
        rather than an all-zero one)."""
        snap = self.snapshot()
        if not snap["count"]:
            return
        p.histogram(name, snap["le"], snap["counts"], snap["sum_s"],
                    help_text, labels)


# Process-wide aggregate: every search's per-run instance also feeds
# this one, so a serve process' /metrics shows dispatch latency across
# all tenants without threading a handle through RuntimeOptions.
_GLOBAL = DispatchLatency()


def global_latency() -> DispatchLatency:
    return _GLOBAL
