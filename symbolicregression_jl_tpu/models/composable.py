"""ValidVector algebra + ComposableExpression — the building blocks of
template expressions.

TPU re-design of /root/reference/src/ComposableExpression.jl:

- ``ValidVector`` (reference :143-165): a device array paired with a
  validity flag. Operations propagate validity (all operands valid AND
  the result finite, matching ``apply_operator``/``_apply_operator``,
  reference :263-289). On TPU the flag is a traced bool scalar, so the
  whole algebra stays inside one jitted program — no branching.
- A vectorized operator surface (reference :353-388 overloads ~80 Base
  ops): Python dunders for arithmetic plus module-level named functions
  (``sin``, ``exp``, ``safe_log``...) drawn from the same safe-operator
  registry as the search itself (ops/operators.py), so template
  combiners see identical NaN-domain semantics as evolved trees.
- ``ComposableExpression`` (reference :198-256): a host expression that
  is *callable* — on data it evaluates, on other ComposableExpressions
  it splices trees (feature ``i`` leaf <- ``i``-th argument's tree).
  The device-side analogue used inside jitted template evaluation is
  ``TreeCallable`` (built by models/template.py from postfix tensors).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.operators import OPERATOR_REGISTRY, OperatorSet
from ..ops.tree import Node

__all__ = ["ValidVector", "ComposableExpression", "apply_operator", "ParamVec"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ValidVector:
    """Array data + validity flag (reference ComposableExpression.jl:143-165).

    ``x``: the row vector [n]; ``valid``: traced bool scalar. Invalid
    values poison everything downstream — the template eval returns
    loss = Inf for the member, matching the reference's invalid => NaN
    output contract (reference :169-186).
    """

    x: jax.Array
    valid: jax.Array  # bool scalar

    # -- arithmetic dunders (validity-propagating) --
    def __add__(self, o): return apply_operator("+", self, o)
    def __radd__(self, o): return apply_operator("+", o, self)
    def __sub__(self, o): return apply_operator("-", self, o)
    def __rsub__(self, o): return apply_operator("-", o, self)
    def __mul__(self, o): return apply_operator("*", self, o)
    def __rmul__(self, o): return apply_operator("*", o, self)
    def __truediv__(self, o): return apply_operator("/", self, o)
    def __rtruediv__(self, o): return apply_operator("/", o, self)
    def __pow__(self, o): return apply_operator("^", self, o)
    def __rpow__(self, o): return apply_operator("^", o, self)
    def __neg__(self): return apply_operator("neg", self)
    def __abs__(self): return apply_operator("abs", self)
    def __mod__(self, o): return apply_operator("mod", self, o)

    def __getitem__(self, idx):
        # Row-indexed gather (ParamVector[ValidVector] pattern,
        # reference TemplateExpression.jl:74-77) is on ParamVec; plain
        # indexing of a ValidVector slices the data, validity unchanged.
        return ValidVector(self.x[idx], self.valid)


def _is_vv(v) -> bool:
    return isinstance(v, ValidVector)


def _all_finite(x) -> jax.Array:
    """Validity of an operation result: all-finite over the row axis.

    Scalars/row vectors give a scalar flag (the per-member path);
    member-batched data [M, n] gives a per-member flag [M] (the batched
    template evaluator) — reduction is over the LAST axis only.
    """
    x = jnp.asarray(x)
    if x.ndim == 0:
        return jnp.isfinite(x)
    return jnp.all(jnp.isfinite(x), axis=-1)


def apply_operator(op: Union[str, Any], *args) -> ValidVector:
    """Apply a (safe) operator elementwise with validity propagation
    (apply_operator, reference ComposableExpression.jl:263-289).

    ``op`` is a registry name (resolved through the same safe-op table
    the search uses) or any jnp-traceable callable. Scalar operands
    broadcast against ValidVector operands.
    """
    if isinstance(op, str):
        from ..ops.operators import resolve_operator

        fn = resolve_operator(op).fn
    elif hasattr(op, "fn"):
        fn = op.fn
    else:
        fn = op
    vals = [a.x if _is_vv(a) else a for a in args]
    out = fn(*vals)
    valid = _all_finite(out)
    for a in args:
        if _is_vv(a):
            valid = valid & a.valid
    return ValidVector(jnp.asarray(out), valid)


# Named function surface: sr.models.composable.sin(vv), exp(vv), ... —
# mirrors the reference's vectorized Base-operator overloads (:353-388).
def _make_named(name):
    def f(*args):
        return apply_operator(name, *args)

    f.__name__ = name
    f.__qualname__ = name
    f.__doc__ = f"ValidVector-lifted `{name}` (validity-propagating)."
    return f


_NAMED_FNS = {}
for _name in OPERATOR_REGISTRY:
    if _name.isidentifier():
        _NAMED_FNS[_name] = _make_named(_name)
# Builtin-shadowing names (max, min, abs, round, pow, ...) stay out of the
# module globals — they resolve through __getattr__ (PEP 562) instead, so
# `from ...composable import max` still gives the lifted version while the
# module's own code keeps the builtins.
import builtins as _builtins

globals().update(
    {k: v for k, v in _NAMED_FNS.items() if not hasattr(_builtins, k)}
)
__all__ += sorted(_NAMED_FNS)


def __getattr__(name):
    try:
        return _NAMED_FNS[name]
    except KeyError:
        raise AttributeError(name) from None


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ParamVec:
    """A (read-only) parameter vector visible to template combiners
    (ParamVector, reference TemplateExpression.jl:40-77).

    Integer indexing gives a traced scalar; ``ValidVector`` indexing
    gathers per-row (the reference's `pv[I::ValidVector]`, :74-77 — the
    idiom for category-dependent parameters inside templates).
    """

    data: jax.Array  # [n_params]

    def __getitem__(self, idx):
        if _is_vv(idx):
            gathered = self.data[
                jnp.clip(idx.x.astype(jnp.int32), 0, self.data.shape[0] - 1)
            ]
            return ValidVector(gathered, idx.valid)
        return self.data[idx]

    def __len__(self):
        return self.data.shape[0]

    def __iter__(self):
        return (self.data[i] for i in range(self.data.shape[0]))


class ComposableExpression:
    """Host-side callable/composable expression
    (reference ComposableExpression.jl:198-256).

    Wraps a host ``Node`` whose variable leaves are *argument slots*
    ``#1..#k``. Calling with:

    - other ComposableExpressions => tree splicing: argument-``i``
      leaves are replaced by copies of ``args[i]``'s tree (:240-256);
    - arrays / ValidVectors / scalars => evaluation: arguments stack
      into an input matrix and run through the tensor interpreter
      (:198-227). Invalid results come back as NaN arrays (:169-186).
    """

    def __init__(self, tree: Node, operators: OperatorSet, nfeatures: int):
        self.tree = tree
        self.operators = operators
        self.nfeatures = nfeatures

    def __repr__(self) -> str:  # pragma: no cover
        return f"ComposableExpression({self.string()})"

    def string(self, variable_names=None) -> str:
        from ..ops.tree import string_tree

        names = variable_names or [f"#{i + 1}" for i in range(self.nfeatures)]
        return string_tree(self.tree, variable_names=names)

    def __call__(self, *args):
        if args and all(isinstance(a, ComposableExpression) for a in args):
            return self._compose(args)
        return self._evaluate(args)

    def derivative(self, argnum: int = 1) -> "ComposableExpression":
        """Symbolic row-wise derivative w.r.t. argument slot ``argnum``
        (1-based) — the host-side face of the template ``D`` operator.

        Returns a new ComposableExpression of the same arity whose tree
        is the simplified symbolic derivative (ops.diff.D). Derivative
        rules can introduce operators outside the original set (e.g.
        ``neg``/``sin`` from d cos); the result carries an operator set
        extended with whatever the derivative tree needs."""
        from ..ops.diff import D as symbolic_D
        from ..ops.operators import OperatorSet

        if not 1 <= argnum <= max(self.nfeatures, 1):
            raise ValueError(
                f"derivative argnum {argnum} out of range "
                f"1..{self.nfeatures}"
            )
        dtree = symbolic_D(self.tree, argnum - 1)
        have = {(op.name, d)
                for d, ops_d in self.operators.ops.items() for op in ops_d}
        need = {(n.op, n.degree) for n in dtree.nodes() if n.degree > 0}
        operators = self.operators
        missing = [(op, d) for op, d in need if (op.name, d) not in have]
        if missing:
            # Extend with the derivative rules' Op OBJECTS (not names —
            # custom operators in self.operators aren't in the registry).
            by_arity = {d: list(ops_d)
                        for d, ops_d in self.operators.ops.items()}
            for op, d in sorted(missing, key=lambda t: (t[1], t[0].name)):
                by_arity.setdefault(d, []).append(op)
            operators = OperatorSet(
                ops_by_arity={d: tuple(o) for d, o in by_arity.items()})
        return ComposableExpression(dtree, operators, self.nfeatures)

    def _compose(self, args: Sequence["ComposableExpression"]):
        if len(args) < self.nfeatures:
            raise ValueError(
                f"Expression uses {self.nfeatures} arguments; got {len(args)}"
            )

        def substitute(n: Node) -> Node:
            if n.degree == 0:
                if (not n.constant) and (not n.is_parameter):
                    return args[n.feature].tree.copy()
                return n.copy()
            return Node(
                op=n.op, children=[substitute(c) for c in n.children]
            )

        nfeat = max((a.nfeatures for a in args), default=0)
        return ComposableExpression(
            substitute(self.tree), self.operators, nfeat
        )

    def _evaluate(self, args):
        from ..ops.encoding import encode_population
        from ..ops.eval import eval_tree_batch

        scalar_input = args and all(np.ndim(getattr(a, "x", a)) == 0 for a in args)
        vecs = []
        valid_in = jnp.bool_(True)
        n = 1
        for a in args:
            if _is_vv(a):
                valid_in = valid_in & a.valid
                v = jnp.atleast_1d(a.x)
            else:
                v = jnp.atleast_1d(jnp.asarray(a, jnp.float32))
            vecs.append(v)
            n = max(n, v.shape[0])
        X = (
            jnp.stack([jnp.broadcast_to(v, (n,)) for v in vecs])
            if vecs
            else jnp.zeros((1, 1), jnp.float32)
        )
        max_nodes = max(self.tree.count_nodes(), 1)
        batch = encode_population([self.tree], max_nodes, self.operators,
                                  dtype=np.asarray(X).dtype)
        y, valid = eval_tree_batch(batch, X, self.operators)
        y, valid = y[0], valid[0] & valid_in
        if any(_is_vv(a) for a in args):
            return ValidVector(y, valid)
        y = jnp.where(valid, y, jnp.nan)
        return float(y[0]) if scalar_input else y
