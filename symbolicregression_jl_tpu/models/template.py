"""TemplateExpression — structured expressions with a user combiner.

TPU re-design of /root/reference/src/TemplateExpression.jl and
TemplateExpressionMacro.jl:

- ``TemplateStructure`` (reference :106-160): K named subexpressions +
  a ``combine`` function + optional named parameter vectors. The
  combiner is an arbitrary *jnp-traceable* Python function over
  ValidVectors (the reference allows arbitrary Julia closures; the TPU
  API contract narrows this to traceable functions — SURVEY.md §7
  "Template combiner generality").
- Arity inference (reference :213-241): probe the combiner with
  ``ArgumentRecorder``s that record how many arguments each
  subexpression is called with.
- ``template_spec`` (reference TemplateExpressionMacro.jl:34-151): the
  Python analogue of ``@template_spec`` — a decorator that reads
  subexpression / variable / parameter names off the function
  signature.
- Evaluation (reference :684-711): subexpressions become device
  callables over postfix tensors; the combiner runs inside the jitted
  eval with ValidVector validity algebra; the result must be a
  ValidVector (else ``TemplateReturnError``).

Population layout: a template member's trees are a ``TreeBatch`` with
an extra leading key axis ``[K, L]``; its parameters are a flat bank
``[total_params, 1]`` riding the same per-member parameter storage as
parametric expressions.
"""

from __future__ import annotations

import dataclasses
import inspect
import re
from types import SimpleNamespace
from typing import Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.encoding import TreeBatch, tree_structure_arrays
from ..ops.eval import eval_single_tree
from ..ops.operators import OperatorSet
from .composable import ParamVec, ValidVector

__all__ = [
    "TemplateStructure",
    "template_spec",
    "TemplateReturnError",
    "ArgumentRecorder",
    "eval_template_single",
    "eval_template_batch",
    "HostTemplateExpression",
    "parse_template_expression",
    "template_from_dict",
]


class TemplateReturnError(TypeError):
    """Combiner returned something other than a ValidVector
    (reference TemplateExpression.jl:634-666)."""

    def __init__(self):
        super().__init__(
            "Template `combine` must return a ValidVector — use the "
            "ValidVector algebra (subexpression calls and lifted "
            "operators) all the way to the final result."
        )


class ArgumentRecorder:
    """Stand-in subexpression that records call arity during inference
    (reference TemplateExpression.jl:243-258). Derivative call sites
    (``D(f, k)``) mark the shared record under the reserved ``__D__``
    key so the structure knows to route constant optimization through
    the jvp-composable interpreter path."""

    def __init__(self, key: str, record: Dict[str, int]):
        self._key = key
        self._record = record

    def _mark_deriv(self, argnum: int) -> None:
        self._record["__D__"] = 1

    def __call__(self, *args):
        prev = self._record.get(self._key, -1)
        if prev == -1:
            self._record[self._key] = len(args)
        elif prev != len(args):
            raise ValueError(
                f"Inconsistent number of arguments passed to {self._key!r}: "
                f"{prev} then {len(args)}"
            )
        if args:
            a0 = args[0]
            if isinstance(a0, ValidVector):
                return a0
            return ValidVector(jnp.atleast_1d(jnp.asarray(a0, jnp.float32)),
                               jnp.bool_(True))
        return ValidVector(jnp.ones((1,), jnp.float32), jnp.bool_(True))


class TemplateStructure(NamedTuple):
    """Static template configuration (hashable; lives inside the jitted
    engine's static config). See reference TemplateExpression.jl:106-160.

    ``combine(exprs, xs)`` or — with parameters — ``combine(exprs,
    params, xs)``, where ``exprs``/``params`` are attribute namespaces
    and ``xs`` is a tuple of per-feature ValidVectors.
    """

    combine: Callable
    expr_keys: Tuple[str, ...]
    num_features: Tuple[int, ...]       # per expr_key call arity
    param_keys: Tuple[str, ...] = ()
    num_params: Tuple[int, ...] = ()    # per param_key vector length
    n_variables: int = 0                # dataset features consumed
    uses_deriv: bool = False            # combiner contains D(...) call sites

    @property
    def has_params(self) -> bool:
        return len(self.param_keys) > 0

    @property
    def total_params(self) -> int:
        return int(sum(self.num_params))

    @property
    def n_subexpressions(self) -> int:
        return len(self.expr_keys)

    @property
    def param_offsets(self) -> Tuple[int, ...]:
        offs, o = [], 0
        for n in self.num_params:
            offs.append(o)
            o += n
        return tuple(offs)



def make_template_structure(
    combine: Callable,
    *,
    num_features: Optional[Dict[str, int]] = None,
    num_parameters: Optional[Dict[str, int]] = None,
    expressions: Optional[Sequence[str]] = None,
    n_variables: Optional[int] = None,
) -> TemplateStructure:
    """Build a TemplateStructure from a reference-style combiner
    ``combine(exprs, xs)`` / ``combine(exprs, params, xs)``.

    ``num_features`` is inferred by probing when not given
    (infer_variable_constraints, reference TemplateExpression.jl:213-241)
    — which requires knowing how many variables to offer; pass
    ``n_variables`` (or ``num_features`` explicitly) when the combiner
    destructures the variable tuple.
    """
    num_parameters = dict(num_parameters or {})
    if expressions is None:
        if num_features is None:
            raise ValueError(
                "Pass `expressions=[...]` (subexpression names) or an "
                "explicit `num_features` dict"
            )
        expressions = list(num_features)
    expr_keys = tuple(expressions)
    param_keys = tuple(num_parameters)
    nparams = tuple(int(num_parameters[k]) for k in param_keys)

    if num_features is None:
        record: Dict[str, int] = {}
        exprs = SimpleNamespace(
            **{k: ArgumentRecorder(k, record) for k in expr_keys}
        )
        dummy_params = SimpleNamespace(
            **{k: ParamVec(jnp.ones((n,), jnp.float32))
               for k, n in zip(param_keys, nparams)}
        )
        tried = (
            [n_variables] if n_variables is not None else list(range(1, 33))
        )
        last_err: Optional[Exception] = None
        inferred_nv = None
        for nv in tried:
            record.clear()
            xs = tuple(
                ValidVector(jnp.ones((1,), jnp.float32), jnp.bool_(True))
                for _ in range(nv)
            )
            try:
                if param_keys:
                    out = combine(exprs, dummy_params, xs)
                else:
                    out = combine(exprs, xs)
            except (TypeError, ValueError, IndexError) as e:  # try next count
                last_err = e
                continue
            if not isinstance(out, ValidVector):
                raise TemplateReturnError()
            inferred_nv = nv
            break
        if inferred_nv is None:
            raise ValueError(
                f"Could not infer the combiner's variable count; "
                f"last error: {last_err!r}"
            )
        missing = [k for k in expr_keys if k not in record]
        if missing:
            raise ValueError(
                f"Failed to infer number of features used by {missing} — "
                "the combiner never called them (reference "
                "TemplateExpression.jl:235-240)"
            )
        num_features = {k: record[k] for k in expr_keys}
        n_variables = inferred_nv
        uses_deriv = record.get("__D__", 0) > 0
    else:
        if n_variables is None:
            raise ValueError(
                "Pass `n_variables` along with explicit `num_features`"
            )
        # Probe solely for D(...) call sites; an un-probeable combiner
        # conservatively takes the autodiff-composable interpreter path
        # for constant optimization (correct, just slower).
        rec2: Dict[str, int] = {}
        try:
            exprs2 = SimpleNamespace(
                **{k: ArgumentRecorder(k, rec2) for k in expr_keys}
            )
            dp2 = SimpleNamespace(
                **{k: ParamVec(jnp.ones((n,), jnp.float32))
                   for k, n in zip(param_keys, nparams)}
            )
            xs2 = tuple(
                ValidVector(jnp.ones((1,), jnp.float32), jnp.bool_(True))
                for _ in range(int(n_variables))
            )
            if param_keys:
                combine(exprs2, dp2, xs2)
            else:
                combine(exprs2, xs2)
            uses_deriv = rec2.get("__D__", 0) > 0
        except Exception:
            uses_deriv = True

    return TemplateStructure(
        combine=combine,
        expr_keys=expr_keys,
        num_features=tuple(int(num_features[k]) for k in expr_keys),
        param_keys=param_keys,
        num_params=nparams,
        n_variables=int(n_variables),
        uses_deriv=bool(uses_deriv),
    )


def template_spec(
    *,
    expressions: Sequence[str],
    parameters: Optional[Dict[str, int]] = None,
):
    """Decorator analogue of ``@template_spec``
    (reference TemplateExpressionMacro.jl:34-151).

    The decorated function's signature names, in order: the
    subexpressions, then the dataset variables, then the parameter
    vectors::

        @template_spec(expressions=("f", "g"), parameters={"p": 2})
        def structure(f, g, x1, x2, x3, p):
            return f(x1, x2) + g(x3) ** 2 * p[0] + p[1]

    Returns a :class:`~symbolicregression_jl_tpu.models.spec.TemplateExpressionSpec`.
    """
    parameters = dict(parameters or {})
    expr_keys = tuple(expressions)
    param_keys = tuple(parameters)

    def build(fn: Callable):
        sig_names = list(inspect.signature(fn).parameters)
        for k in expr_keys:
            if k not in sig_names:
                raise ValueError(
                    f"Subexpression {k!r} not in function signature {sig_names}"
                )
        for k in param_keys:
            if k not in sig_names:
                raise ValueError(
                    f"Parameter {k!r} not in function signature {sig_names}"
                )
        var_names = [
            n for n in sig_names if n not in expr_keys and n not in param_keys
        ]

        def combine(exprs, *rest):
            if param_keys:
                params, xs = rest
            else:
                (xs,) = rest
                params = None
            kw = {k: getattr(exprs, k) for k in expr_keys}
            if len(xs) != len(var_names):
                raise ValueError(
                    f"Template expects {len(var_names)} variables "
                    f"({var_names}); dataset provides {len(xs)}"
                )
            kw.update(dict(zip(var_names, xs)))
            if params is not None:
                kw.update({k: getattr(params, k) for k in param_keys})
            return fn(**kw)

        structure = make_template_structure(
            combine,
            num_parameters=parameters,
            expressions=expr_keys,
            n_variables=len(var_names),
        )
        from .spec import TemplateExpressionSpec

        return TemplateExpressionSpec(structure=structure)

    return build


# ---------------------------------------------------------------------------
# Device-side evaluation
# ---------------------------------------------------------------------------


def eval_template_single(
    trees: TreeBatch,            # [K, L]
    X: jax.Array,                # [F, n]
    structure: TemplateStructure,
    operators: OperatorSet,
    params_flat: Optional[jax.Array] = None,   # [total_params]
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate one template member over all rows; returns (y[n], valid).

    Thin M=1 wrapper over :func:`eval_template_batch` — one evaluator
    implementation serves both shapes (the batched path is the
    load-bearing one: search candidates, optimizer, prediction)."""
    batched = TreeBatch(
        arity=trees.arity[None], op=trees.op[None], feat=trees.feat[None],
        const=trees.const[None], length=trees.length[None],
    )
    p = None if params_flat is None else params_flat[None]
    y, valid = eval_template_batch(batched, X, structure, operators, params=p)
    return y[0], valid[0]


class _BatchedTreeCallable:
    """Member-batched subexpression callable: one call evaluates key k of
    EVERY member in the batch over a shared (or per-member) argument set.

    The combiner is traced once over these — the whole template forward
    becomes a handful of batched tree-eval launches plus elementwise
    ValidVector algebra, instead of a per-member vmap of the full
    combiner. Dataset-column arguments (shared [n] rows) route through
    the fused Pallas kernel; member-dependent arguments (outputs of
    other subexpressions, [M, n]) fall back to the vmapped interpreter.
    """

    def __init__(self, key, trees: TreeBatch, child, arity_expected: int,
                 operators, n: int, fused: bool, interpret: bool):
        self.key = key
        self.trees = trees           # fields [M, L]
        self.child = child           # [M, L, A]
        self.arity_expected = arity_expected
        self.operators = operators
        self.n = n
        self.fused = fused
        self.interpret = interpret

    def _prep_args(self, args):
        """(rows, shared, valid_in) from combiner-supplied arguments."""
        if len(args) != self.arity_expected:
            raise ValueError(
                f"Subexpression {self.key!r} takes {self.arity_expected} "
                f"arguments; got {len(args)}"
            )
        dtype = self.trees.const.dtype
        valid_in = jnp.bool_(True)
        rows = []
        shared = True
        for a in args:
            if isinstance(a, ValidVector):
                valid_in = valid_in & a.valid
                x = jnp.asarray(a.x)
            else:
                x = jnp.asarray(a, dtype)
            if x.ndim >= 2:
                shared = False
            rows.append(x)
        return rows, shared, valid_in

    def _member_x(self, rows):
        """Broadcast arguments to a per-member [M, a, n] input block."""
        M = self.trees.arity.shape[0]
        n = self.n
        dtype = self.trees.const.dtype
        if not rows:
            return jnp.zeros((M, 1, n), dtype)
        return jnp.stack(
            [jnp.broadcast_to(jnp.atleast_1d(r), (M, n)) for r in rows],
            axis=1,
        ).astype(dtype)

    def derivative(self, argnum: int, *args):
        """Row-wise ∂ self(args) / ∂ args[argnum-1] — the ``D`` operator.

        Rows are independent, so on the fused path the derivative is a
        VJP with an all-ones cotangent: `fused_predict_ad`'s backward
        emits per-argument row cotangents (gx) in per-member X mode.
        The interpreter path uses forward-mode (jax.jvp), which also
        composes under jax.grad for constant optimization — structures
        with D call sites set `uses_deriv` and optimize on that path.
        """
        if not 1 <= argnum <= self.arity_expected:
            raise ValueError(
                f"D argnum {argnum} out of range 1..{self.arity_expected} "
                f"for subexpression {self.key!r}"
            )
        rows, _, valid_in = self._prep_args(args)
        Xm = self._member_x(rows)
        tr = self.trees
        if self.fused:
            from ..ops.fused_eval import fused_predict_ad

            (pred, v), vjp = jax.vjp(
                lambda xm: fused_predict_ad(
                    tr, xm, self.operators, interpret=self.interpret),
                Xm,
            )
            ct_valid = np.zeros(v.shape, jax.dtypes.float0)
            (gx,) = vjp((jnp.ones_like(pred), ct_valid))
            deriv = gx[:, argnum - 1, :]
        else:
            tangent = jnp.zeros_like(Xm).at[:, argnum - 1, :].set(1.0)

            def f(xm):
                return jax.vmap(
                    lambda a_, o_, f_, c_, l_, ch_, x_: eval_single_tree(
                        a_, o_, f_, c_, l_, ch_, x_, self.operators
                    )
                )(tr.arity, tr.op, tr.feat, tr.const, tr.length,
                  self.child, xm)

            (pred, v), (deriv, _) = jax.jvp(f, (Xm,), (tangent,))
        # Non-finite derivative rows invalidate the member (both paths
        # surface them as NaN/Inf in the raw derivative).
        v = v & jnp.all(jnp.isfinite(deriv), axis=-1)
        deriv = jnp.where(jnp.isfinite(deriv), deriv, 0.0)
        return ValidVector(deriv, v & valid_in)

    def __call__(self, *args):
        n = self.n
        dtype = self.trees.const.dtype
        rows, shared, valid_in = self._prep_args(args)

        tr = self.trees
        if shared:
            Xk = (
                jnp.stack([jnp.broadcast_to(jnp.atleast_1d(r), (n,))
                           for r in rows])
                if rows else jnp.zeros((1, n), dtype)
            )
            if self.fused:
                # _ad variant: constant gradients flow through a
                # cotangent-seeded backward kernel, so jax.grad through
                # the whole template eval works (constant optimization).
                from ..ops.fused_eval import fused_predict_ad

                pred, v = fused_predict_ad(
                    tr, Xk.astype(dtype), self.operators,
                    interpret=self.interpret,
                )
            else:
                pred, v = jax.vmap(
                    lambda a_, o_, f_, c_, l_, ch_: eval_single_tree(
                        a_, o_, f_, c_, l_, ch_, Xk, self.operators
                    )
                )(tr.arity, tr.op, tr.feat, tr.const, tr.length, self.child)
        else:
            # Every argument broadcasts to [M, n]: shared rows [n],
            # per-member rows [M, n], parameter columns [M, 1], scalars.
            Xm = self._member_x(rows)
            if self.fused:
                # Per-member X tiles keep composition chains like g(f(x))
                # on the fused kernel; its VJP returns d/dX row cotangents
                # so gradients flow back into the inner call's constants.
                from ..ops.fused_eval import fused_predict_ad

                pred, v = fused_predict_ad(
                    tr, Xm, self.operators, interpret=self.interpret,
                )
            else:
                pred, v = jax.vmap(
                    lambda a_, o_, f_, c_, l_, ch_, xm: eval_single_tree(
                        a_, o_, f_, c_, l_, ch_, xm, self.operators
                    )
                )(tr.arity, tr.op, tr.feat, tr.const, tr.length, self.child,
                  Xm)
        return ValidVector(pred, v & valid_in)


class _DerivCallable:
    """Result of ``D(f, argnum)``: a callable evaluating the row-wise
    partial derivative of subexpression ``f`` w.r.t. its argnum-th
    argument (1-based, matching the reference's DynamicDiff.D export,
    /root/reference/src/SymbolicRegression.jl:172)."""

    def __init__(self, f, argnum: int):
        if not isinstance(argnum, int) or argnum < 1:
            raise ValueError("D argnum must be a positive integer (1-based)")
        self.f = f
        self.argnum = argnum

    def __call__(self, *args):
        f = self.f
        if isinstance(f, ArgumentRecorder):
            f._mark_deriv(self.argnum)
            return f(*args)
        if isinstance(f, _BatchedTreeCallable):
            return f.derivative(self.argnum, *args)
        if isinstance(f, _DerivCallable):  # higher-order: D(D(f, i), j)
            raise NotImplementedError(
                "Nested D is not supported on the device evaluator; "
                "compose host-side via symbolic differentiation "
                "(ops.diff.D) instead."
            )
        deriv = getattr(f, "derivative", None)
        if deriv is not None:  # host ComposableExpression
            return deriv(self.argnum)(*args)
        raise TypeError(
            f"D does not know how to differentiate {type(f).__name__}"
        )


def D(f, argnum: int = 1) -> _DerivCallable:
    """Derivative operator for template combiners.

    ``D(V, 1)(x)`` inside a ``combine`` evaluates dV/darg1 row-wise —
    the reference's physics-template idiom (e.g. force = -D(potential,
    1)(r)). Works on device subexpression callables (fused VJP kernel or
    jvp-composable interpreter; see `_BatchedTreeCallable.derivative`)
    and on host :class:`ComposableExpression`s (symbolic, via ops.diff.D).
    Structures with D call sites run constant optimization on the
    interpreter path (`TemplateStructure.uses_deriv`).
    """
    return _DerivCallable(f, argnum)


class _BatchedParamVec:
    """Member-batched ParamVec view: ``p[i]`` is a [M, 1] column (so it
    broadcasts against both shared [n] rows and batched [M, n] data);
    ValidVector indexing gathers per row -> [M, n] (per-member when the
    index itself is member-batched)."""

    def __init__(self, data: jax.Array):  # [M, cnt]
        self.data = data

    def __getitem__(self, idx):
        if isinstance(idx, ValidVector):
            ix = jnp.clip(idx.x.astype(jnp.int32), 0, self.data.shape[1] - 1)
            if ix.ndim >= 2:  # member-dependent index [M, n]
                g = jnp.take_along_axis(self.data, ix, axis=1)
            else:             # shared index rows [n]
                g = self.data[:, ix]
            return ValidVector(g, idx.valid)
        if isinstance(idx, int):
            if not -len(self) <= idx < len(self):
                raise IndexError(
                    f"parameter index {idx} out of range [0, {len(self)})"
                )
            idx = idx % len(self)
            return self.data[:, idx:idx + 1]
        return self.data[:, idx]

    def __len__(self):
        return self.data.shape[1]

    def __iter__(self):
        return (self[i] for i in range(len(self)))


def eval_template_batch(
    trees: TreeBatch,            # [..., K, L]
    X: jax.Array,                # [F, n]
    structure: TemplateStructure,
    operators: OperatorSet,
    params: Optional[jax.Array] = None,   # [..., total_params]
    fused: bool = False,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Batched template evaluation; returns (y[..., n], valid[...]).

    The combiner runs ONCE over member-batched callables (see
    _BatchedTreeCallable) — with ``fused=True`` each shared-argument
    call site is one fused Pallas launch over the whole member batch.
    """
    K = structure.n_subexpressions
    batch_shape = trees.arity.shape[:-2]
    flat = trees.reshape(-1, K)
    M = flat.length.shape[0]
    n = X.shape[1]
    child, _, _ = tree_structure_arrays(flat, need_depth=False)  # [M, K, L, A]

    exprs = {}
    for k, key in enumerate(structure.expr_keys):
        sub = TreeBatch(
            arity=flat.arity[:, k], op=flat.op[:, k], feat=flat.feat[:, k],
            const=flat.const[:, k], length=flat.length[:, k],
        )
        exprs[key] = _BatchedTreeCallable(
            key, sub, child[:, k], structure.num_features[k], operators, n,
            fused, interpret,
        )
    xs = tuple(
        ValidVector(X[i], jnp.bool_(True)) for i in range(structure.n_variables)
    )
    if structure.has_params:
        if params is None:
            raise ValueError("Template has parameters but none were provided")
        p_flat = params.reshape(M, structure.total_params)
        pns = SimpleNamespace(**{
            key: _BatchedParamVec(
                jax.lax.slice_in_dim(p_flat, off, off + cnt, axis=1)
            )
            for key, off, cnt in zip(
                structure.param_keys, structure.param_offsets,
                structure.num_params,
            )
        })
        out = structure.combine(SimpleNamespace(**exprs), pns, xs)
    else:
        out = structure.combine(SimpleNamespace(**exprs), xs)
    if not isinstance(out, ValidVector):
        raise TemplateReturnError()
    y = jnp.broadcast_to(jnp.atleast_2d(out.x), (M, n))
    valid = jnp.broadcast_to(jnp.asarray(out.valid), (M,))
    valid = valid & jnp.all(jnp.isfinite(y), axis=-1)
    return y.reshape(*batch_shape, n), valid.reshape(batch_shape)


def parse_template_expression(
    s: str,
    structure: TemplateStructure,
    operators: OperatorSet,
) -> "HostTemplateExpression":
    """Parse the template string format back into a host expression
    (round trip of :meth:`HostTemplateExpression.string`; the analogue
    of the reference's '#N'-placeholder parse_expression,
    /root/reference/src/TemplateExpression.jl:1014+).

    Format: ``f = <expr over #1..#k>; g = <expr>; p = [v1, v2]`` —
    components separated by ``; `` (or newlines), subexpression
    arguments written ``#i``.
    """
    from ..ops.tree import parse_expression

    trees: Dict[str, object] = {}
    params = (
        np.zeros((structure.total_params,), np.float64)
        if structure.has_params else None
    )
    seen_params: set = set()
    parts = [p.strip() for p in s.replace("\n", ";").split(";") if p.strip()]
    for part in parts:
        if "=" not in part:
            raise ValueError(f"Template component missing '=': {part!r}")
        name, rhs = part.split("=", 1)
        name = name.strip().lstrip("╭├╰ ").strip()
        rhs = rhs.strip()
        if name in structure.expr_keys:
            k = structure.expr_keys.index(name)
            nf = structure.num_features[k]
            names = [f"x{i + 1}" for i in range(max(nf, 1))]
            # '#i' argument slots -> parser-friendly identifiers
            rhs_sub = re.sub(r"#(\d+)", r"x\1", rhs)
            trees[name] = parse_expression(
                rhs_sub, operators, variable_names=names
            )
        elif name in structure.param_keys:
            if not (rhs.startswith("[") and rhs.endswith("]")):
                raise ValueError(f"Parameter vector {name!r} must be [..]")
            vals = [float(v) for v in rhs[1:-1].split(",") if v.strip()]
            i = structure.param_keys.index(name)
            off = structure.param_offsets[i]
            cnt = structure.num_params[i]
            if len(vals) != cnt:
                raise ValueError(
                    f"Parameter {name!r} expects {cnt} values; got {len(vals)}"
                )
            params[off:off + cnt] = vals
            seen_params.add(name)
        else:
            raise ValueError(
                f"Unknown template component {name!r} (expressions: "
                f"{structure.expr_keys}, parameters: {structure.param_keys})"
            )
    missing = [k for k in structure.expr_keys if k not in trees]
    if missing:
        raise ValueError(f"Template string missing subexpressions: {missing}")
    if structure.has_params:
        if not seen_params:
            # No parameter components at all: leave params unset so the
            # seeding path draws fresh randn banks instead of silently
            # zeroing every parameter.
            params = None
        else:
            missing_p = [k for k in structure.param_keys if k not in seen_params]
            if missing_p:
                raise ValueError(
                    f"Template string sets {sorted(seen_params)} but is "
                    f"missing parameter vectors: {missing_p}"
                )
    return HostTemplateExpression(
        trees=trees, structure=structure, operators=operators, params=params
    )


def template_from_dict(
    d: Dict,
    structure: TemplateStructure,
    operators: OperatorSet,
) -> "HostTemplateExpression":
    """Build a host template expression from ``{key: expr}`` (+ optional
    parameter-vector entries under their own keys) — the dict analogue of
    :func:`parse_template_expression`, sharing its '#i' placeholder
    grammar and validation."""
    from ..ops.tree import Node, parse_expression

    missing = [k for k in structure.expr_keys if k not in d]
    if missing:
        raise ValueError(
            f"Template guess dict missing subexpressions: {missing} "
            f"(keys: {structure.expr_keys})"
        )
    unknown = [
        k for k in d
        if k not in structure.expr_keys and k not in structure.param_keys
    ]
    if unknown:
        raise ValueError(
            f"Template guess dict has unknown keys: {unknown} (expressions: "
            f"{structure.expr_keys}, parameters: {structure.param_keys})"
        )
    trees: Dict[str, object] = {}
    for k, key in enumerate(structure.expr_keys):
        v = d[key]
        if isinstance(v, Node):
            trees[key] = v
            continue
        names = [f"x{i + 1}" for i in range(max(structure.num_features[k], 1))]
        trees[key] = parse_expression(
            re.sub(r"#(\d+)", r"x\1", str(v)), operators, variable_names=names
        )
    params = None
    if structure.has_params and any(k in d for k in structure.param_keys):
        missing_p = [k for k in structure.param_keys if k not in d]
        if missing_p:
            raise ValueError(
                f"Template guess dict sets some parameter vectors but is "
                f"missing: {missing_p}"
            )
        params = np.concatenate([
            np.asarray(d[k], np.float64).reshape(-1)
            for k in structure.param_keys
        ])
        if params.shape[0] != structure.total_params:
            raise ValueError(
                f"Template guess parameters have {params.shape[0]} values; "
                f"expected {structure.total_params}"
            )
    return HostTemplateExpression(
        trees=trees, structure=structure, operators=operators, params=params
    )


# ---------------------------------------------------------------------------
# Host-side expression (printing / export / prediction bookkeeping)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HostTemplateExpression:
    """Decoded template member: named host subtrees + parameter values.

    The printing format mirrors the reference's multi-component string
    (reference TemplateExpression.jl:594-630): subexpression arguments
    display as ``#1..#k``, components join with ``; ``.
    """

    trees: Dict[str, "object"]          # key -> ops.tree.Node
    structure: TemplateStructure
    operators: OperatorSet
    params: Optional[np.ndarray] = None  # [total_params]

    def string(self, pretty: bool = False, precision: int = 5) -> str:
        from ..ops.tree import string_tree

        parts = []
        for k, key in enumerate(self.structure.expr_keys):
            names = [f"#{i + 1}" for i in range(self.structure.num_features[k])]
            s = string_tree(self.trees[key], variable_names=names,
                            precision=precision)
            parts.append(f"{key} = {s}")
        if self.structure.has_params and self.params is not None:
            for key, off, cnt in zip(
                self.structure.param_keys,
                self.structure.param_offsets,
                self.structure.num_params,
            ):
                vals = ", ".join(
                    f"{float(v):.{precision}g}"
                    for v in self.params[off:off + cnt]
                )
                parts.append(f"{key} = [{vals}]")
        sep = "\n" if pretty else "; "
        return sep.join(parts)

    def __repr__(self) -> str:  # pragma: no cover
        return f"HostTemplateExpression({self.string()})"

    def encode(self, max_nodes: int, dtype=np.float32):
        """Postfix-encode into a [K, max_nodes] TreeBatch (member layout)."""
        from ..ops.encoding import encode_population

        enc = encode_population(
            [self.trees[k] for k in self.structure.expr_keys],
            max_nodes, self.operators, dtype=dtype,
        )
        return enc

    def __call__(self, X: np.ndarray) -> np.ndarray:
        """Evaluate on host data X [n, F]; invalid => NaN
        (prediction semantics, reference ComposableExpression.jl:169-186)."""
        from ..ops.encoding import encode_population

        Xt = jnp.asarray(np.asarray(X).T)
        L = max(
            max(t.count_nodes() for t in self.trees.values()), 1
        )
        enc = encode_population(
            [self.trees[k] for k in self.structure.expr_keys], L, self.operators
        )
        stacked = TreeBatch(
            arity=enc.arity[None], op=enc.op[None], feat=enc.feat[None],
            const=enc.const[None], length=enc.length[None],
        )  # [1, K, L]
        p = (
            jnp.asarray(self.params, enc.const.dtype)[None]
            if self.params is not None and self.structure.total_params
            else None
        )
        y, valid = eval_template_batch(
            stacked, Xt, self.structure, self.operators, p
        )
        y = np.asarray(y[0])
        if not bool(valid[0]):
            return np.full_like(y, np.nan)
        return y
