"""Expression-family plugin layer (TPU analogue of the reference's L5,
SURVEY.md §2.5): expression specs, parametric expressions, and
template/composable expressions."""

from .composable import ComposableExpression, ParamVec, ValidVector
from .spec import ExpressionSpec, ParametricExpressionSpec, TemplateExpressionSpec
from .template import (
    TemplateStructure,
    make_template_structure,
    template_spec,
)

__all__ = [
    "ExpressionSpec",
    "ParametricExpressionSpec",
    "TemplateExpressionSpec",
    "TemplateStructure",
    "make_template_structure",
    "template_spec",
    "ComposableExpression",
    "ParamVec",
    "ValidVector",
]
