"""Expression-family plugin layer (TPU analogue of the reference's L5,
SURVEY.md §2.5): expression specs and parametric expressions."""

from .spec import ExpressionSpec, ParametricExpressionSpec

__all__ = [
    "ExpressionSpec",
    "ParametricExpressionSpec",
]
