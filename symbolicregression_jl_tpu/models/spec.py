"""Expression specifications: which expression family the search evolves.

TPU analogue of the reference's AbstractExpressionSpec layer
(/root/reference/src/ExpressionSpec.jl:5-20): a spec selects the
(expression_type, expression_options, node_type) triple. Here a spec
selects the population-tensor layout extensions (e.g. per-member
parameter banks) and the eval dispatch.

- ``ExpressionSpec``            — plain expression trees (default).
- ``ParametricExpressionSpec``  — trees with parameter leaves ``p1..pK``
  whose values form a per-member (max_parameters × num_classes) matrix,
  indexed by the dataset's ``class`` column
  (/root/reference/src/ParametricExpression.jl:35-51).
- ``TemplateExpressionSpec``    — K named subexpressions combined by a
  user structure function
  (/root/reference/src/TemplateExpression.jl:1159-1187).
"""

from __future__ import annotations

import dataclasses

__all__ = ["ExpressionSpec", "ParametricExpressionSpec", "TemplateExpressionSpec"]


@dataclasses.dataclass(frozen=True)
class ExpressionSpec:
    """Default spec: plain expression trees (src/ExpressionSpec.jl:16-20)."""


@dataclasses.dataclass(frozen=True)
class ParametricExpressionSpec(ExpressionSpec):
    """Spec for parametric expressions with per-class parameters
    (ParametricExpressionSpec, /root/reference/src/ParametricExpression.jl:203-233).

    The dataset must carry a ``class`` column in ``extra``; each member
    owns a ``(max_parameters, num_classes)`` parameter matrix. Parameter
    leaves evaluate to ``parameters[p, class[row]]``.
    """

    max_parameters: int = 2

    def __post_init__(self):
        if self.max_parameters < 1:
            raise ValueError("max_parameters must be >= 1")


@dataclasses.dataclass(frozen=True)
class TemplateExpressionSpec(ExpressionSpec):
    """Spec for template expressions (TemplateExpressionSpec,
    /root/reference/src/TemplateExpression.jl:1159-1187).

    ``structure`` is a :class:`~..models.template.TemplateStructure` —
    build it with :func:`~..models.template.template_spec` (decorator)
    or :func:`~..models.template.make_template_structure`.
    """

    structure: "object" = None  # TemplateStructure (NamedTuple, hashable)

    def __post_init__(self):
        from .template import TemplateStructure

        if not isinstance(self.structure, TemplateStructure):
            raise ValueError(
                "TemplateExpressionSpec requires structure=TemplateStructure"
            )
