"""graftlint rule catalog — JAX hazards that pytest doesn't catch.

Each rule is a function ``check(mod: ModuleAnalysis) -> Iterator[Finding]``
registered in the table-driven :data:`RULES` registry via the
:func:`rule` decorator. Adding a rule is ~20 lines: write the checker,
decorate it with id/name/summary/rationale (and an optional ``scope`` of
directory names it applies to), and it participates in the CLI, the
suppression machinery, and ``--list-rules`` automatically.

Suppression: append ``# graftlint: disable=GL003`` (or a bare
``# graftlint: disable``) to the *reported* line.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .analyzer import (
    FUNC_NODES,
    Finding,
    ModuleAnalysis,
    dotted_name,
    local_bindings,
    root_name,
    walk_pruned,
)

__all__ = ["Rule", "RULES", "rule", "run_rules"]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    rationale: str
    # Directory names the rule is limited to (None = whole tree). A file
    # is in scope when any component of its path matches.
    scope: Optional[Tuple[str, ...]]
    check: Callable[[ModuleAnalysis], Iterator[Finding]]


RULES: Dict[str, Rule] = {}


def rule(
    id: str,
    name: str,
    summary: str,
    rationale: str = "",
    scope: Optional[Sequence[str]] = None,
):
    def decorator(fn):
        RULES[id] = Rule(
            id=id,
            name=name,
            summary=summary,
            rationale=rationale,
            scope=tuple(scope) if scope else None,
            check=fn,
        )
        return fn

    return decorator


def _in_scope(path: str, scope: Optional[Tuple[str, ...]]) -> bool:
    if scope is None:
        return True
    parts = path.replace("\\", "/").split("/")
    return any(p in scope for p in parts)


def run_rules(
    mod: ModuleAnalysis, select: Optional[Set[str]] = None
) -> List[Finding]:
    """All non-suppressed findings for a module, sorted by position."""
    out: List[Finding] = []
    for r in RULES.values():
        if select is not None and r.id not in select:
            continue
        if not _in_scope(mod.path, r.scope):
            continue
        for f in r.check(mod):
            if not mod.is_suppressed(f.rule_id, f.line):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.rule_id))
    return out


def _finding(mod: ModuleAnalysis, rid: str, node: ast.AST, msg: str) -> Finding:
    return Finding(
        rule_id=rid,
        rule_name=RULES[rid].name if rid in RULES else rid,
        path=mod.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=msg,
    )


# ---------------------------------------------------------------------------
# GL001 — PRNG key reuse
# ---------------------------------------------------------------------------

# jax.random functions that CONSUME a key as their first argument.
# Constructors / key-data plumbing don't count, and neither does
# `fold_in`: deriving many streams from one base key with distinct data
# (`fold_in(key, i)` in a loop) is the canonical JAX idiom, not reuse.
_KEY_NONCONSUMING = {
    "key", "PRNGKey", "key_data", "wrap_key_data", "key_impl", "clone",
    "fold_in",
}


def _jax_random_prefixes(mod: ModuleAnalysis) -> Tuple[str, ...]:
    """Module prefixes denoting jax.random here. The bare ``random``
    prefix only counts when the module does ``from jax import random`` —
    with ``import random`` (or no import at all) it's the stdlib module
    and first arguments are not PRNG keys."""
    prefixes = ["jax.random", "jrandom", "jr"]
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module == "jax"
            and any(a.name == "random" and a.asname is None
                    for a in node.names)
        ):
            prefixes.append("random")
            break
    return tuple(prefixes)


def _random_key_call(
    call: ast.Call, prefixes: Tuple[str, ...]
) -> Optional[str]:
    """The consumed-key variable name if this is a key-consuming
    jax.random call with a plain-Name key, else None."""
    dn = dotted_name(call.func)
    if dn is None or "." not in dn:
        return None
    mod_, fn = dn.rsplit(".", 1)
    if mod_ not in prefixes or fn in _KEY_NONCONSUMING:
        return None
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


@rule(
    "GL001",
    "key-reuse",
    "jax.random key consumed more than once without a split",
    "Reusing a PRNG key yields identical 'random' draws: correlated "
    "mutations, duplicated restarts, silently degraded search. Every "
    "consumption (samplers, split, fold_in) must use a fresh key.",
)
def check_key_reuse(mod: ModuleAnalysis) -> Iterator[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[int, int, str]] = set()
    prefixes = _jax_random_prefixes(mod)

    def emit(node: ast.AST, name: str) -> None:
        key = (node.lineno, node.col_offset, name)
        if key not in seen:
            seen.add(key)
            findings.append(
                _finding(
                    mod,
                    "GL001",
                    node,
                    f"PRNG key `{name}` is consumed again without an "
                    f"intervening rebind from `jax.random.split`/`fold_in`",
                )
            )

    def reset_target(t: ast.AST, env: Dict[str, bool]) -> None:
        if isinstance(t, ast.Name):
            env[t.id] = False
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                reset_target(elt, env)
        elif isinstance(t, ast.Starred):
            reset_target(t.value, env)

    def visit_expr(e: Optional[ast.AST], env: Dict[str, bool]) -> None:
        if e is None:
            return
        # walk_pruned: nested lambda/def scopes get their own pass
        for node in walk_pruned(e):
            if isinstance(node, ast.Call):
                name = _random_key_call(node, prefixes)
                if name is not None:
                    if env.get(name, False):
                        emit(node, name)
                    env[name] = True

    def visit_stmts(stmts: Sequence[ast.stmt], env: Dict[str, bool]) -> None:
        for s in stmts:
            if isinstance(s, FUNC_NODES + (ast.ClassDef,)):
                continue  # separate scope
            if isinstance(s, ast.If):
                visit_expr(s.test, env)
                env_a, env_b = dict(env), dict(env)
                visit_stmts(s.body, env_a)
                visit_stmts(s.orelse, env_b)
                for k in set(env_a) | set(env_b):
                    env[k] = env_a.get(k, False) or env_b.get(k, False)
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                visit_expr(getattr(s, "iter", None), env)
                visit_expr(getattr(s, "test", None), env)
                # two passes: the second catches keys consumed every
                # iteration without a rebind (dedup keeps one finding)
                for _ in range(2):
                    body_env = dict(env)
                    if isinstance(s, (ast.For, ast.AsyncFor)):
                        reset_target(s.target, body_env)
                    visit_stmts(s.body, body_env)
                    env.update(body_env)
                visit_stmts(s.orelse, env)
            elif isinstance(s, ast.Try):
                visit_stmts(s.body, env)
                for h in s.handlers:
                    visit_stmts(h.body, dict(env))
                visit_stmts(s.orelse, env)
                visit_stmts(s.finalbody, env)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    visit_expr(item.context_expr, env)
                visit_stmts(s.body, env)
            else:
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        visit_expr(child, env)
                if isinstance(s, ast.Assign):
                    for t in s.targets:
                        reset_target(t, env)
                elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                    reset_target(s.target, env)

    # module body + every function body, each with a fresh environment
    visit_stmts(
        [s for s in mod.tree.body], {}
    )
    for fn in mod.functions():
        if isinstance(fn, ast.Lambda):
            visit_expr(fn.body, {})
        else:
            visit_stmts(fn.body, {})
    yield from findings


# ---------------------------------------------------------------------------
# GL002 — host RNG in device-code directories
# ---------------------------------------------------------------------------


@rule(
    "GL002",
    "host-rng",
    "Python `random` / `np.random` used in device-code directories",
    "Host RNG calls are invisible to jit, ignore the threaded "
    "jax.random keys (breaking seeded reproducibility), and bake a "
    "single host draw into the traced program as a constant.",
    scope=("evolve", "ops"),
)
def check_host_rng(mod: ModuleAnalysis) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn is None:
            continue
        if dn.startswith(("np.random.", "numpy.random.")):
            yield _finding(
                mod, "GL002", node,
                f"`{dn}` draws from the host numpy RNG; use the threaded "
                f"`jax.random` key plumbing instead",
            )
        elif dn.startswith("random.") and not dn.startswith(
            ("jax.random.", "np.random.", "numpy.random.")
        ):
            yield _finding(
                mod, "GL002", node,
                f"`{dn}` uses Python's global `random` module; use the "
                f"threaded `jax.random` key plumbing instead",
            )


# ---------------------------------------------------------------------------
# GL003 — device-scalar materialization inside traced code
# ---------------------------------------------------------------------------

_SYNC_CALLS = {
    "float", "int", "bool",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.float32", "np.float64", "np.int32", "np.int64",
    "numpy.float32", "numpy.float64", "numpy.int32", "numpy.int64",
    "jax.device_get", "device_get",
}
_SYNC_METHODS = {"item", "tolist", "to_py"}
# Calls whose result is a host scalar regardless of input (a traced
# value passed to them would already have errored) — casting it is
# noise, not a sync. Matched on the last dotted component so module
# aliases (`math`/`_math`) don't matter.
_STATIC_RESULT_FNS = {
    "len", "round", "ord", "hash", "id", "prod", "ceil", "floor", "sqrt",
}


def _is_host_literal(node: ast.AST) -> bool:
    """Expressions that are host containers by construction (list/tuple
    displays, comprehensions, or `or`-chains of those) — np.asarray on
    them is trace-time table building, not a device sync."""
    if isinstance(node, (ast.List, ast.Tuple, ast.Dict, ast.Set,
                         ast.ListComp, ast.DictComp, ast.SetComp,
                         ast.GeneratorExp)):
        return True
    if isinstance(node, ast.BoolOp):
        return all(_is_host_literal(v) for v in node.values)
    return False


@rule(
    "GL003",
    "traced-sync",
    "host materialization (`float()`/`.item()`/`np.asarray`) in a "
    "jit/vmap/scan body",
    "Materializing a traced value on the host forces a blocking "
    "device→host sync at trace time and a ConcretizationTypeError on "
    "abstract values; in the evolve hot loop a single stray `.item()` "
    "serializes the pipeline. Static Python-scalar reads (e.g. options "
    "fields) are legitimate — annotate those with "
    "`# graftlint: disable=GL003`.",
)
def check_traced_sync(mod: ModuleAnalysis) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not mod.is_traced(node):
            continue
        dn = dotted_name(node.func)
        if dn in _SYNC_CALLS:
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) or _is_host_literal(arg):
                continue  # float("nan"), np.asarray([...]): host values
            if isinstance(arg, ast.Call):
                adn = dotted_name(arg.func)
                if adn and adn.rsplit(".", 1)[-1] in _STATIC_RESULT_FNS:
                    continue  # float(len(xs)), int(math.ceil(...)): host
            yield _finding(
                mod, "GL003", node,
                f"`{dn}(...)` inside a traced body materializes its "
                f"argument on the host (device sync / concretization "
                f"error on traced values)",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and not node.args
        ):
            yield _finding(
                mod, "GL003", node,
                f"`.{node.func.attr}()` inside a traced body forces a "
                f"blocking device→host transfer",
            )


# ---------------------------------------------------------------------------
# GL004 — recompilation hazards
# ---------------------------------------------------------------------------


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call node if ``node`` is ``jax.jit(...)`` / ``jit(...)`` /
    ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn in ("jax.jit", "jit"):
        return node
    if dn in ("partial", "functools.partial") and node.args:
        if dotted_name(node.args[0]) in ("jax.jit", "jit"):
            return node
    return None


def _static_positions(jit: ast.Call) -> Tuple[List[int], List[str]]:
    nums: List[int] = []
    names: List[str] = []
    for kw in jit.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.append(e.value)
    return nums, names


_UNHASHABLE_VALUE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                     ast.DictComp)
_ARRAY_CTORS = {
    "np.array", "np.asarray", "numpy.array", "numpy.asarray",
    "jnp.array", "jnp.asarray", "jax.numpy.array", "jax.numpy.asarray",
    "np.zeros", "np.ones", "jnp.zeros", "jnp.ones",
}


def _is_unhashable_arg(arg: ast.AST) -> bool:
    if isinstance(arg, _UNHASHABLE_VALUE):
        return True
    if isinstance(arg, ast.Call) and dotted_name(arg.func) in _ARRAY_CTORS:
        return True
    return False


@rule(
    "GL004",
    "recompile-hazard",
    "jit wrapper rebuilt per call/iteration, or non-hashable static arg",
    "A `jax.jit` wrapper built inside a loop (or invoked inline as "
    "`jax.jit(f)(x)`) is a fresh cache every time — each call retraces "
    "and recompiles. Non-hashable values (lists, dicts, arrays) passed "
    "for `static_argnums` positions raise or, worse, force a recompile "
    "per distinct object.",
)
def check_recompile_hazard(mod: ModuleAnalysis) -> Iterator[Finding]:
    # (a) jax.jit(f)(...) — wrapper discarded after one call
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _jit_call(node.func) is not None:
            yield _finding(
                mod, "GL004", node,
                "`jax.jit(...)` invoked inline builds a fresh wrapper "
                "(and cache) per call; bind the jitted function once "
                "outside the call site",
            )

    # (b) jit of a lambda / locally-defined function inside a loop body
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for node in ast.walk(loop):
            if node is loop:
                continue
            jc = _jit_call(node)
            if jc is not None and not (
                isinstance(mod.parents.get(jc), ast.Call)
                and mod.parents[jc].func is jc
            ):
                yield _finding(
                    mod, "GL004", node,
                    "jit wrapper constructed inside a loop body — the "
                    "compilation cache is dropped and rebuilt every "
                    "iteration; hoist the `jax.jit` call out of the loop",
                )
                break  # one finding per loop is enough signal

    # (c) non-hashable literals passed at static positions of a wrapper
    # jitted in the same module: g = jax.jit(f, static_argnums=(1,));
    # ... g(x, [1, 2]) ...
    static_of: Dict[str, Tuple[List[int], List[str]]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            jc = _jit_call(node.value)
            if isinstance(tgt, ast.Name) and jc is not None:
                nums, names = _static_positions(jc)
                if nums or names:
                    static_of[tgt.id] = (nums, names)
    if static_of:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname not in static_of:
                continue
            nums, names = static_of[fname]
            for i in nums:
                if i < len(node.args) and _is_unhashable_arg(node.args[i]):
                    yield _finding(
                        mod, "GL004", node.args[i],
                        f"non-hashable value passed for static_argnums "
                        f"position {i} of `{fname}` — static arguments "
                        f"must be hashable (tuples, not lists/arrays)",
                    )
            for kw in node.keywords:
                if kw.arg in names and _is_unhashable_arg(kw.value):
                    yield _finding(
                        mod, "GL004", kw.value,
                        f"non-hashable value passed for static argname "
                        f"`{kw.arg}` of `{fname}` — static arguments "
                        f"must be hashable (tuples, not lists/arrays)",
                    )


# ---------------------------------------------------------------------------
# GL005 — mutation of captured state inside traced bodies
# ---------------------------------------------------------------------------

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "add", "discard", "setdefault", "popitem", "sort", "reverse",
}


@rule(
    "GL005",
    "captured-mutation",
    "mutation of closure/parameter state inside a jit/vmap/scan body",
    "Side effects on captured Python state execute ONCE at trace time, "
    "then never again: counters stay stale, accumulator lists hold "
    "tracers, and retraces silently re-run the mutation. Traced code "
    "must be functionally pure; thread state through carries/returns.",
)
def check_captured_mutation(mod: ModuleAnalysis) -> Iterator[Finding]:
    for fn in mod.functions():
        if fn not in mod.traced:
            continue
        # Pallas kernels mutate Ref parameters by design — that IS the
        # programming model; skip them (and their nested helpers).
        in_pallas = mod.in_pallas_kernel(fn)
        bound = local_bindings(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        # nested defs are separate scopes with their own iteration
        body = [s for s in body if not isinstance(s, FUNC_NODES + (ast.ClassDef,))]

        nonlocals: Set[str] = set()
        for stmt in body:
            for node in walk_pruned(stmt):
                if isinstance(node, (ast.Nonlocal, ast.Global)):
                    nonlocals.update(node.names)

        def is_foreign(base: Optional[str]) -> bool:
            # parameters count: mutating an argument mutates caller state
            if base is None:
                return False
            if isinstance(fn, ast.Lambda):
                params = {a.arg for a in fn.args.args}
            else:
                params = {
                    a.arg
                    for a in (
                        list(fn.args.posonlyargs)
                        + list(fn.args.args)
                        + list(fn.args.kwonlyargs)
                    )
                }
            return base in params or base not in bound

        for stmt in body:
            for node in walk_pruned(stmt):
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)):
                            if in_pallas and isinstance(t, ast.Subscript):
                                continue  # Ref stores are the idiom
                            base = root_name(t)
                            if is_foreign(base):
                                kind = (
                                    "subscript"
                                    if isinstance(t, ast.Subscript)
                                    else "attribute"
                                )
                                yield _finding(
                                    mod, "GL005", node,
                                    f"{kind} store on `{base}` mutates "
                                    f"captured state inside a traced body "
                                    f"(runs once at trace time only)",
                                )
                        elif isinstance(t, ast.Name) and t.id in nonlocals:
                            yield _finding(
                                mod, "GL005", node,
                                f"write to {'nonlocal/global'} `{t.id}` "
                                f"inside a traced body runs once at trace "
                                f"time only",
                            )
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATING_METHODS
                ):
                    base = root_name(node.func.value)
                    if base in mod.imported_names:
                        continue  # jax.lax.sort etc.: library calls
                    if is_foreign(base):
                        yield _finding(
                            mod, "GL005", node,
                            f"`{base}.{node.func.attr}(...)` mutates "
                            f"captured state inside a traced body (runs "
                            f"once at trace time only)",
                        )


# ---------------------------------------------------------------------------
# GL006 — debug prints / callbacks in non-debug paths
# ---------------------------------------------------------------------------

_DEBUG_CALLS = {
    "jax.debug.print", "debug.print",
    "jax.debug.callback", "debug.callback",
    "jax.debug.breakpoint", "debug.breakpoint",
    "jax.debug.visualize_array_sharding",
}


@rule(
    "GL006",
    "stray-debug",
    "`jax.debug.print`/`callback` outside a guarded debug path",
    "jax.debug hooks insert host callbacks into the compiled program: "
    "they serialize dispatch, defeat donation/fusion, and on TPU stall "
    "the whole step on the host round-trip. They belong behind an "
    "explicit debug flag or in *debug* modules only.",
)
def check_stray_debug(mod: ModuleAnalysis) -> Iterator[Finding]:
    import os

    base = os.path.basename(mod.path).lower()
    if "debug" in base:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn not in _DEBUG_CALLS:
            continue
        # allowed when an enclosing function or guarding `if` mentions
        # debug (e.g. `if options.debug_checks:`)
        allowed = False
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if "debug" in cur.name.lower():
                    allowed = True
                    break
            if isinstance(cur, ast.If):
                try:
                    test_src = ast.unparse(cur.test)
                except Exception:  # pragma: no cover
                    test_src = ""
                if "debug" in test_src.lower():
                    allowed = True
                    break
            cur = mod.parents.get(cur)
        if not allowed:
            yield _finding(
                mod, "GL006", node,
                f"`{dn}` in a non-debug path inserts a host callback "
                f"into the compiled program; guard it behind a debug "
                f"flag or move it to a debug module",
            )


# ---------------------------------------------------------------------------
# GL007 — device/IO work inside a signal handler (graftshield)
# ---------------------------------------------------------------------------

# Dotted-name prefixes that mean "this handler touches the device, the
# filesystem, or heavyweight serialization" — none of which is
# async-signal-safe, and a jax call from a handler that interrupted the
# runtime can deadlock the process it was meant to preempt gracefully.
_SIGNAL_HAZARD_PREFIXES = (
    "jax.", "jnp.", "np.", "numpy.", "pickle.", "json.",
)
_SIGNAL_HAZARD_NAMES = {
    "open", "float", "int", "device_get", "block_until_ready",
    "save_search_state", "load_search_state",
}


def _signal_handler_names(mod: ModuleAnalysis) -> Set[str]:
    """Function/method names registered via `signal.signal(sig, fn)`."""
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func) != "signal.signal" or len(node.args) < 2:
            continue
        handler = node.args[1]
        if isinstance(handler, ast.Name):
            out.add(handler.id)
        elif isinstance(handler, ast.Attribute):
            out.add(handler.attr)
    return out


@rule(
    "GL007",
    "signal-unsafe-handler",
    "device sync / IO / serialization inside a signal handler",
    "A signal handler runs at an arbitrary bytecode boundary — possibly "
    "inside the XLA runtime or mid-checkpoint. jax calls, device syncs, "
    "pickling, or file writes from it can deadlock or corrupt the very "
    "state graftshield exists to save. Handlers must only set flags "
    "(threading.Event / attributes); the emergency checkpoint happens "
    "later, at the iteration boundary, on the main thread "
    "(shield/signals.py is the reference implementation).",
)
def check_signal_unsafe_handler(mod: ModuleAnalysis) -> Iterator[Finding]:
    handlers = _signal_handler_names(mod)
    if not handlers:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, FUNC_NODES) or node.name not in handlers:
            continue
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            dn = dotted_name(inner.func)
            if dn is None:
                continue
            last = dn.rsplit(".", 1)[-1]
            if dn.startswith(_SIGNAL_HAZARD_PREFIXES) or (
                dn in _SIGNAL_HAZARD_NAMES or last in _SIGNAL_HAZARD_NAMES
            ):
                yield _finding(
                    mod, "GL007", inner,
                    f"`{dn}` inside signal handler `{node.name}` — "
                    f"handlers must only set flags; do the work at the "
                    f"next iteration boundary",
                )


# ---------------------------------------------------------------------------
# GL008 — host calls / axis-less collectives inside shard_map bodies
# ---------------------------------------------------------------------------

# Host-side calls that are poison inside a per-device shard_map body:
# they force a device→host sync (or host I/O) from EVERY shard's
# program, serializing the mesh (GL003 covers the generic traced-sync
# cases like float(); this table is the shard_map-specific surface).
_SMAP_HOST_CALLS = {
    "jax.device_get", "device_get", "jax.block_until_ready",
    "block_until_ready",
    "open", "print",
    "np.save", "numpy.save", "np.load", "numpy.load",
    "json.dump", "json.dumps", "pickle.dump", "pickle.dumps",
}
_SMAP_HOST_METHODS = {"item", "tolist", "to_py"}

# jax.lax collectives that REQUIRE a named axis inside shard_map; the
# minimum positional arity that carries it (axis_name is the 2nd
# positional for all of these except axis_index, where it is the 1st).
_COLLECTIVE_MIN_ARGS = {
    "psum": 2, "pmean": 2, "pmax": 2, "pmin": 2,
    "all_gather": 2, "all_to_all": 2, "ppermute": 2, "pshuffle": 2,
    "psum_scatter": 2, "axis_index": 1,
}
_COLLECTIVE_PREFIXES = ("jax.lax", "lax")


@rule(
    "GL008",
    "shard-map-hazard",
    "host-side call or axis-less collective inside a shard_map body",
    "A shard_map body is one per-device program: a host call inside it "
    "(`jax.device_get`, `.item()`, file/print I/O) syncs every shard "
    "through the host and serializes the mesh, and a collective "
    "without its named axis (`psum(x)` instead of `psum(x, 'island')`) "
    "either fails to lower or silently reduces over nothing. "
    "Collectives inside shard_map must name the mesh axis they reduce "
    "over; host work belongs outside, at the iteration boundary "
    "(mesh/engine.py is the reference implementation).",
)
def check_shard_map_hazard(mod: ModuleAnalysis) -> Iterator[Finding]:
    if not mod.shardmap:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = mod.enclosing_function(node)
        if fn is None or not mod.in_shard_map_body(fn):
            continue
        dn = dotted_name(node.func)
        if dn in _SMAP_HOST_CALLS:
            yield _finding(
                mod, "GL008", node,
                f"`{dn}(...)` inside a shard_map body forces a per-"
                f"shard host sync / host I/O; move it outside the "
                f"mapped region",
            )
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SMAP_HOST_METHODS
            and not node.args
        ):
            yield _finding(
                mod, "GL008", node,
                f"`.{node.func.attr}()` inside a shard_map body forces "
                f"a per-shard host sync; move it outside the mapped "
                f"region",
            )
            continue
        if dn is None or "." not in dn:
            continue
        prefix, last = dn.rsplit(".", 1)
        if prefix not in _COLLECTIVE_PREFIXES:
            continue
        min_args = _COLLECTIVE_MIN_ARGS.get(last)
        if min_args is None:
            continue
        has_axis = len(node.args) >= min_args or any(
            kw.arg == "axis_name" for kw in node.keywords
        )
        if not has_axis:
            yield _finding(
                mod, "GL008", node,
                f"`{dn}` inside a shard_map body without a named axis — "
                f"pass the mesh axis it reduces over (e.g. "
                f"`{dn}(x, 'island')`)",
            )
