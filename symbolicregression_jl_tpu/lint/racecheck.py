"""graftwarden runtime side — lock-order auditing + race replay.

The static analyzer (:mod:`.concurrency`, GL010) derives the lock
acquisition graph on paper; this module checks it against *execution*:

- :class:`InstrumentedLock` wraps any ``threading.Lock/RLock`` behind
  the same acquire/release/context-manager surface (including the
  private ``_release_save``/``_acquire_restore``/``_is_owned`` protocol
  ``threading.Condition`` uses, so ``SearchServer._cond`` keeps working
  over the wrapped lock).
- :class:`LockRecorder` keeps a per-thread held-lock stack and the
  global set of observed acquisition edges; with ``assert_order=True``
  every acquisition is checked against the blessed
  :mod:`.lock_order` manifest *before* the inner lock is taken, raising
  :class:`LockOrderViolation` (an ``AssertionError``, matching
  lint/runtime.py's debug_checks tier) on an inversion.
- :class:`RacePlan` injects deterministic context-switch windows at
  named lock boundaries. Activate via :func:`install_race_plan` or the
  ``SR_RACE_PLAN`` env var (JSON, mirroring ``SR_FAULT_PLAN`` /
  ``SR_SERVE_FAULT_PLAN`` in shield/faults.py)::

      {"windows": [{"lock": "RequestJournal._lock", "op": "acquire",
                    "caller": "submit", "nth": 1, "pause_s": 0.8}]}

  The ``nth`` matching acquire (or release) of the named lock whose
  thread stack contains ``caller`` pauses for ``pause_s`` seconds —
  long enough for the interfering operation to land in the window. Each
  window fires once and exposes an ``entered`` event scenarios wait on,
  so the interleaving is *scheduled*, not raced.

- :func:`instrument_server` swaps every serve/shield lock of a
  :class:`~..serve.server.SearchServer` for instrumented wrappers
  (``SearchServer(..., debug_checks=True)`` or ``SR_RACECHECK=1`` does
  this at construction).
- :data:`SCENARIOS` replays the three races PR 6 fixed by hand, each as
  current-code-passes / reverted-shim-fails (tools/race_smoke.py, and
  pinned in tests/test_racecheck.py).

docs/LINT.md ("Concurrency rules") documents the workflow.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import lock_order

__all__ = [
    "InstrumentedLock",
    "LockOrderViolation",
    "LockRecorder",
    "RacePlan",
    "RaceWindow",
    "SCENARIOS",
    "active_race_plan",
    "clear_race_plan",
    "global_recorder",
    "install_race_plan",
    "instrument_server",
    "replay_scenario",
]


class LockOrderViolation(AssertionError):
    """An actual acquisition inverted the blessed lock order."""


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


class LockRecorder:
    """Per-thread held-lock stacks + the observed global edge set."""

    def __init__(self) -> None:
        self._tls = threading.local()
        self._meta = threading.Lock()  # guards .edges only
        self.edges: Dict[tuple, int] = {}
        self.violations: List[str] = []

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> List[str]:
        """This thread's held canonical lock names, outermost first."""
        return list(self._stack())

    def before_acquire(self, name: str, assert_order: bool) -> None:
        """Record (and optionally assert) the edges this acquisition
        creates. Called BEFORE the inner lock is taken, so a raised
        violation never leaves the lock held."""
        stack = self._stack()
        for h in stack:
            if h == name:
                continue  # RLock reentrancy
            with self._meta:
                self.edges[(h, name)] = self.edges.get((h, name), 0) + 1
            if assert_order and lock_order.violates(h, name):
                msg = (
                    f"lock-order inversion: acquiring {name!r} while "
                    f"holding {h!r} (thread {threading.current_thread().name};"
                    f" blessed order in lint/lock_order.py sanctions "
                    f"{name!r} before {h!r})"
                )
                with self._meta:
                    self.violations.append(msg)
                raise LockOrderViolation(msg)

    def after_acquire(self, name: str) -> None:
        self._stack().append(name)

    def after_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return


_GLOBAL_RECORDER = LockRecorder()


def global_recorder() -> LockRecorder:
    return _GLOBAL_RECORDER


# ---------------------------------------------------------------------------
# deterministic context-switch windows
# ---------------------------------------------------------------------------


class RaceWindow:
    """One scheduled pause at a named lock boundary."""

    def __init__(self, lock: str, op: str = "acquire",
                 caller: Optional[str] = None, nth: int = 1,
                 pause_s: float = 0.5) -> None:
        if op not in ("acquire", "release"):
            raise ValueError(f"window op must be acquire|release: {op!r}")
        self.lock = lock
        self.op = op
        self.caller = caller
        self.nth = int(nth)
        self.pause_s = float(pause_s)
        self.entered = threading.Event()  # set when the pause begins
        self._count = 0
        self._fired = False

    def to_dict(self) -> Dict[str, Any]:
        return {"lock": self.lock, "op": self.op, "caller": self.caller,
                "nth": self.nth, "pause_s": self.pause_s}


def _caller_in_stack(name: str) -> bool:
    f = sys._getframe(2)
    while f is not None:
        if f.f_code.co_name == name:
            return True
        f = f.f_back
    return False


class RacePlan:
    """A set of one-shot :class:`RaceWindow` pauses."""

    def __init__(self, windows: Sequence[RaceWindow] = ()) -> None:
        self.windows = list(windows)
        self._meta = threading.Lock()

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RacePlan":
        return cls([RaceWindow(**w) for w in d.get("windows", ())])

    @classmethod
    def from_json(cls, s: str) -> "RacePlan":
        return cls.from_dict(json.loads(s))

    def window(self, lock: str, op: str = "acquire") -> Optional[RaceWindow]:
        for w in self.windows:
            if w.lock == lock and w.op == op:
                return w
        return None

    def maybe_pause(self, lock: str, op: str) -> None:
        for w in self.windows:
            if w.lock != lock or w.op != op:
                continue
            with self._meta:
                if w._fired:
                    continue
                if w.caller is not None and not _caller_in_stack(w.caller):
                    continue
                w._count += 1
                if w._count != w.nth:
                    continue
                w._fired = True
            w.entered.set()
            time.sleep(w.pause_s)


_ACTIVE_PLAN: Optional[RacePlan] = None
_ENV_PLAN: Optional[tuple] = None  # (env string, parsed plan)


def install_race_plan(plan: RacePlan) -> RacePlan:
    global _ACTIVE_PLAN
    _ACTIVE_PLAN = plan
    return plan


def clear_race_plan() -> None:
    global _ACTIVE_PLAN, _ENV_PLAN
    _ACTIVE_PLAN = None
    _ENV_PLAN = None


def active_race_plan() -> Optional[RacePlan]:
    """The installed plan, else one parsed from ``SR_RACE_PLAN`` (JSON)
    if set, else None. The env parse is cached on the raw string so the
    windows' one-shot state survives repeated lookups."""
    global _ENV_PLAN
    if _ACTIVE_PLAN is not None:
        return _ACTIVE_PLAN
    env = os.environ.get("SR_RACE_PLAN")
    if not env:
        return None
    if _ENV_PLAN is not None and _ENV_PLAN[0] == env:
        return _ENV_PLAN[1]
    plan = RacePlan.from_json(env)
    _ENV_PLAN = (env, plan)
    return plan


# ---------------------------------------------------------------------------
# the instrumented lock
# ---------------------------------------------------------------------------


class InstrumentedLock:
    """A named wrapper over a ``Lock``/``RLock`` that feeds the
    recorder, honors the active race plan, and forwards the Condition
    lock protocol so ``threading.Condition(wrapped)`` works."""

    def __init__(self, name: str, inner=None, *,
                 recorder: Optional[LockRecorder] = None,
                 assert_order: bool = True) -> None:
        self.name = name
        self.inner = inner if inner is not None else threading.RLock()
        self.recorder = recorder or _GLOBAL_RECORDER
        self.assert_order = assert_order

    # -- plan hook -----------------------------------------------------
    def _pause(self, op: str) -> None:
        plan = active_race_plan()
        if plan is not None:
            plan.maybe_pause(self.name, op)

    # -- lock surface --------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._pause("acquire")
        self.recorder.before_acquire(self.name, self.assert_order)
        got = self.inner.acquire(blocking, timeout)
        if got:
            self.recorder.after_acquire(self.name)
        return got

    def release(self) -> None:
        self.inner.release()
        self.recorder.after_release(self.name)
        self._pause("release")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition interop (threading.Condition probes these) ----------
    def _release_save(self):
        """Full release for Condition.wait: pop every reentrant hold of
        this lock from the recorder stack, remembering the depth."""
        stack = self.recorder._stack()
        n = stack.count(self.name)
        for _ in range(n):
            self.recorder.after_release(self.name)
        return (self.inner._release_save(), n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        self.inner._acquire_restore(state)
        # no order assert: Condition.wait re-acquiring its own lock is
        # the sanctioned wake-up path, not a new nesting decision
        for _ in range(n):
            self.recorder.after_acquire(self.name)

    def _is_owned(self) -> bool:
        return self.inner._is_owned()

    def __repr__(self) -> str:
        return f"InstrumentedLock({self.name!r}, {self.inner!r})"


def _wrap(obj: Any, attr: str, name: str, recorder: LockRecorder,
          assert_order: bool) -> None:
    cur = getattr(obj, attr, None)
    if cur is None or isinstance(cur, InstrumentedLock):
        return
    setattr(obj, attr, InstrumentedLock(
        name, cur, recorder=recorder, assert_order=assert_order))


def instrument_server(server, assert_order: bool = True) -> LockRecorder:
    """Swap every serve/shield lock of a SearchServer for instrumented
    wrappers (idempotent). Returns the recorder. Canonical names match
    lint/lock_order.py's MANIFEST_LOCKS."""
    rec = _GLOBAL_RECORDER
    _wrap(server, "_lock", "SearchServer._lock", rec, assert_order)
    # _cond must be a Condition OVER the wrapped lock (same aliasing as
    # the real fabric) — rebuild it if _lock was just wrapped
    if not isinstance(getattr(server._cond, "_lock", None),
                      InstrumentedLock):
        server._cond = threading.Condition(server._lock)
    _wrap(server.admission, "_lock", "AdmissionController._lock",
          rec, assert_order)
    _wrap(server.journal, "_lock", "RequestJournal._lock",
          rec, assert_order)
    _wrap(server.log, "_lock", "ServeLog._lock", rec, assert_order)
    _wrap(server.cache, "_lock", "ExecutableCache._lock",
          rec, assert_order)
    if getattr(server, "metrics", None) is not None:
        _wrap(server.metrics, "_state_lock", "MetricsServer._state_lock",
              rec, assert_order)
    from ..shield import signals as _signals

    _wrap(_signals._STATE, "lock", "_SharedSignalState.lock",
          rec, assert_order)
    return rec


# ---------------------------------------------------------------------------
# the three PR-6 races, replayed deterministically
# ---------------------------------------------------------------------------
#
# Each scenario returns {"name", "ok", "detail"...}: ok=True means the
# CURRENT code held its invariant under the scheduled interleaving.
# shim=True swaps in a minimal revert of the historical fix — the same
# plan must then flip ok to False, proving the window actually lands on
# the fixed line (a replay that passes either way pins nothing).


def _mini_problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    return X, y


_FAST_OPTIONS = dict(
    binary_operators=["+", "*"], unary_operators=[], maxsize=8,
    populations=2, population_size=8, ncycles_per_iteration=2,
    tournament_selection_n=4, optimizer_probability=0.0,
)


def _set_plan(plan_dict: Dict[str, Any]) -> RacePlan:
    os.environ["SR_RACE_PLAN"] = json.dumps(plan_dict)
    clear_race_plan()
    plan = active_race_plan()
    assert plan is not None
    return plan


def _clear_plan_env() -> None:
    os.environ.pop("SR_RACE_PLAN", None)
    clear_race_plan()


def _scenario_cancel_vs_submit(root: str, shim: bool) -> Dict[str, Any]:
    """PR 6 round: a cancel racing submit's UNLOCKED journal append.

    The fix: cancel() defers its journal write until the submit record
    is durable (rec.journaled), and submit's publish step finalizes a
    deferred cancel — so the journal can never order `cancel` before
    its `submit` (replay drops lifecycle records preceding their
    submit, resurrecting the request). The window pauses submit at the
    journal-lock boundary with the record still un-journaled; the
    cancel lands inside that window.
    """
    from ..serve import server as _srvmod
    from ..serve.server import SearchServer

    plan = _set_plan({"windows": [{
        "lock": "RequestJournal._lock", "op": "acquire",
        "caller": "submit", "nth": 1, "pause_s": 1.5,
    }]})
    window = plan.windows[0]
    orig_cancel = SearchServer.cancel
    try:
        if shim:
            def _old_cancel(self, request_id, reason="cancelled"):
                # pre-fix behavior: journal the cancel IMMEDIATELY, no
                # journaled/deferred-finalize handshake with submit
                with self._lock:
                    rec = self._records.get(request_id)
                    if rec is None:
                        raise KeyError(request_id)
                    if rec.state in _srvmod._TERMINAL:
                        return False
                    rec.cancel(reason)
                    finalize = rec.state == "queued"
                    if finalize:
                        rec.state = "cancelled"
                        rec.finished_t = time.time()
                        self.admission.release(rec.request.bucket)
                        rec.cancel_event.clear()
                if finalize:
                    self._journal_cancel(rec, where="queued")
                return True

            SearchServer.cancel = _old_cancel

        X, y = _mini_problem()
        srv = SearchServer(root, capacity=4, workers=0,
                           debug_checks=True)
        err = None
        rid = "race1"

        def _submit():
            nonlocal err
            try:
                srv.submit(X, y, options=dict(_FAST_OPTIONS),
                           niterations=1, request_id=rid)
            except BaseException as e:  # surfaced in detail
                err = e

        t = threading.Thread(target=_submit, name="race1-submit")
        t.start()
        # deterministic: wait until submit is INSIDE the journal-append
        # window (record registered, not yet durable), then cancel
        if not window.entered.wait(timeout=10.0):
            t.join(timeout=5.0)
            return {"name": "cancel_vs_submit", "ok": False,
                    "detail": "race window never entered"}
        srv.cancel(rid)
        t.join(timeout=10.0)
        if err is not None:
            return {"name": "cancel_vs_submit", "ok": False,
                    "detail": f"submit raised: {err!r}"}

        recs, _ = srv.journal.replay()
        seqs = {}
        for r in recs:
            key = (r["event"], r["request_id"])
            seqs.setdefault(key, r["seq"])
        submit_seq = seqs.get(("submit", rid))
        cancel_seq = seqs.get(("cancel", rid))
        ordered = (submit_seq is not None and cancel_seq is not None
                   and submit_seq < cancel_seq)

        # the authoritative probe: a restarted server must see the
        # request as terminally cancelled, not resurrect it as queued
        srv2 = SearchServer(root, capacity=4, workers=0)
        state = srv2.poll(rid)["state"]
        ok = ordered and state == "cancelled"
        return {"name": "cancel_vs_submit", "ok": ok,
                "detail": {"submit_seq": submit_seq,
                           "cancel_seq": cancel_seq,
                           "replayed_state": state}}
    finally:
        SearchServer.cancel = orig_cancel
        _clear_plan_env()


def _scenario_cancel_overlapping_preemption(root: str,
                                            shim: bool) -> Dict[str, Any]:
    """PR 6 round: a client cancel landing in the preemption window.

    The fix: a terminal cancel OVERRIDES a pending "preempted" reason
    (_RequestRecord.cancel), and the requeue path re-checks the reason
    under the lock — otherwise the requeue resurrects a cancelled
    request, which later completes as "done". The window pauses the
    worker at its requeue-lock boundary; the client cancel lands inside
    it.
    """
    from ..serve import server as _srvmod
    from ..serve.server import SearchServer, _RequestRecord

    plan = _set_plan({"windows": [{
        "lock": "SearchServer._lock", "op": "acquire",
        "caller": "_run_request", "nth": 1, "pause_s": 2.0,
    }]})
    window = plan.windows[0]
    orig_cancel = _RequestRecord.cancel
    try:
        if shim:
            def _old_rec_cancel(self, reason="cancelled"):
                # pre-fix behavior: first reason sticks, so "preempted"
                # can never be overridden by a terminal client cancel
                if self.cancel_reason is None:
                    self.cancel_reason = reason
                self.cancel_event.set()

            _RequestRecord.cancel = _old_rec_cancel

        X, y = _mini_problem()
        srv = SearchServer(root, capacity=4, workers=1,
                           debug_checks=True).start()
        rid = srv.submit(X, y, options=dict(_FAST_OPTIONS),
                         niterations=50, seed=0)
        deadline = time.monotonic() + 30.0
        while (srv.poll(rid)["state"] != "running"
               and time.monotonic() < deadline):
            time.sleep(0.01)
        if srv.poll(rid)["state"] != "running":
            srv.stop(drain=False, timeout=5.0)
            return {"name": "cancel_overlapping_preemption", "ok": False,
                    "detail": "request never started"}

        # preempt (not drain): the worker exits its search at the next
        # iteration boundary and walks into the requeue window
        stopper = threading.Thread(
            target=lambda: srv.stop(drain=False, timeout=30.0),
            name="race2-stop")
        stopper.start()
        if not window.entered.wait(timeout=30.0):
            stopper.join(timeout=30.0)
            return {"name": "cancel_overlapping_preemption", "ok": False,
                    "detail": "race window never entered"}
        # the terminal cancel lands while the worker is parked at the
        # requeue boundary, preemption already decided
        try:
            srv.cancel(rid)
        except KeyError:
            pass
        stopper.join(timeout=30.0)
        snap = srv.poll(rid)
        ok = (snap["state"] == "cancelled"
              and snap["cancel_reason"] == "cancelled")
        return {"name": "cancel_overlapping_preemption", "ok": ok,
                "detail": {"state": snap["state"],
                           "cancel_reason": snap["cancel_reason"]}}
    finally:
        _RequestRecord.cancel = orig_cancel
        _clear_plan_env()


def _scenario_stale_guard_restart(root: str, shim: bool) -> Dict[str, Any]:
    """PR 6 round: restart after a SIGTERM-drained pool.

    A SIGTERM kills the workers without stop() running, leaving the
    installed PreemptionGuard's shared preempt flag SET. The fix:
    start() detaches the stale guard before attaching a fresh one
    (refcount to 0 clears the flag) — otherwise the new workers observe
    the old signal and exit immediately, and the submitted request
    stays queued forever.
    """
    import signal as _signal

    from ..serve.server import SearchServer
    from ..shield.signals import PreemptionGuard

    # plan kept for uniformity: the pause marks the restart boundary in
    # the recorder timeline (no cross-thread interleaving needed here —
    # the race is stale state, not a window)
    _set_plan({"windows": [{
        "lock": "_SharedSignalState.lock", "op": "acquire",
        "caller": "start", "nth": 1, "pause_s": 0.05,
    }]})
    orig_start = SearchServer.start
    try:
        if shim:
            def _old_start(self):
                with self._lock:
                    self._threads = [
                        t for t in self._threads if t.is_alive()]
                    if self._threads:
                        return self
                    self._stopping = False
                    self._preempting = False
                    # pre-fix behavior: keep whatever guard is already
                    # attached — a SIGTERM-drained pool leaves its
                    # preempt flag set for the new workers
                    if self._guard is None:
                        self._guard = PreemptionGuard().install()
                    for i in range(max(self.workers, 1)):
                        t = threading.Thread(
                            target=self._worker_loop,
                            name=f"graftserve-worker-{i}", daemon=True)
                        t.start()
                        self._threads.append(t)
                if self.metrics is not None and not self.metrics.running:
                    self.metrics.start()
                return self

            SearchServer.start = _old_start

        X, y = _mini_problem()
        srv = SearchServer(root, capacity=4, workers=1,
                           debug_checks=True)
        (orig_start if shim else SearchServer.start)(srv)
        # simulated preemption notice: the guard's handler sets the
        # shared flag; idle workers drain and die WITHOUT stop()
        os.kill(os.getpid(), _signal.SIGTERM)
        deadline = time.monotonic() + 15.0
        while (any(t.is_alive() for t in srv._threads)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        if any(t.is_alive() for t in srv._threads):
            srv.stop(drain=False, timeout=5.0)
            return {"name": "stale_guard_restart", "ok": False,
                    "detail": "workers survived SIGTERM drain"}

        srv.start()  # the restart under test (shimmed or fixed)
        rid = srv.submit(X, y, options=dict(_FAST_OPTIONS),
                         niterations=1, seed=0)
        snap = srv.wait(rid, timeout=60.0)
        srv.stop(drain=False, timeout=15.0)
        ok = snap["state"] == "done"
        return {"name": "stale_guard_restart", "ok": ok,
                "detail": {"state": snap["state"]}}
    finally:
        SearchServer.start = orig_start
        _clear_plan_env()


SCENARIOS: Dict[str, Callable[[str, bool], Dict[str, Any]]] = {
    "cancel_vs_submit": _scenario_cancel_vs_submit,
    "cancel_overlapping_preemption": _scenario_cancel_overlapping_preemption,
    "stale_guard_restart": _scenario_stale_guard_restart,
}


def replay_scenario(name: str, root: str, shim: bool = False
                    ) -> Dict[str, Any]:
    """Replay one historical race under its SR_RACE_PLAN schedule.
    ``shim=True`` swaps in the pre-fix behavior (the result's ``ok``
    must then be False — the replay detects the reverted bug)."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}")
    return fn(root, shim)
