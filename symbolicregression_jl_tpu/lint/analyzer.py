"""Shared AST analysis infrastructure for graftlint.

The rules in :mod:`.rules` are small functions over a
:class:`ModuleAnalysis`, which precomputes everything the JAX-hazard
rules need from a module's source:

- a parent map (``ast`` has no uplinks),
- per-line suppression directives (``# graftlint: disable=GL003``),
- the *traced-context* set: every function-like node whose body executes
  under a JAX trace (``jit`` / ``vmap`` / ``scan`` / ``shard_map`` / ...),
  including functions reached transitively through module-local calls
  (``jax.jit(self._iteration_impl)`` marks the method, which marks the
  helpers it calls, ...).

The traced-context analysis is deliberately an over-approximation in the
direction that matters for the rules: a function passed to any tracing
transform is traced, and anything it calls by simple name or
``self.<method>`` is traced too. Host-side drivers that merely *invoke*
jitted callables (e.g. ``Engine.run_iteration``) are not traced, so
host-side syncs there are not flagged.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

__all__ = [
    "Finding",
    "ModuleAnalysis",
    "dotted_name",
    "parse_suppressions",
    "root_name",
    "walk_pruned",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, pointing at a source line."""

    rule_id: str
    rule_name: str
    path: str
    line: int
    col: int
    message: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule_id}[{self.rule_name}] {self.message}"
        )


# ``# graftlint: disable`` suppresses every rule on the line;
# ``# graftlint: disable=GL001,GL003`` suppresses the listed rules.
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>[A-Za-z0-9_,\s-]+))?"
)

# Module-level directive for pure-device kernel modules whose callers
# live in *other* modules (the traced-context fixpoint is module-local):
# every function in the module is treated as a traced body.
_ASSUME_TRACED_RE = re.compile(r"#\s*graftlint:\s*assume-traced")

# Sentinel for "all rules suppressed on this line".
ALL_RULES = None


def parse_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """Map line number -> suppressed rule-id set (None = all rules).

    Directives are matched textually, so a suppression string inside a
    string literal also counts — acceptable for a repo linter, and it
    keeps the scanner independent of tokenization errors.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = ALL_RULES
        else:
            out[i] = {
                r.strip().upper()
                for r in rules.replace(";", ",").split(",")
                if r.strip()
            }
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an Attribute/Subscript chain (``a`` in ``a.b[0].c``)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_pruned(node: ast.AST, prune=None):
    """``ast.walk`` that does not descend into nested function scopes.

    ``node`` itself is always yielded (even if function-like); children
    matching ``prune`` (default: function-like nodes) are skipped whole.
    """
    if prune is None:
        prune = FUNC_NODES
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, prune):
                continue
            stack.append(child)


FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# Call targets whose function-valued arguments are traced by JAX. Exact
# dotted forms as they appear in source (aliases like `from jax import
# jit` produce the short forms).
TRACER_CALLS = {
    "jax.jit", "jit",
    "jax.vmap", "vmap",
    "jax.pmap", "pmap",
    "jax.grad", "grad",
    "jax.value_and_grad", "value_and_grad",
    "jax.jacfwd", "jax.jacrev", "jax.hessian",
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "lax.scan",
    "jax.lax.map", "lax.map",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch",
    "jax.lax.associative_scan", "lax.associative_scan",
    "jax.experimental.shard_map.shard_map", "shard_map", "_shard_map",
    "jax.custom_jvp", "jax.custom_vjp",
    "pl.pallas_call", "pallas_call",
}

_PARTIAL_CALLS = {"partial", "functools.partial"}

# Pallas kernel entry points: their function argument runs with the
# ref-mutation programming model (stores into Ref params are the idiom,
# not a hazard).
PALLAS_CALLS = {
    "pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call",
}

# shard_map entry points: their function argument runs PER DEVICE with
# named-axis collectives — host-side calls and axis-less collectives
# inside are hazards (GL008).
SHARD_MAP_CALLS = {
    "shard_map", "_shard_map", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}


def _is_tracer_dotted(dn: Optional[str]) -> bool:
    return dn is not None and dn in TRACER_CALLS


def _tracer_in_call(call: ast.Call) -> bool:
    """True if ``call`` is a tracing transform (directly or via partial)."""
    dn = dotted_name(call.func)
    if _is_tracer_dotted(dn):
        return True
    # partial(jax.jit, static_argnums=...) used as decorator/value
    if dn in _PARTIAL_CALLS and call.args:
        return _is_tracer_dotted(dotted_name(call.args[0]))
    return False


class ModuleAnalysis:
    """Parsed module + the shared analyses rules consume."""

    def __init__(self, source: str, path: str = "<string>"):
        self.source = source
        self.path = path
        self.tree = ast.parse(source)
        self.suppressions = parse_suppressions(source)

        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        # Module-level import aliases (`jax`, `np`, `lax`, ...): calls
        # like `jax.lax.sort(...)` are library functions, not method
        # mutations of local state.
        self.imported_names: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    self.imported_names.add(
                        (alias.asname or alias.name).split(".")[0]
                    )

        # name -> function-like def nodes (module defs, nested defs,
        # methods, and lambdas bound via simple assignment).
        self._defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Lambda
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._defs_by_name.setdefault(tgt.id, []).append(
                            node.value
                        )

        self.traced: Set[ast.AST] = set()
        self.pallas: Set[ast.AST] = set()
        self.shardmap: Set[ast.AST] = set()
        self._compute_traced()

    # ------------------------------------------------------------------
    def _resolve_func_ref(self, node: ast.AST) -> List[ast.AST]:
        """Function-def nodes a reference may denote (over-approximate)."""
        if isinstance(node, ast.Lambda):
            return [node]
        if isinstance(node, ast.Name):
            return self._defs_by_name.get(node.id, [])
        if isinstance(node, ast.Attribute):
            # self._foo / cls._foo — resolve by method name anywhere in
            # the module (class attribution is an over-approximation).
            base = root_name(node)
            if base in ("self", "cls"):
                return self._defs_by_name.get(node.attr, [])
        return []

    def _compute_traced(self) -> None:
        roots: List[ast.AST] = []

        if _ASSUME_TRACED_RE.search(self.source):
            roots.extend(self.functions())

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_tracer_dotted(dotted_name(dec)) or (
                        isinstance(dec, ast.Call) and _tracer_in_call(dec)
                    ):
                        roots.append(node)
            elif isinstance(node, ast.Call) and _tracer_in_call(node):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        roots.append(arg)
                    elif isinstance(arg, ast.List):
                        # lax.switch takes a list of branches
                        for elt in arg.elts:
                            roots.extend(self._resolve_func_ref(elt))
                            if isinstance(elt, ast.Lambda):
                                roots.append(elt)
                    else:
                        roots.extend(self._resolve_func_ref(arg))

        pallas_roots: List[ast.AST] = []
        shardmap_roots: List[ast.AST] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            for calls, sink in ((PALLAS_CALLS, pallas_roots),
                                (SHARD_MAP_CALLS, shardmap_roots)):
                if dn not in calls:
                    continue
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        sink.append(arg)
                    else:
                        sink.extend(self._resolve_func_ref(arg))

        # Propagate through module-local calls: anything a traced body
        # calls by simple name or self-attribute is traced too (same
        # fixpoint for the pallas-kernel and shard_map-body sets).
        for seed, out in ((roots, self.traced), (pallas_roots, self.pallas),
                          (shardmap_roots, self.shardmap)):
            work = list(seed)
            while work:
                fn = work.pop()
                if fn in out:
                    continue
                out.add(fn)
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                for stmt in body:
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Call):
                            for target in self._resolve_func_ref(node.func):
                                if target not in out:
                                    work.append(target)

    # ------------------------------------------------------------------
    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, FUNC_NODES):
            cur = self.parents.get(cur)
        return cur

    def in_pallas_kernel(self, fn: ast.AST) -> bool:
        """Whether ``fn`` is (or is nested inside) a Pallas kernel."""
        while fn is not None:
            if fn in self.pallas:
                return True
            fn = self.enclosing_function(fn)
        return False

    def in_shard_map_body(self, fn: ast.AST) -> bool:
        """Whether ``fn`` is (or is nested inside / called from) a
        function passed to ``shard_map`` (module-local fixpoint)."""
        while fn is not None:
            if fn in self.shardmap:
                return True
            fn = self.enclosing_function(fn)
        return False

    def is_traced(self, node: ast.AST) -> bool:
        """Whether ``node`` sits in a traced (jit/vmap/scan/...) body.

        Walks up to the nearest enclosing function; if that function is
        not itself traced, keeps walking (a helper closure defined but
        never called inside a jitted function stays host-semantics, but
        the calls that matter were already propagated by the traced-set
        fixpoint)."""
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced:
                return True
            fn = self.enclosing_function(fn)
        return False

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, FUNC_NODES):
                yield node

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if line not in self.suppressions:
            return False
        rules = self.suppressions[line]
        return rules is ALL_RULES or rule_id.upper() in rules


def local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside a function body: params, assignments, imports,
    for-targets, with-as, walrus, nested defs. Comprehension targets are
    their own scope and intentionally excluded."""
    bound: Set[str] = set()
    if isinstance(fn, ast.Lambda):
        args = fn.args
        body = [fn.body]
    else:
        args = fn.args
        body = fn.body
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    ):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)

    def collect_target(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            bound.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                collect_target(elt)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    collect_target(t)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                collect_target(node.target)
            elif isinstance(node, ast.For):
                collect_target(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                collect_target(node.optional_vars)
            elif isinstance(node, ast.NamedExpr):
                collect_target(node.target)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound.add((alias.asname or alias.name).split(".")[0])
    return bound
