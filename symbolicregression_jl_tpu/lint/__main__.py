"""Entry point: ``python -m symbolicregression_jl_tpu.lint``."""

import os
import sys

from .cli import main

try:
    code = main()
except BrokenPipeError:
    # downstream pager/head closed the pipe — conventional silent exit
    devnull = os.open(os.devnull, os.O_WRONLY)
    os.dup2(devnull, sys.stdout.fileno())
    code = 0
sys.exit(code)
