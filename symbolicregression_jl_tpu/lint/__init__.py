"""graftlint — JAX-hazard static analysis + runtime invariant auditing.

Static side (pure-Python AST, no JAX import needed):

- :data:`~.rules.RULES` — table-driven rule registry (GL001-GL006)
- :func:`~.cli.lint_source` / :func:`~.cli.lint_paths` — programmatic API
- ``python -m symbolicregression_jl_tpu.lint <paths>`` — CLI, exits
  nonzero on findings

Runtime side (imports JAX lazily via :mod:`.runtime`):

- :func:`~.runtime.validate_programs` — postfix program-table invariants
- :func:`~.runtime.compile_count_guard` — "no recompiles in this region"
- :func:`~.runtime.no_transfer` — "no implicit host↔device transfers"

The static analyzer intentionally avoids importing :mod:`jax` so the CLI
stays usable (and fast) in environments without an accelerator stack.
"""

from .analyzer import Finding, ModuleAnalysis
from .cli import lint_paths, lint_source, main
from .rules import RULES, Rule, rule

__all__ = [
    "Finding",
    "ModuleAnalysis",
    "RULES",
    "Rule",
    "rule",
    "lint_paths",
    "lint_source",
    "main",
]
