"""graftlint — JAX-hazard static analysis + runtime invariant auditing.

Static side (pure-Python AST, no JAX import needed):

- :data:`~.rules.RULES` — table-driven rule registry: GL001-GL008
  (single-module JAX hazards) plus the graftwarden concurrency rules
  GL009-GL014 (:mod:`.concurrency` — interprocedural lock-context
  dataflow over the serve/shield thread fabric, checked against the
  blessed lock-order manifest in :mod:`.lock_order`)
- :func:`~.cli.lint_source` / :func:`~.cli.lint_paths` — programmatic API
- ``python -m symbolicregression_jl_tpu.lint <paths>`` — CLI, exits
  nonzero on findings

Runtime side (imports JAX lazily via :mod:`.runtime`):

- :func:`~.runtime.validate_programs` — postfix program-table invariants
- :func:`~.runtime.compile_count_guard` — "no recompiles in this region"
- :func:`~.runtime.no_transfer` — "no implicit host↔device transfers"
- :mod:`.racecheck` — instrumented lock wrappers that assert the
  lock-order manifest at runtime and replay races deterministically
  via ``SR_RACE_PLAN`` context-switch windows

The static analyzer intentionally avoids importing :mod:`jax` so the CLI
stays usable (and fast) in environments without an accelerator stack.
"""

from .analyzer import Finding, ModuleAnalysis
from .cli import lint_paths, lint_source, main
from .lock_order import BLESSED_EDGES, check_manifest_acyclic
from .rules import RULES, Rule, rule

__all__ = [
    "BLESSED_EDGES",
    "Finding",
    "ModuleAnalysis",
    "RULES",
    "Rule",
    "rule",
    "check_manifest_acyclic",
    "lint_paths",
    "lint_source",
    "main",
]
