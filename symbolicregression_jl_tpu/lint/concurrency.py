"""graftwarden — interprocedural lock-discipline analysis (GL009-GL014).

graftlint's GL001-GL008 are single-module AST checks; the serve/shield
thread fabric needs more: the hazards PR 6 fixed by review archaeology
(journal fsyncs under the server-wide lock, cancel racing submit's
unlocked append, stale preemption-guard state) are only visible when
you know *which locks are held at a call site, through calls*. This
module builds that view over the concurrent slice of the package —
``serve/``, ``shield/``, ``pulse/``, ``telemetry/``, and
``utils/stdin_quit.py``:

1. **lock inventory** — every ``self.X = threading.Lock/RLock/
   Condition`` attribute, with Condition-over-existing-lock ALIASING
   resolved (``SearchServer._cond`` *is* ``SearchServer._lock``), plus
   module-level shared instances (``shield.signals._STATE``);
2. **per-class call graph** — ``self.m()``, ``self.attr.m()`` through
   constructor-resolved attribute types (``self.admission =
   AdmissionController(...)``, ``self._guard =
   PreemptionGuard().install()``), module functions, and
   ``Ctor().m()`` builder chains;
3. **lock-context dataflow** — which locks are held at every statement
   (``with`` nesting, try/except de-scoping), propagated through the
   call graph as may-acquire / may-block / may-dispatch summaries with
   witness chains.

Rules emitted (same ``# graftlint: disable=RULE`` suppression and CLI
as GL001-GL008; docs/LINT.md "Concurrency rules" is the catalog):

- **GL009** blocking I/O (``open``/``os.fsync``/``time.sleep``/...)
  while holding a lock, directly or through a callee;
- **GL010** lock-order inversion: the derived global acquisition graph
  must be acyclic AND consistent with the blessed partial order
  committed in :mod:`.lock_order`;
- **GL011** unguarded shared mutation: an attribute written both from a
  ``threading.Thread(target=self.m)`` entry point's closure and from
  the class's other (public-path) methods, with any write lockless;
- **GL012** ``Condition.wait`` outside a ``while``-predicate loop
  (lost-wakeup / spurious-wakeup hazard);
- **GL013** JAX dispatch / device-blocking calls while holding a lock
  (one tenant's compile would serialize every other thread);
- **GL014** interprocedural GL007: anything transitively reachable
  from a registered signal handler must stay flag-only.

The runtime counterpart is :mod:`.racecheck`, which asserts the same
:mod:`.lock_order` manifest against *actual* acquisition order.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .analyzer import (
    FUNC_NODES,
    Finding,
    ModuleAnalysis,
    dotted_name,
)
from .lock_order import violates
from .rules import (
    RULES,
    _SIGNAL_HAZARD_NAMES,
    _SIGNAL_HAZARD_PREFIXES,
    rule,
)

__all__ = ["ConcurrencyAnalysis", "analysis_for"]

# Directory components (plus the one utils file) the warden analyzes —
# the concurrent slice of the package. The rule `scope=` uses the same
# tuple, so fixtures under pkg/serve/... exercise the rules too.
_SCOPE_DIRS = ("serve", "shield", "pulse", "telemetry")
_SCOPE_FILES = ("stdin_quit.py",)
_RULE_SCOPE = _SCOPE_DIRS + _SCOPE_FILES

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_COND_CTORS = {"threading.Condition", "Condition"}
_THREAD_CTORS = {"threading.Thread", "Thread"}

# Calls that block on I/O or the scheduler — poison under a lock every
# other thread contends for. `.join`/`.flush`/`write` are deliberately
# absent: flagging them would bury the true fsync/open findings in
# noise (a buffered write under a log lock is the working idiom).
_BLOCKING_CALLS = {
    "open", "os.fsync", "os.fdatasync", "os.replace", "os.rename",
    "os.remove", "os.unlink", "os.makedirs", "time.sleep",
    "json.dump", "pickle.dump", "np.save", "np.load",
    "numpy.save", "numpy.load", "shutil.rmtree", "shutil.copy",
    "shutil.copyfile", "shutil.move", "subprocess.run",
    "subprocess.Popen", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}

# JAX dispatch / device-blocking surface (GL013): a trace+compile or a
# blocking sync under the server-wide lock stalls submit/poll/cancel
# for every tenant until XLA returns.
_JAX_PREFIXES = ("jax.", "jnp.")
_JAX_NAMES = {
    "equation_search", "block_until_ready", "device_get", "device_put",
}


class _ClassInfo:
    def __init__(self, name: str, path: str) -> None:
        self.name = name
        self.path = path
        self.methods: Dict[str, ast.AST] = {}
        # attr -> canonical lock name ("Class.attr"); Condition aliases
        # resolve to their underlying lock's canonical name
        self.locks: Dict[str, str] = {}
        self.conds: Dict[str, str] = {}
        self.attr_types: Dict[str, str] = {}
        self.thread_entries: Set[str] = set()


class _FuncInfo:
    def __init__(self, qual: str, node: ast.AST, mod: ModuleAnalysis,
                 cls: Optional[_ClassInfo]) -> None:
        self.qual = qual
        self.node = node
        self.mod = mod
        self.cls = cls
        # direct facts (filled by _summarize)
        self.acquire_locks: Dict[str, ast.AST] = {}
        self.blocking: List[Tuple[str, ast.AST]] = []
        self.jaxing: List[Tuple[str, ast.AST]] = []
        self.calls: Set[str] = set()

    @property
    def display(self) -> str:
        return self.qual.rsplit("::", 1)[-1]


def _short(qual: str) -> str:
    return qual.rsplit("::", 1)[-1]


def _canon(path: str) -> str:
    return os.path.realpath(os.path.abspath(path))


class ConcurrencyAnalysis:
    """Whole-package (or single-fixture) concurrency facts + findings."""

    def __init__(self, mods: Sequence[ModuleAnalysis]) -> None:
        self.mods = list(mods)
        self.classes: Dict[str, _ClassInfo] = {}
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        self.module_vars: Dict[str, Dict[str, str]] = {}
        self.funcs: Dict[str, _FuncInfo] = {}
        self.findings: List[Finding] = []
        # (held, acquired) -> (path, line, col, chain)
        self.edges: Dict[Tuple[str, str],
                         Tuple[str, int, int, Tuple[str, ...]]] = {}
        self._finding_keys: Set[Tuple] = set()
        self._collect()
        self._summarize()
        self._fixpoint()
        for fi in self.funcs.values():
            self._analyze_func(fi)
        self._check_lock_order()
        self._check_shared_mutation()
        self._check_cond_wait()
        self._check_signal_closure()

    # ------------------------------------------------------------------
    # collection
    # ------------------------------------------------------------------
    def _collect(self) -> None:
        # pass 1: classes, methods, module funcs, lock attributes
        for mod in self.mods:
            self.module_funcs[mod.path] = {}
            self.module_vars[mod.path] = {}
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{mod.path}::{node.name}"
                    self.module_funcs[mod.path][node.name] = qual
                    self.funcs[qual] = _FuncInfo(qual, node, mod, None)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                ci = _ClassInfo(node.name, mod.path)
                for item in node.body:
                    if isinstance(item,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ci.methods[item.name] = item
                self.classes[node.name] = ci
                for item in ci.methods.values():
                    for n in ast.walk(item):
                        a = self._self_assign(n)
                        if a is None:
                            continue
                        attr, value = a
                        if isinstance(value, ast.Call):
                            dn = dotted_name(value.func)
                            if dn in _LOCK_CTORS:
                                ci.locks[attr] = f"{ci.name}.{attr}"
                for item in ci.methods.values():
                    self.funcs[f"{ci.name}.{item.name}"] = _FuncInfo(
                        f"{ci.name}.{item.name}", item, mod, ci)

        # pass 2 (needs the global class-name set and pass-1 locks):
        # Condition aliasing, attribute types, module-level instances,
        # thread entry points
        for mod in self.mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if (isinstance(tgt, ast.Name)
                            and self._enclosing_class(mod, node) is None
                            and mod.enclosing_function(node) is None):
                        cls = self._ctor_class(node.value)
                        if cls is not None:
                            self.module_vars[mod.path][tgt.id] = cls
            for ci in self.classes.values():
                if ci.path != mod.path:
                    continue
                for item in ci.methods.values():
                    for n in ast.walk(item):
                        a = self._self_assign(n)
                        if a is None:
                            continue
                        attr, value = a
                        if not isinstance(value,
                                          (ast.Call, ast.BoolOp)):
                            continue
                        dn = (dotted_name(value.func)
                              if isinstance(value, ast.Call) else None)
                        if dn in _LOCK_CTORS:
                            continue  # pass 1
                        if dn in _COND_CTORS:
                            under = None
                            if isinstance(value, ast.Call) and value.args:
                                arg0 = value.args[0]
                                if (isinstance(arg0, ast.Attribute)
                                        and isinstance(arg0.value, ast.Name)
                                        and arg0.value.id == "self"):
                                    under = ci.locks.get(arg0.attr)
                            ci.conds[attr] = under or f"{ci.name}.{attr}"
                            continue
                        cls = self._ctor_class(value)
                        if cls is not None:
                            ci.attr_types[attr] = cls
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) not in _THREAD_CTORS:
                    continue
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and len(node.args) >= 2:
                    target = node.args[1]
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    ci = self._enclosing_class(mod, node)
                    if ci is not None and target.attr in ci.methods:
                        ci.thread_entries.add(target.attr)

    @staticmethod
    def _self_assign(n: ast.AST) -> Optional[Tuple[str, ast.AST]]:
        """(attr, value) for a direct ``self.attr = value``."""
        if not isinstance(n, ast.Assign) or len(n.targets) != 1:
            return None
        tgt = n.targets[0]
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            return tgt.attr, n.value
        return None

    def _enclosing_class(self, mod: ModuleAnalysis,
                         node: ast.AST) -> Optional[_ClassInfo]:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return self.classes.get(cur.name)
            cur = mod.parents.get(cur)
        return None

    def _ctor_class(self, value: ast.AST) -> Optional[str]:
        """Class name a constructor-ish expression evaluates to:
        ``C(...)``, ``x or C(...)``, ``C(...).install()`` builder
        chains (assumed to return self)."""
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                c = self._ctor_class(v)
                if c is not None:
                    return c
            return None
        if isinstance(value, ast.Call):
            dn = dotted_name(value.func)
            if dn is not None:
                last = dn.rsplit(".", 1)[-1]
                if last in self.classes:
                    return last
            if isinstance(value.func, ast.Attribute):
                return self._ctor_class(value.func.value)
        return None

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def _resolve_lock(self, fi: _FuncInfo,
                      expr: ast.AST) -> Optional[str]:
        """Canonical lock name of an acquisition expression
        (``self._lock``, ``self._cond``, ``_STATE.lock``)."""
        if not (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            return None
        base, attr = expr.value.id, expr.attr
        if base == "self" and fi.cls is not None:
            if attr in fi.cls.locks:
                return fi.cls.locks[attr]
            if attr in fi.cls.conds:
                return fi.cls.conds[attr]
            return None
        cls = self.module_vars.get(fi.mod.path, {}).get(base)
        if cls is not None:
            ci = self.classes.get(cls)
            if ci is not None:
                return ci.locks.get(attr) or ci.conds.get(attr)
        return None

    def _resolve_call(self, fi: _FuncInfo,
                      call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            q = self.module_funcs.get(fi.mod.path, {}).get(f.id)
            if q is not None:
                return q
            if f.id in self.classes:
                init = f"{f.id}.__init__"
                return init if init in self.funcs else None
            return None
        if not isinstance(f, ast.Attribute):
            return None
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self" and fi.cls is not None:
                if f.attr in fi.cls.methods:
                    return f"{fi.cls.name}.{f.attr}"
                t = fi.cls.attr_types.get(f.attr)
                if t is not None and f.attr in self.classes.get(
                        t, _ClassInfo("", "")).methods:
                    return f"{t}.{f.attr}"
                return None
            cls = self.module_vars.get(fi.mod.path, {}).get(v.id)
            if cls is not None and f.attr in self.classes.get(
                    cls, _ClassInfo("", "")).methods:
                return f"{cls}.{f.attr}"
            return None
        if (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self" and fi.cls is not None):
            # self.attr.m() through the constructor-resolved attr type
            t = fi.cls.attr_types.get(v.attr)
            if t is not None and f.attr in self.classes.get(
                    t, _ClassInfo("", "")).methods:
                return f"{t}.{f.attr}"
            return None
        if isinstance(v, ast.Call):
            # Ctor().m() builder chain
            cls = self._ctor_class(v)
            if cls is not None and f.attr in self.classes.get(
                    cls, _ClassInfo("", "")).methods:
                return f"{cls}.{f.attr}"
        return None

    # ------------------------------------------------------------------
    # summaries + fixpoint
    # ------------------------------------------------------------------
    def _summarize(self) -> None:
        for fi in self.funcs.values():
            body = fi.node.body
            body = body if isinstance(body, list) else [body]
            for stmt in body:
                for n in _walk_no_nested(stmt):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            ln = self._resolve_lock(fi, item.context_expr)
                            if ln is not None:
                                fi.acquire_locks.setdefault(
                                    ln, item.context_expr)
                    if not isinstance(n, ast.Call):
                        continue
                    if (isinstance(n.func, ast.Attribute)
                            and n.func.attr == "acquire"):
                        ln = self._resolve_lock(fi, n.func.value)
                        if ln is not None:
                            fi.acquire_locks.setdefault(ln, n)
                    dn = dotted_name(n.func)
                    if dn in _BLOCKING_CALLS:
                        fi.blocking.append((dn, n))
                    elif dn is not None and (
                            dn.startswith(_JAX_PREFIXES)
                            or dn in _JAX_NAMES):
                        fi.jaxing.append((dn, n))
                    q = self._resolve_call(fi, n)
                    if q is not None and q != fi.qual:
                        fi.calls.add(q)

    def _fixpoint(self) -> None:
        # qual -> lock -> witness chain (quals, ending at the acquirer)
        self.may_acquire: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        # qual -> (description, witness chain)
        self.may_block: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        self.may_jax: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        for q, fi in self.funcs.items():
            self.may_acquire[q] = {
                ln: (q,) for ln in fi.acquire_locks}
            if fi.blocking:
                self.may_block[q] = (fi.blocking[0][0], (q,))
            if fi.jaxing:
                self.may_jax[q] = (fi.jaxing[0][0], (q,))
        for _ in range(len(self.funcs) + 1):
            changed = False
            for q, fi in self.funcs.items():
                for callee in fi.calls:
                    for ln, chain in self.may_acquire.get(
                            callee, {}).items():
                        if ln not in self.may_acquire[q]:
                            self.may_acquire[q][ln] = (q,) + chain
                            changed = True
                    if callee in self.may_block and q not in self.may_block:
                        desc, chain = self.may_block[callee]
                        self.may_block[q] = (desc, (q,) + chain)
                        changed = True
                    if callee in self.may_jax and q not in self.may_jax:
                        desc, chain = self.may_jax[callee]
                        self.may_jax[q] = (desc, (q,) + chain)
                        changed = True
            if not changed:
                break

    # ------------------------------------------------------------------
    # findings plumbing
    # ------------------------------------------------------------------
    def _emit(self, rid: str, fi: _FuncInfo, node: ast.AST,
              msg: str) -> None:
        key = (rid, fi.mod.path, getattr(node, "lineno", 1),
               getattr(node, "col_offset", 0))
        if key in self._finding_keys:
            return
        self._finding_keys.add(key)
        self.findings.append(Finding(
            rule_id=rid,
            rule_name=RULES[rid].name if rid in RULES else rid,
            path=fi.mod.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=msg,
        ))

    def findings_for(self, path: str, rid: str) -> Iterator[Finding]:
        # Package-mode modules are loaded from disk with absolute paths
        # while the CLI may lint with relative ones — match on realpath
        # and re-attribute to the requesting module's spelling so
        # run_rules' suppression filter and output stay consistent.
        want = _canon(path)
        for f in self.findings:
            if f.rule_id != rid or _canon(f.path) != want:
                continue
            if f.path != path:
                f = Finding(
                    rule_id=f.rule_id, rule_name=f.rule_name, path=path,
                    line=f.line, col=f.col, message=f.message)
            yield f

    def _edge(self, held: str, acquired: str, fi: _FuncInfo,
              node: ast.AST, chain: Tuple[str, ...] = ()) -> None:
        if held == acquired:
            return  # RLock reentrancy / condition re-entry
        self.edges.setdefault(
            (held, acquired),
            (fi.mod.path, getattr(node, "lineno", 1),
             getattr(node, "col_offset", 0), chain))

    # ------------------------------------------------------------------
    # lock-context dataflow (GL009, GL010 edges, GL013, GL011 writes)
    # ------------------------------------------------------------------
    def _analyze_func(self, fi: _FuncInfo) -> None:
        self._writes: Dict[Tuple[str, str], List] = getattr(
            self, "_writes", {})
        body = fi.node.body
        body = body if isinstance(body, list) else [body]
        self._visit_stmts(fi, body, ())

    def _visit_stmts(self, fi: _FuncInfo, stmts, held: Tuple[str, ...]
                     ) -> None:
        for s in stmts:
            if isinstance(s, FUNC_NODES + (ast.ClassDef,)):
                continue  # separate scope/execution time
            if isinstance(s, (ast.With, ast.AsyncWith)):
                cur = list(held)
                for item in s.items:
                    ln = self._resolve_lock(fi, item.context_expr)
                    if ln is not None:
                        for h in cur:
                            self._edge(h, ln, fi, item.context_expr)
                        cur.append(ln)
                    else:
                        self._visit_expr(fi, item.context_expr,
                                         tuple(cur))
                self._visit_stmts(fi, s.body, tuple(cur))
            elif isinstance(s, ast.Try):
                self._visit_stmts(fi, s.body, held)
                for h in s.handlers:
                    self._visit_stmts(fi, h.body, held)
                self._visit_stmts(fi, s.orelse, held)
                self._visit_stmts(fi, s.finalbody, held)
            elif isinstance(s, ast.If):
                self._visit_expr(fi, s.test, held)
                self._visit_stmts(fi, s.body, held)
                self._visit_stmts(fi, s.orelse, held)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                self._visit_expr(fi, s.iter, held)
                self._visit_stmts(fi, s.body, held)
                self._visit_stmts(fi, s.orelse, held)
            elif isinstance(s, ast.While):
                self._visit_expr(fi, s.test, held)
                self._visit_stmts(fi, s.body, held)
                self._visit_stmts(fi, s.orelse, held)
            else:
                self._record_writes(fi, s, held)
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        self._visit_expr(fi, child, held)

    def _record_writes(self, fi: _FuncInfo, s: ast.stmt,
                       held: Tuple[str, ...]) -> None:
        if fi.cls is None:
            return
        targets: List[ast.AST] = []
        if isinstance(s, ast.Assign):
            targets = list(s.targets)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets = [s.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                mname = fi.qual.split(".", 1)[1]
                self._writes.setdefault(
                    (fi.cls.name, mname), []).append(
                        (t.attr, bool(held), t, fi))

    def _visit_expr(self, fi: _FuncInfo, e: Optional[ast.AST],
                    held: Tuple[str, ...]) -> None:
        if e is None:
            return
        for n in _walk_no_nested(e):
            if not isinstance(n, ast.Call):
                continue
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "acquire"):
                ln = self._resolve_lock(fi, n.func.value)
                if ln is not None:
                    for h in held:
                        self._edge(h, ln, fi, n)
                    continue
            dn = dotted_name(n.func)
            if held and dn in _BLOCKING_CALLS:
                self._emit(
                    "GL009", fi, n,
                    f"`{dn}(...)` while holding `{held[-1]}` — blocking "
                    f"I/O under a lock stalls every thread contending "
                    f"for it; move the I/O outside the lock",
                )
                continue
            if held and dn is not None and (
                    dn.startswith(_JAX_PREFIXES) or dn in _JAX_NAMES):
                self._emit(
                    "GL013", fi, n,
                    f"`{dn}(...)` while holding `{held[-1]}` — JAX "
                    f"dispatch/compile under a lock serializes every "
                    f"other thread on XLA; dispatch outside the lock",
                )
                continue
            q = self._resolve_call(fi, n)
            if q is None:
                continue
            if held and q in self.may_block:
                desc, chain = self.may_block[q]
                self._emit(
                    "GL009", fi, n,
                    f"call to `{_short(q)}` performs blocking I/O "
                    f"(`{desc}` via "
                    f"{' -> '.join(_short(c) for c in chain)}) while "
                    f"holding `{held[-1]}`; move the call outside the "
                    f"lock",
                )
            if held and q in self.may_jax:
                desc, chain = self.may_jax[q]
                self._emit(
                    "GL013", fi, n,
                    f"call to `{_short(q)}` dispatches to JAX "
                    f"(`{desc}` via "
                    f"{' -> '.join(_short(c) for c in chain)}) while "
                    f"holding `{held[-1]}`; dispatch outside the lock",
                )
            for ln, chain in self.may_acquire.get(q, {}).items():
                for h in held:
                    self._edge(h, ln, fi, n, chain)

    # ------------------------------------------------------------------
    # GL010 — derived acquisition graph: cycles + manifest inversions
    # ------------------------------------------------------------------
    def _check_lock_order(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen: Set[str] = set()
            work = [src]
            while work:
                n = work.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                work.extend(adj.get(n, ()))
            return False

        for (a, b), (path, line, col, chain) in sorted(
                self.edges.items()):
            fi = _SyntheticSite(path, line, col)
            via = (f" (via {' -> '.join(_short(c) for c in chain)})"
                   if chain else "")
            if reaches(b, a):
                self._emit(
                    "GL010", fi, fi,
                    f"acquiring `{b}` while holding `{a}`{via} "
                    f"completes a cycle in the derived lock graph "
                    f"(`{b}` already reaches `{a}`): deadlock under "
                    f"the right interleaving",
                )
            elif violates(a, b):
                self._emit(
                    "GL010", fi, fi,
                    f"acquiring `{b}` while holding `{a}`{via} inverts "
                    f"the blessed lock order (lint/lock_order.py "
                    f"sanctions `{b}` before `{a}`)",
                )

    # ------------------------------------------------------------------
    # GL011 — unguarded shared mutation across thread boundary
    # ------------------------------------------------------------------
    def _thread_closure(self, ci: _ClassInfo) -> Set[str]:
        work = [f"{ci.name}.{m}" for m in ci.thread_entries]
        seen: Set[str] = set()
        while work:
            q = work.pop()
            if q in seen or q not in self.funcs:
                continue
            seen.add(q)
            work.extend(self.funcs[q].calls)
        return seen

    def _check_shared_mutation(self) -> None:
        writes = getattr(self, "_writes", {})
        for ci in self.classes.values():
            if not ci.thread_entries:
                continue
            closure = self._thread_closure(ci)
            thread_methods = {
                q.split(".", 1)[1] for q in closure
                if q.startswith(ci.name + ".")}
            by_attr: Dict[str, Dict[str, List]] = {}
            for (cname, mname), ws in writes.items():
                if cname != ci.name or mname == "__init__":
                    continue
                side = ("thread" if mname in thread_methods else "main")
                for (attr, locked, node, fi) in ws:
                    by_attr.setdefault(attr, {"thread": [], "main": []})[
                        side].append((locked, node, fi, mname))
            for attr, sides in by_attr.items():
                if not sides["thread"] or not sides["main"]:
                    continue
                for locked, node, fi, mname in (
                        sides["thread"] + sides["main"]):
                    if locked:
                        continue
                    entry = sorted(ci.thread_entries)[0]
                    self._emit(
                        "GL011", fi, node,
                        f"`self.{attr}` is written both from the "
                        f"`{ci.name}.{entry}` thread's call closure and "
                        f"from the class's other methods, and this "
                        f"write in `{mname}` holds no lock — guard "
                        f"every write with the owning lock",
                    )

    # ------------------------------------------------------------------
    # GL012 — Condition.wait outside a while-predicate loop
    # ------------------------------------------------------------------
    def _check_cond_wait(self) -> None:
        for fi in self.funcs.values():
            for n in ast.walk(fi.node):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "wait"):
                    continue
                recv = n.func.value
                ln = None
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)):
                    base, attr = recv.value.id, recv.attr
                    if (base == "self" and fi.cls is not None
                            and attr in fi.cls.conds):
                        ln = fi.cls.conds[attr]
                    else:
                        cls = self.module_vars.get(
                            fi.mod.path, {}).get(base)
                        if cls is not None:
                            ln = self.classes.get(
                                cls, _ClassInfo("", "")).conds.get(attr)
                if ln is None:
                    continue  # Event.wait / unknown receiver
                cur = fi.mod.parents.get(n)
                in_while = False
                while cur is not None and not isinstance(cur, FUNC_NODES):
                    if isinstance(cur, ast.While):
                        in_while = True
                        break
                    cur = fi.mod.parents.get(cur)
                if not in_while:
                    self._emit(
                        "GL012", fi, n,
                        f"`Condition.wait` on `{ln}` outside a "
                        f"while-predicate loop — spurious wakeups and "
                        f"notify-before-wait races require "
                        f"`while not <predicate>: cond.wait()`",
                    )

    # ------------------------------------------------------------------
    # GL014 — interprocedural signal-handler closure (GL007, but deep)
    # ------------------------------------------------------------------
    def _signal_handlers(self) -> Dict[str, str]:
        """qual -> registered display name, from signal.signal calls."""
        out: Dict[str, str] = {}
        for mod in self.mods:
            for n in ast.walk(mod.tree):
                if not (isinstance(n, ast.Call)
                        and dotted_name(n.func) == "signal.signal"
                        and len(n.args) >= 2):
                    continue
                h = n.args[1]
                name = None
                if isinstance(h, ast.Name):
                    name = h.id
                elif isinstance(h, ast.Attribute):
                    name = h.attr
                if name is None:
                    continue
                q = self.module_funcs.get(mod.path, {}).get(name)
                if q is None:
                    for cname, ci in self.classes.items():
                        if name in ci.methods:
                            q = f"{cname}.{name}"
                            break
                if q is not None:
                    out[q] = name
        return out

    def _check_signal_closure(self) -> None:
        handlers = self._signal_handlers()
        if not handlers:
            return
        parent: Dict[str, Optional[str]] = {}
        work = list(handlers)
        for q in work:
            parent[q] = None
        while work:
            q = work.pop()
            fi = self.funcs.get(q)
            if fi is None:
                continue
            for callee in fi.calls:
                if callee not in parent:
                    parent[callee] = q
                    work.append(callee)
        for q in parent:
            if q in handlers:
                continue  # direct hazards in the handler are GL007's
            fi = self.funcs.get(q)
            if fi is None:
                continue
            chain: List[str] = []
            cur: Optional[str] = q
            while cur is not None:
                chain.append(_short(cur))
                cur = parent[cur]
            chain.reverse()
            root = chain[0]
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dn = dotted_name(n.func)
                if dn is None:
                    continue
                last = dn.rsplit(".", 1)[-1]
                if dn.startswith(_SIGNAL_HAZARD_PREFIXES) or (
                        dn in _SIGNAL_HAZARD_NAMES
                        or last in _SIGNAL_HAZARD_NAMES):
                    self._emit(
                        "GL014", fi, n,
                        f"`{dn}` is reachable from signal handler "
                        f"`{handlers.get(root, root)}` "
                        f"(via {' -> '.join(chain)}) — everything a "
                        f"handler can reach must stay flag-only; do "
                        f"the work at the next iteration boundary",
                    )


class _SyntheticSite:
    """Finding site for graph-level (edge) findings: quacks like a
    node (lineno/col_offset) and like a _FuncInfo (mod.path)."""

    def __init__(self, path: str, line: int, col: int) -> None:
        self.lineno = line
        self.col_offset = col
        self.mod = type("_M", (), {"path": path})()


def _walk_no_nested(node: ast.AST):
    """ast.walk that does not descend into nested function/class
    scopes (node itself is yielded even if function-like)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, FUNC_NODES + (ast.ClassDef,)):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# package assembly + caching
# ---------------------------------------------------------------------------

_PACKAGE_CACHE: Dict[str, ConcurrencyAnalysis] = {}
_SINGLE_CACHE: List = [None, None]  # [mod, analysis]


def _package_root(path: str) -> Optional[str]:
    parts = os.path.abspath(path).replace("\\", "/").split("/")
    for i in range(len(parts) - 1, 0, -1):
        if parts[i] in _SCOPE_DIRS or parts[i] == "utils":
            root = "/".join(parts[:i])
            if os.path.isdir(os.path.join(root, "serve")):
                return root
    return None


def analysis_for(mod: ModuleAnalysis) -> ConcurrencyAnalysis:
    """The (cached) package-wide analysis covering ``mod`` — or a
    single-module analysis when ``mod`` is a synthetic fixture whose
    package root does not exist on disk."""
    root = _package_root(mod.path)
    if root is None:
        if _SINGLE_CACHE[0] is mod:
            return _SINGLE_CACHE[1]
        ana = ConcurrencyAnalysis([mod])
        _SINGLE_CACHE[0], _SINGLE_CACHE[1] = mod, ana
        return ana
    cached = _PACKAGE_CACHE.get(root)
    if cached is not None:
        return cached
    paths: List[str] = []
    for d in _SCOPE_DIRS:
        full = os.path.join(root, d)
        if os.path.isdir(full):
            for fn in sorted(os.listdir(full)):
                if fn.endswith(".py"):
                    paths.append(os.path.join(full, fn))
    for fn in _SCOPE_FILES:
        p = os.path.join(root, "utils", fn)
        if os.path.isfile(p):
            paths.append(p)
    mods: List[ModuleAnalysis] = []
    mod_real = os.path.realpath(os.path.abspath(mod.path))
    for p in paths:
        if os.path.realpath(p) == mod_real:
            mods.append(mod)
            continue
        try:
            with open(p, "r", encoding="utf-8") as f:
                mods.append(ModuleAnalysis(f.read(), p))
        except (OSError, SyntaxError, ValueError):
            continue  # GL000 reports parse failures; skip here
    if mod.path not in {m.path for m in mods}:
        mods.append(mod)
    ana = ConcurrencyAnalysis(mods)
    _PACKAGE_CACHE[root] = ana
    return ana


# ---------------------------------------------------------------------------
# rule registrations
# ---------------------------------------------------------------------------


@rule(
    "GL009",
    "lock-blocking-io",
    "blocking I/O (open/fsync/sleep/...) while holding a lock, "
    "directly or through a callee",
    "An fsync'd journal append under the server-wide lock stalls "
    "submit/poll/cancel and every worker's queue transition for a "
    "disk round-trip — the exact class of hang PR 6 fixed by moving "
    "journal/audit writes outside `self._lock`. Locks that exist "
    "specifically to serialize one file's writes (the serve log, the "
    "journal) annotate the write with "
    "`# graftlint: disable=GL009`.",
    scope=_RULE_SCOPE,
)
def check_blocking_io_under_lock(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL009")


@rule(
    "GL010",
    "lock-order-inversion",
    "acquisition edge that cycles the derived lock graph or inverts "
    "the blessed order in lint/lock_order.py",
    "Two threads taking the same two locks in opposite orders is a "
    "deadlock waiting for the right interleaving. The warden derives "
    "the global acquisition graph (with-nesting plus call-graph "
    "propagation) and checks it against the committed partial order; "
    "new legitimate edges are added to lint/lock_order.py, where the "
    "racecheck runtime auditor asserts them too.",
    scope=_RULE_SCOPE,
)
def check_lock_order_inversion(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL010")


@rule(
    "GL011",
    "unguarded-shared-write",
    "attribute written from a Thread-target closure AND from other "
    "methods with at least one write lockless",
    "State shared between a worker thread and the public API needs "
    "one owning lock on every write; a lockless write on either side "
    "is a data race the GIL hides until a preemption lands between "
    "read-modify-write steps. Thread-confined attributes (written "
    "only by the worker) are fine and not flagged.",
    scope=_RULE_SCOPE,
)
def check_unguarded_shared_write(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL011")


@rule(
    "GL012",
    "naked-cond-wait",
    "Condition.wait outside a while-predicate loop",
    "Condition.wait can return spuriously and a notify can land "
    "before the wait starts; only `while not predicate: cond.wait()` "
    "is correct (the wait_idle hang PR 6 round 7 fixed). Event.wait "
    "is level-triggered and exempt.",
    scope=_RULE_SCOPE,
)
def check_naked_cond_wait(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL012")


@rule(
    "GL013",
    "jax-under-lock",
    "JAX dispatch / device-blocking call while holding a lock",
    "A trace+compile or blocking device sync under the server-wide "
    "lock freezes submit/poll/cancel for every tenant until XLA "
    "returns — up to minutes for a cold compile. Dispatch outside "
    "the lock; publish results under it.",
    scope=_RULE_SCOPE,
)
def check_jax_under_lock(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL013")


@rule(
    "GL014",
    "signal-closure-hazard",
    "device/IO/serialization work transitively reachable from a "
    "registered signal handler",
    "GL007 checks the handler body; this closes the loophole of a "
    "flag-only handler calling a helper that fsyncs or pickles. A "
    "signal handler runs at an arbitrary bytecode boundary, so its "
    "whole call closure must stay flag-only "
    "(shield/signals.py is the reference).",
    scope=_RULE_SCOPE,
)
def check_signal_closure_hazard(mod: ModuleAnalysis) -> Iterator[Finding]:
    yield from analysis_for(mod).findings_for(mod.path, "GL014")
