"""The blessed lock acquisition order — graftwarden's manifest.

The serve/shield thread fabric holds seven locks (docs/SERVING.md,
"Concurrency" in docs/LINT.md):

- ``SearchServer._lock`` — the server-wide RLock (its ``_cond`` is a
  Condition OVER the same lock, so both names denote one lock),
- ``AdmissionController._lock``, ``RequestJournal._lock``,
  ``ServeLog._lock``, ``ExecutableCache._lock``,
  ``MetricsServer._state_lock``,
- ``_SharedSignalState.lock`` — the process-global signal-guard RLock
  (shield/signals.py).

:data:`BLESSED_EDGES` is the committed partial order: ``(A, B)`` means
"holding A while acquiring B is sanctioned". The static analyzer
(lint/concurrency.py, rule GL010) flags any *derived* acquisition edge
whose reverse is reachable in this order, and the runtime auditor
(lint/racecheck.py) asserts every *actual* acquisition against the same
closure when ``debug_checks=True`` — one manifest, checked twice.

Adding an edge: append it here, run
``python -m symbolicregression_jl_tpu.lint symbolicregression_jl_tpu/``
(GL010 re-derives the graph), and keep
:func:`check_manifest_acyclic` green — a cycle in the manifest itself
is a deadlock blessed on paper, and tests/test_lint_rules.py pins that
it raises.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Set, Tuple

__all__ = [
    "BLESSED_EDGES",
    "MANIFEST_LOCKS",
    "blessed_closure",
    "check_manifest_acyclic",
    "violates",
]

# (held, then-acquired): the sanctioned nesting, one tuple per edge.
BLESSED_EDGES: Tuple[Tuple[str, str], ...] = (
    # cancel()/_finish()/submit-rollback release the admission slot
    # while holding the server lock (admission's own lock is leaf-ward)
    ("SearchServer._lock", "AdmissionController._lock"),
    # start() attaches/detaches the process-global preemption guard
    # under the server lock (shield/signals.py refcounting)
    ("SearchServer._lock", "_SharedSignalState.lock"),
    # the overload ladder audits sheds/rejects to serve telemetry from
    # inside the admission decision
    ("AdmissionController._lock", "ServeLog._lock"),
    # the serve fault injector's journal-corruption hook audits from
    # inside the journal append
    ("RequestJournal._lock", "ServeLog._lock"),
)

# Every lock name the manifest talks about. The analyzers only assert
# order between locks in this universe; locks outside it (per-request
# watchdogs, test fixtures) are unordered by fiat.
MANIFEST_LOCKS: Tuple[str, ...] = tuple(sorted(
    {a for a, _ in BLESSED_EDGES} | {b for _, b in BLESSED_EDGES}
    | {"ExecutableCache._lock", "MetricsServer._state_lock"}
))


def blessed_closure(
    edges: Sequence[Tuple[str, str]] = BLESSED_EDGES,
) -> Dict[str, Set[str]]:
    """``before -> {every lock reachable after it}`` (transitive)."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    out: Dict[str, Set[str]] = {}
    for src in adj:
        seen: Set[str] = set()
        work: List[str] = list(adj[src])
        while work:
            n = work.pop()
            if n in seen:
                continue
            seen.add(n)
            work.extend(adj.get(n, ()))
        out[src] = seen
    return out


def violates(
    held: str,
    acquiring: str,
    edges: Sequence[Tuple[str, str]] = BLESSED_EDGES,
) -> bool:
    """True when acquiring ``acquiring`` while holding ``held`` inverts
    the blessed order (i.e. the manifest sanctions the REVERSE path).
    Unrelated lock pairs are not violations — the manifest is a partial
    order, not a total one."""
    if held == acquiring:
        return False  # RLock reentrancy
    return held in blessed_closure(edges).get(acquiring, ())


def check_manifest_acyclic(
    edges: Iterable[Tuple[str, str]] = BLESSED_EDGES,
) -> None:
    """Raise ``ValueError`` if the manifest contains a cycle — a blessed
    deadlock. Run by the lint test suite on every edit."""
    edges = list(edges)
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}

    def visit(node: str, trail: List[str]) -> None:
        color[node] = GRAY
        trail.append(node)
        for nxt in adj.get(node, ()):
            c = color.get(nxt, WHITE)
            if c == GRAY:
                cyc = trail[trail.index(nxt):] + [nxt]
                raise ValueError(
                    "lock-order manifest has a cycle: "
                    + " -> ".join(cyc)
                )
            if c == WHITE:
                visit(nxt, trail)
        trail.pop()
        color[node] = BLACK

    for node in list(adj):
        if color.get(node, WHITE) == WHITE:
            visit(node, [])


# the committed manifest must itself be a DAG at import time
check_manifest_acyclic(BLESSED_EDGES)
