"""graftlint CLI: ``python -m symbolicregression_jl_tpu.lint <paths>``.

Walks the given files/directories, runs every registered rule (see
:mod:`.rules`), prints findings as ``path:line:col: ID[name] message``,
and exits nonzero when anything is found. ``--list-rules`` prints the
rule catalog; ``--select GL001,GL003`` restricts the run.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Iterable, List, Optional, Sequence, Set

from .analyzer import Finding, ModuleAnalysis
from .rules import RULES, run_rules

# registers GL009-GL014 (graftwarden concurrency rules) in RULES
from . import concurrency  # noqa: E402,F401  isort:skip

__all__ = ["lint_source", "lint_paths", "iter_py_files", "main"]

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build"}


def iter_py_files(target: str) -> Iterable[str]:
    if os.path.isfile(target):
        if target.endswith(".py"):
            yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in _SKIP_DIRS and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Set[str]] = None,
) -> List[Finding]:
    """Lint one source string. ``path`` drives directory-scoped rules
    (e.g. GL002 only fires for paths containing an ``evolve``/``ops``
    component) — tests pass synthetic paths like ``pkg/evolve/x.py``."""
    return run_rules(ModuleAnalysis(source, path), select=select)


def lint_paths(
    targets: Sequence[str],
    select: Optional[Set[str]] = None,
    on_error=None,
) -> List[Finding]:
    findings: List[Finding] = []
    for target in targets:
        for path in iter_py_files(target):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                findings.extend(lint_source(source, path, select=select))
            except SyntaxError as e:
                findings.append(
                    Finding(
                        rule_id="GL000",
                        rule_name="parse-error",
                        path=path,
                        line=e.lineno or 1,
                        col=e.offset or 0,
                        message=f"could not parse: {e.msg}",
                    )
                )
            except (UnicodeDecodeError, ValueError) as e:
                # non-UTF-8 bytes, or ast.parse on source with null
                # bytes (ValueError, not SyntaxError) — report, continue
                findings.append(
                    Finding(
                        rule_id="GL000",
                        rule_name="parse-error",
                        path=path,
                        line=1,
                        col=0,
                        message=f"could not read/parse: {e}",
                    )
                )
            except OSError as e:
                if on_error is not None:
                    on_error(path, e)
    return findings


def _print_catalog(out) -> None:
    for r in RULES.values():
        scope = (
            f" [only: {', '.join(r.scope)}/]" if r.scope else ""
        )
        print(f"{r.id}  {r.name}{scope}", file=out)
        print(f"    {r.summary}", file=out)
        if r.rationale:
            print(f"    why: {r.rationale}", file=out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.lint",
        description=(
            "graftlint — static analysis for JAX hazards (PRNG key "
            "reuse, hidden host syncs, recompile traps, impure traced "
            "code, stray debug callbacks)."
        ),
    )
    parser.add_argument(
        "targets", nargs="*", default=["symbolicregression_jl_tpu"],
        help="files or directories to lint (default: the package)",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        _print_catalog(sys.stdout)
        return 0

    select = None
    if args.select:
        select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = select - set(RULES)
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2

    findings = lint_paths(
        args.targets,
        select=select,
        on_error=lambda p, e: print(f"{p}: {e}", file=sys.stderr),
    )
    for f in findings:
        print(f)
    if findings:
        print(
            f"\ngraftlint: {len(findings)} finding(s). Suppress a "
            f"legitimate line with `# graftlint: disable=<RULE>`.",
            file=sys.stderr,
        )
        return 1
    return 0
