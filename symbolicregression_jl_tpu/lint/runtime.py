"""Runtime invariant auditing for the postfix program tables + hot-loop
budget guards.

Three tools, all debug-tier (none belongs in a jitted hot path):

- :func:`validate_programs` / :func:`check_programs` — structural
  invariants of the padded postfix encoding (see ops/encoding.py): a
  corrupt table evaluates without error but silently produces garbage
  genomes, so mutation/crossover machinery changes should run under this
  checker (``options.debug_checks`` wires it into the Engine).
- :func:`compile_count_guard` — context manager bounding how many XLA
  compilations (traces) may happen in a region; pins the "warm evolve
  cycle compiles nothing" property.
- :func:`no_transfer` — thin wrapper over :func:`jax.transfer_guard`
  asserting no *implicit* host↔device transfers in a region (explicit
  ``jnp.asarray``/``device_get`` calls stay allowed).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax

from ..ops.encoding import LEAF_CONST, LEAF_PARAM, LEAF_VAR, MAX_ARITY

__all__ = [
    "ProgramInvariantError",
    "CompileBudgetExceeded",
    "CompileStats",
    "check_programs",
    "validate_programs",
    "compile_count_guard",
    "no_transfer",
]


class ProgramInvariantError(AssertionError):
    """A postfix program table violates a structural invariant."""


class CompileBudgetExceeded(AssertionError):
    """More XLA compilations happened in a guarded region than allowed."""


def _resolve_nops(operators) -> Tuple[int, ...]:
    """Per-arity operator counts (index d-1 = arity d) from an
    OperatorSet, a dict {arity: n}, or a plain sequence."""
    if operators is None:
        return ()
    if hasattr(operators, "nops_tuple"):
        return tuple(operators.nops_tuple())
    if isinstance(operators, dict):
        ma = max(operators) if operators else 0
        return tuple(int(operators.get(d, 0)) for d in range(1, ma + 1))
    return tuple(int(n) for n in operators)


def _subtree_sizes(arity: np.ndarray, D: np.ndarray) -> np.ndarray:
    """Subtree sizes per slot via the postfix prefix-sum identity
    (numpy mirror of ops/encoding._structure_from_arity):
    ``start(k) = max{ j <= k : D(j-1) == D(k) - 1 }``, size = k-start+1.

    O(N*L) memory and one Python loop over the L slots — the auditor
    runs on full device-scale populations (debug_checks pulls every
    island every iteration), so an [N, L, L] one-hot formulation is out.
    """
    N, L = arity.shape
    Dm1 = D - (1 - arity)  # exclusive prefix sum
    rows = np.arange(N)
    # last_at[n, h] = most recent slot j <= k with Dm1[n, j] == h.
    # Heights live in [0, L]; one extra bucket absorbs clipped garbage.
    last_at = np.full((N, L + 2), -1, np.int64)
    start = np.empty((N, L), np.int64)
    for k in range(L):
        last_at[rows, np.clip(Dm1[:, k], 0, L + 1)] = k
        start[:, k] = last_at[rows, np.clip(D[:, k] - 1, 0, L + 1)]
    start = np.clip(start, 0, np.arange(L)[None, :])
    return np.arange(L)[None, :] - start + 1, start


def check_programs(
    trees,
    operators=None,
    *,
    nfeatures: Optional[int] = None,
    n_params: Optional[int] = None,
    strict_padding: bool = False,
    max_report: int = 10,
) -> List[str]:
    """Check every tree in a (arbitrarily batched) TreeBatch; return a
    list of human-readable violation strings (empty = all invariants
    hold). Device arrays are pulled to host — debug-tier only.

    Invariants (ops/encoding.py module docstring):

    1. ``1 <= length <= L`` — at least the root slot is used.
    2. ``0 <= arity <= MAX_ARITY`` everywhere.
    3. op-code ranges: leaves in {LEAF_CONST, LEAF_VAR, LEAF_PARAM};
       arity-d operators index into the d-ary table (``op < nops[d-1]``).
    4. postfix stack discipline over used slots: the running stack
       height ``D(k) = sum_{j<=k} (1 - arity_j)`` stays >= 1 and ends at
       exactly 1 — equivalently every subtree occupies the contiguous
       span ``[k - size_k + 1, k]``.
    5. span recurrence: for every operator node the child subtree spans
       tile its own span exactly (binary: ``size_k = 1 + size_{k-1} +
       size_{k-1-size_{k-1}}``; unary: ``size_k = 1 + size_{k-1}``).
    6. padding cleanliness: slots ``k >= length`` hold arity 0 — the
       structural derivations (ops/encoding._structure_from_arity) run
       over the full slot axis, so a stray operator arity in padding
       corrupts the prefix-sum algebra for the whole tree. With
       ``strict_padding=True`` op/feat/const must be zeroed too
       (canonical form; the generators do not maintain this, but
       canonicalized tables dedup/hash exactly).
    7. optional leaf-payload ranges: variable features in
       ``[0, nfeatures)``, parameter indices in ``[0, n_params)``.
    """
    arity = np.asarray(trees.arity)
    op = np.asarray(trees.op)
    feat = np.asarray(trees.feat)
    const = np.asarray(trees.const)
    length = np.asarray(trees.length)

    L = arity.shape[-1]
    arity = arity.reshape(-1, L).astype(np.int64)
    op = op.reshape(-1, L).astype(np.int64)
    feat = feat.reshape(-1, L).astype(np.int64)
    const = const.reshape(-1, L)
    length = length.reshape(-1).astype(np.int64)
    N = arity.shape[0]
    nops = _resolve_nops(operators)

    msgs: List[str] = []

    def report(mask: np.ndarray, fmt) -> None:
        idx = np.flatnonzero(mask)
        room = max(0, max_report - len(msgs))
        for i in idx[:room]:
            msgs.append(fmt(int(i)))
        omitted = len(idx) - min(room, len(idx))
        if omitted > 0:
            msgs.append(
                f"... (+{omitted} more of this kind, report truncated)"
            )

    # 1. length bounds
    bad_len = (length < 1) | (length > L)
    report(bad_len, lambda i: (
        f"tree {i}: length {length[i]} outside [1, {L}]"
    ))
    if bad_len.any():
        # downstream masks index with length; clamp to keep going
        length = np.clip(length, 1, L)

    used = np.arange(L)[None, :] < length[:, None]

    # 2. arity range
    bad_arity = used & ((arity < 0) | (arity > MAX_ARITY))
    report(bad_arity.any(axis=1), lambda i: (
        f"tree {i}: arity outside [0, {MAX_ARITY}] at slots "
        f"{np.flatnonzero(bad_arity[i]).tolist()}"
    ))

    # 3. op-code ranges
    is_leaf = arity == 0
    bad_leaf = used & is_leaf & (
        (op < LEAF_CONST) | (op > LEAF_PARAM)
    )
    report(bad_leaf.any(axis=1), lambda i: (
        f"tree {i}: leaf op code outside "
        f"{{{LEAF_CONST},{LEAF_VAR},{LEAF_PARAM}}} at slots "
        f"{np.flatnonzero(bad_leaf[i]).tolist()}"
    ))
    if nops:
        for d in range(1, len(nops) + 1):
            sel = used & (arity == d)
            bad_op = sel & ((op < 0) | (op >= max(nops[d - 1], 1)))
            if nops[d - 1] == 0:
                bad_op = sel  # arity with no operators at all
            report(bad_op.any(axis=1), lambda i, d=d, bad=bad_op: (
                f"tree {i}: arity-{d} op index outside "
                f"[0, {nops[d - 1]}) at slots "
                f"{np.flatnonzero(bad[i]).tolist()}"
            ))

    # 4. stack discipline (subtree contiguity)
    safe_arity = np.clip(arity, 0, MAX_ARITY)
    step = np.where(used, 1 - safe_arity, 0)
    D = np.cumsum(step, axis=1)
    under = used & (D < 1)
    report(under.any(axis=1), lambda i: (
        f"tree {i}: postfix stack underflow at slot "
        f"{int(np.flatnonzero(under[i])[0])} (operator consumes "
        f"operands that don't exist — subtree contiguity broken)"
    ))
    final = D[np.arange(N), length - 1]
    bad_final = (~under.any(axis=1)) & (final != 1)
    report(bad_final, lambda i: (
        f"tree {i}: postfix stack ends at height {int(final[i])} "
        f"(expected 1) — {int(final[i]) - 1} unrooted subtree(s)"
    ))

    # 5. span recurrence (independent contiguity cross-check via the
    #    [k - size_k + 1, k] property)
    structurally_ok = ~(under.any(axis=1) | bad_final | bad_arity.any(axis=1))
    if structurally_ok.any():
        sizes, start = _subtree_sizes(safe_arity, D)
        k = np.arange(L)[None, :]
        un = used & (safe_arity == 1)
        bin_ = used & (safe_arity == 2)
        prev = np.maximum(k - 1, 0)
        size_prev = np.take_along_axis(sizes, prev, axis=1)
        left_root = np.maximum(k - 1 - size_prev, 0)
        size_left = np.take_along_axis(sizes, left_root, axis=1)
        bad_un = un & (sizes != 1 + size_prev)
        bad_bin = bin_ & (sizes != 1 + size_prev + size_left)
        bad_span = (bad_un | bad_bin) & structurally_ok[:, None]
        report(bad_span.any(axis=1), lambda i: (
            f"tree {i}: child spans do not tile the subtree span at "
            f"slots {np.flatnonzero(bad_span[i]).tolist()}"
        ))

    # 6. padding cleanliness
    pad = ~used
    dirty_arity = pad & (arity != 0)
    report(dirty_arity.any(axis=1), lambda i: (
        f"tree {i}: nonzero arity in padding slots "
        f"{np.flatnonzero(dirty_arity[i]).tolist()} (corrupts the "
        f"full-axis structural prefix sums)"
    ))
    if strict_padding:
        dirty = pad & ((op != 0) | (feat != 0) | (const != 0))
        report(dirty.any(axis=1), lambda i: (
            f"tree {i}: padding slots "
            f"{np.flatnonzero(dirty[i]).tolist()} not zeroed "
            f"(non-canonical table: hashing/dedup equality breaks)"
        ))

    # 7. leaf payload ranges
    if nfeatures is not None:
        var = used & is_leaf & (op == LEAF_VAR)
        bad_feat = var & ((feat < 0) | (feat >= nfeatures))
        report(bad_feat.any(axis=1), lambda i: (
            f"tree {i}: variable feature outside [0, {nfeatures}) at "
            f"slots {np.flatnonzero(bad_feat[i]).tolist()}"
        ))
    if n_params is not None:
        par = used & is_leaf & (op == LEAF_PARAM)
        bad_par = par & ((feat < 0) | (feat >= max(n_params, 1)))
        if n_params == 0:
            bad_par = par
        report(bad_par.any(axis=1), lambda i: (
            f"tree {i}: parameter index outside [0, {n_params}) at "
            f"slots {np.flatnonzero(bad_par[i]).tolist()}"
        ))

    return msgs


def validate_programs(
    trees,
    operators=None,
    *,
    nfeatures: Optional[int] = None,
    n_params: Optional[int] = None,
    where: str = "",
    strict_padding: bool = False,
    max_report: int = 10,
) -> int:
    """Raise :class:`ProgramInvariantError` on any violation; return the
    number of trees checked when clean. Debug wrapper for
    mutation/crossover outputs (``options.debug_checks=True`` calls this
    on every engine state)."""
    msgs = check_programs(
        trees, operators, nfeatures=nfeatures, n_params=n_params,
        strict_padding=strict_padding, max_report=max_report,
    )
    if msgs:
        ctx = f" [{where}]" if where else ""
        raise ProgramInvariantError(
            f"postfix program table invariants violated{ctx}:\n  "
            + "\n  ".join(msgs)
        )
    n = int(np.prod(np.asarray(trees.length).shape)) or 1
    return n


# ---------------------------------------------------------------------------
# Hot-loop budget guards
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompileStats:
    """Counters filled in by :func:`compile_count_guard`.

    ``traces`` counts end-to-end jaxpr traces — every compilation starts
    with one, *including* programs served from the persistent
    compilation cache (which still pay trace + lowering, just not XLA).
    ``backend_compiles`` counts actual XLA backend compilations. A warm
    jitted hot loop should add ZERO of either."""

    traces: int = 0
    backend_compiles: int = 0


@contextlib.contextmanager
def compile_count_guard(
    max_compiles: Optional[int] = None, *, what: str = "guarded region"
) -> Iterator[CompileStats]:
    """Count XLA compilations in a region via ``jax.monitoring`` events;
    raise :class:`CompileBudgetExceeded` when ``max_compiles`` (compared
    against the trace count) is exceeded.

    Usage::

        engine.run_iteration(state, data, maxsize)       # warm-up
        with compile_count_guard(max_compiles=0):
            engine.run_iteration(state2, data, maxsize)  # must be cached
    """
    from jax._src import monitoring

    stats = CompileStats()
    active = [True]

    def on_duration(name: str, secs: float, **kw) -> None:
        if not active[0]:
            return
        if name.endswith("jaxpr_trace_duration"):
            stats.traces += 1
        elif name.endswith("backend_compile_duration") or name.endswith(
            "backend_compile_time"
        ):
            stats.backend_compiles += 1

    monitoring.register_event_duration_secs_listener(on_duration)
    try:
        yield stats
    finally:
        active[0] = False
        unreg = getattr(
            monitoring, "_unregister_event_duration_listener_by_callback",
            None,
        )
        if unreg is not None:  # pragma: no branch
            try:
                unreg(on_duration)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass
    if max_compiles is not None and stats.traces > max_compiles:
        raise CompileBudgetExceeded(
            f"{what}: {stats.traces} compilation(s) "
            f"({stats.backend_compiles} reached the XLA backend), budget "
            f"is {max_compiles} — a shape/static-argument/key-dtype "
            f"change is defeating the jit cache in the hot loop"
        )


def no_transfer(level: str = "disallow"):
    """Context manager asserting no *implicit* host↔device transfers.

    Thin wrapper over :func:`jax.transfer_guard`: ``"disallow"`` raises
    on implicit transfers (e.g. ``np.asarray(device_array)``, traced
    ``float()`` casts, host scalars silently uploaded per step) while
    explicit ``jnp.asarray`` / ``jax.device_get`` / ``jax.device_put``
    remain allowed. Use ``"disallow_explicit"`` to forbid those too, or
    ``"log"`` to locate offenders without failing.
    """
    return jax.transfer_guard(level)
