"""Pack grouping + bin capacity: what may launch together, and how many.

A **pack group** is the set of requests that can share one compiled
device program: same shape bucket (after padding every member to the
bucket's row count) and the same canonical Options kwargs (the
executable-cache key is the canonical options fingerprint — different
options would build different engines, defeating the point).

The **slot cap** is the bin capacity of one launch group. graftgauge's
:class:`~..gauge.HeadroomModel` per-bucket byte prediction is the
input: each extra tenant adds roughly one more program's working state,
so the cap is ``1 + headroom_bytes // predicted_bytes`` clamped to the
policy maximum. The advisory contract from admission carries over
unchanged — a missing prediction (cold ledger, no byte limit) never
hard-rejects; it just falls back to the policy cap, and the floor is
always one tenant (the lead launches regardless).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple

__all__ = ["PackPolicy", "pack_group_key", "packable", "slot_cap"]


@dataclasses.dataclass
class PackPolicy:
    """Knobs of the packed scheduler (``SearchServer(pack=...)``).

    ``coalesce_window_s`` — how long a freshly-popped lead request waits
    for the rest of its burst before launching; late arrivals can still
    join a running cohort at iteration boundaries, so this only trades
    first-request latency against first-launch occupancy.
    ``join_poll_s`` — the cohort manager's poll interval for late joins
    while its tenants run. ``barrier_timeout_s`` — lockstep-barrier
    fallback: the barrier is scheduling-only (each tenant's numerics are
    a pure function of its own inputs), so releasing a round when a peer
    stalls is always safe.
    """

    max_tenants: int = 4
    coalesce_window_s: float = 0.05
    join_poll_s: float = 0.02
    barrier_timeout_s: float = 30.0


def packable(options_kwargs: Optional[Dict[str, Any]]) -> bool:
    """Whether a request's options are compatible with bucket padding.

    ``batching=True`` samples ``batch_size`` row indices uniformly over
    the materialized rows each cycle — pad rows would enter the sample
    and the search would no longer equal its unpadded meaning, so such
    requests run on the unpacked path (correctness over throughput).
    """
    return not bool((options_kwargs or {}).get("batching", False))


def pack_group_key(bucket: Tuple[int, int, int],
                   options_kwargs: Optional[Dict[str, Any]]) -> str:
    """Canonical co-launch key: shape bucket + exact options kwargs.

    The kwargs dict is JSON-able by the submit contract (the journal
    replays it), so a sorted dump is a stable canonical form.
    """
    return json.dumps(
        {"bucket": list(bucket), "options": options_kwargs or {}},
        sort_keys=True, separators=(",", ":"))


def slot_cap(policy: PackPolicy,
             memory_advice: Optional[Dict[str, Any]]) -> int:
    """Bin capacity of one launch group, from the headroom advisory.

    ``memory_advice`` is ``HeadroomModel.advise()``'s dict (or None):
    ``predicted_bytes`` for the bucket's program and ``headroom_bytes``
    left under the device budget. Absent either number the policy cap
    stands — the advisory becomes an input, never a hard reject.
    """
    cap = max(int(policy.max_tenants), 1)
    if not memory_advice:
        return cap
    try:
        predicted = memory_advice.get("predicted_bytes")
        headroom = memory_advice.get("headroom_bytes")
        if predicted and headroom is not None and int(predicted) > 0:
            fit = 1 + max(int(headroom), 0) // int(predicted)
            return max(1, min(cap, fit))
    except (TypeError, ValueError):
        pass
    return cap
