"""Shape-bucket padding: grow a dataset to its admission bucket's row
count with rows that are provably inert.

Why replicas and not zeros: the fused kernel's validity test
(ops/fused_eval.py) is built from an internal all-ones row mask over
every *materialized* row — weights gate the loss sum, they do NOT gate
finiteness tracking. A zero-filled pad row would run every tree through
operators at x=0 (div, log, inverse-sqrt gradients...), and one
non-finite value there would invalidate the whole tree even though the
row carries zero weight. A pad row that **replicates a real row**
cannot do that: it computes bit-for-bit the same values as its source
row, so it is finite exactly when the source row is — validity is
unchanged by construction, with zero kernel changes.

The three inertness guarantees (pinned by tests/test_pack.py):

- **loss**: the kernel zeroes zero-weight elements before the weighted
  sum (``elt = where(w > 0, elt, 0); sum(elt * w)``), so a pad row
  contributes exactly ``+0.0`` — bit-identical sums, not just close;
- **gradients**: the constant optimizer's cotangent on a zero-weight
  row is exactly 0, and the replica's forward chain is finite wherever
  the source row's is, so ``0 × finite = 0`` (never ``0 × inf = NaN``);
- **validity**: see above.

``fill`` selects WHICH real rows the pad replicates. Production always
uses ``"cyclic"``; ``"edge"`` exists so the masking-completeness test
can pin that pad *content* cannot influence a search at all (two
different fills must produce bit-identical results).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["pad_to_bucket"]


def pad_to_bucket(
    X: np.ndarray,
    y: np.ndarray,
    *,
    rows: int,
    fill: str = "cyclic",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad ``X [n, f]`` / ``y [n]`` to ``rows`` total rows.

    Returns ``(Xp, yp, weights)`` where ``weights`` is 1.0 on the ``n``
    real rows and 0.0 on the ``rows - n`` pad rows. ``fill="cyclic"``
    makes pad row ``j`` a copy of real row ``j % n``; ``fill="edge"``
    replicates the single middle row (test-only, see module docstring).
    Deterministic in (n, rows, fill) only, so a journal replay pads
    identically. ``rows == n`` returns copies with all-ones weights.
    """
    X = np.asarray(X)
    y = np.asarray(y)
    n = int(X.shape[0])
    rows = int(rows)
    if rows < n:
        raise ValueError(f"cannot pad {n} rows down to {rows}")
    if n == 0:
        raise ValueError("cannot pad an empty dataset")
    pad = rows - n
    if fill == "cyclic":
        src = np.arange(pad) % n
    elif fill == "edge":
        src = np.full(pad, n // 2)
    else:
        raise ValueError(f"unknown pad fill {fill!r}")
    Xp = np.concatenate([X, X[src]], axis=0)
    yp = np.concatenate([y, y[src]], axis=0)
    weights = np.zeros(rows, dtype=X.dtype if X.dtype.kind == "f"
                       else np.float32)
    weights[:n] = 1.0
    return Xp, yp, weights
