"""graftpack — device-level multi-tenant packing (docs/SERVING.md,
"Packed tenancy"; ROADMAP item 1).

The serve layer timeshares tenants per worker: one search runs while
queued requests wait. This package turns co-queued same-bucket requests
into **one device program's worth of concurrent work**:

- :mod:`.padding` pads a request's dataset to its pow2 admission bucket
  (serve/admission.py ``shape_bucket``) with the pad rows zero-weighted
  out of every loss/norm, so near-miss shapes share one traced+compiled
  executable instead of requiring exact row equality;
- :mod:`.scheduler` decides what may pack together (``pack_group_key``,
  ``packable``) and how many tenants one launch group may hold
  (``slot_cap`` — graftgauge's per-bucket byte prediction is the bin
  capacity input, advisory-floored at one tenant);
- :mod:`.cohort` is the lockstep launch group: tenants join, run their
  (unchanged, individually-journaled) searches in step via a
  per-iteration barrier, and peel off at iteration boundaries when they
  finish, are cancelled, or are preempted.

The packed path never changes a tenant's numerics: each search is a
pure function of its own (padded) inputs, the barrier only shapes
scheduling, and the padding itself is journaled effective
configuration (``SearchRequest.bucket_rows``/``pad_rows``) — so every
tenant's result is bit-identical to the same request run alone, and the
graftserve kill-restart-replay contract holds unchanged under packing.
"""

from .cohort import PackedCohort
from .padding import pad_to_bucket
from .scheduler import PackPolicy, pack_group_key, packable, slot_cap

__all__ = [
    "PackPolicy",
    "PackedCohort",
    "pack_group_key",
    "packable",
    "pad_to_bucket",
    "slot_cap",
]
