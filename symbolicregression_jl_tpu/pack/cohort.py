"""The lockstep launch group: N tenant searches advancing in step.

A :class:`PackedCohort` holds the live slot table of one pack launch.
Each tenant thread runs its own full ``equation_search`` (keeping its
journal records, checkpoints, ledger spans, and telemetry stream
exactly as on the unpacked path) and calls :meth:`arrive` from its
per-iteration logger probe; the barrier releases a round once every
active tenant has arrived. Because the engine (and with it every
compiled executable and jit trace) is shared through the serve
ExecutableCache and all tenants run the same program shapes, lockstep
rounds keep the device executing one program's worth of concurrent
island work instead of N interleaved cold dispatches.

Correctness stance: the barrier is **scheduling-only**. A tenant's
search result is a pure function of its own (padded) inputs and seed —
peers can arrive late, time out, join mid-flight, or peel off without
touching anyone's numerics. That is why the timeout fallback and
"leave releases the round" below are safe by construction, and why a
packed tenant is bit-identical to its solo run (tests/test_pack.py
pins this).

Rule-of-thumb lint notes (lint/concurrency.py): the cohort lock is
outside the lint/lock_order.py manifest universe (unordered by fiat,
and never held across file I/O or JAX dispatch); the only blocking
call under it is ``Condition.wait`` inside a while-predicate loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["PackedCohort"]


class PackedCohort:
    """Slot table + iteration barrier of one pack launch group."""

    def __init__(self, group_key: str, *, slot_cap: int,
                 barrier_timeout_s: float = 30.0) -> None:
        self.group_key = group_key
        self.slot_cap = max(int(slot_cap), 1)
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._lock = threading.Lock()
        self._barrier = threading.Condition(self._lock)
        self._active: Dict[int, str] = {}  # slot -> request_id
        self._next_slot = 0
        self._arrived: set = set()
        self._generation = 0
        # active-tenant count at each completed round: the occupancy
        # record (tenant-islands / capacity, per round) pack_done and
        # `bench load --packed` report
        self._rounds: List[int] = []
        self._tenants_total = 0
        self._peak = 0

    # ------------------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._active)

    def join(self, request_id: str) -> Optional[int]:
        """Claim a slot; None when the group is at its bin capacity.
        Joining mid-round simply grows the arrival quorum — the new
        tenant's first ``arrive`` closes the round like any other."""
        with self._lock:
            if len(self._active) >= self.slot_cap:
                return None
            slot = self._next_slot
            self._next_slot += 1
            self._active[slot] = request_id
            self._tenants_total += 1
            self._peak = max(self._peak, len(self._active))
            return slot

    def leave(self, slot: int) -> None:
        """Peel a tenant off at an iteration boundary (finished,
        cancelled, preempted, or failed). If the departing tenant was
        the last hold-out of the current round, the round releases."""
        with self._barrier:
            self._active.pop(slot, None)
            self._arrived.discard(slot)
            if self._active and len(self._arrived) >= len(self._active):
                self._release_round_locked()
            else:
                # waiters re-check their predicate (a shrunken quorum
                # may already be satisfied on the next arrival)
                self._barrier.notify_all()

    def arrive(self, slot: int) -> None:
        """Iteration-boundary barrier: block until every active tenant
        reaches its boundary, the round times out, or this tenant is no
        longer active. Scheduling-only — see the module docstring for
        why the timeout fallback cannot affect results."""
        with self._barrier:
            if slot not in self._active:
                return
            self._arrived.add(slot)
            gen = self._generation
            if len(self._arrived) >= len(self._active):
                self._release_round_locked()
                return
            deadline = time.monotonic() + self.barrier_timeout_s
            while (self._generation == gen and slot in self._active
                   and len(self._arrived) < len(self._active)):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._release_round_locked()
                    return
                self._barrier.wait(timeout=min(remaining, 0.1))
            if (self._generation == gen
                    and len(self._arrived) >= len(self._active)):
                self._release_round_locked()

    def _release_round_locked(self) -> None:
        self._rounds.append(len(self._active))
        self._generation += 1
        self._arrived.clear()
        self._barrier.notify_all()

    # ------------------------------------------------------------------
    def occupancy(self) -> Dict[str, Any]:
        """Launch-group summary for the ``pack_done`` serve event."""
        with self._lock:
            rounds = list(self._rounds)
            return {
                "tenants_total": self._tenants_total,
                "peak_tenants": self._peak,
                "slot_cap": self.slot_cap,
                "rounds": len(rounds),
                # mean per-round occupancy: active tenant-islands over
                # the bin capacity, averaged across lockstep rounds
                "occupancy": (
                    round(sum(rounds) / (len(rounds) * self.slot_cap), 4)
                    if rounds else None),
            }
