"""AOT mesh executables: ``jit(...).lower().compile()`` + serialization.

The compile storm is the serve layer's cold-start tax (ROADMAP item 2):
every fresh process pays minutes of XLA for the same iteration program.
AOT compilation splits trace/lower/compile from dispatch, and — where
the backend supports it — the compiled executable serializes to bytes,
so a restarted or horizontally scaled-out replica can load the program
instead of recompiling it.

``compile_iteration`` lowers the engine's already-donating jitted
iteration against concrete (state, data) avals and returns a
:class:`MeshIterationExecutable` whose ``run`` is a pure dispatch — no
tracing can ever happen on it, which also makes it the deterministic
core of the mesh scaling harness (profiling/mesh_scaling.py measures
dispatch-only throughput through it).

Serialization uses ``jax.experimental.serialize_executable`` when
present (gate with :func:`aot_serialization_supported`); the payload is
keyed by :func:`aot_cache_key` — the canonical ``options_fingerprint``
(serve/cache.py's collision rules) plus the mesh/data geometry — so a
payload can never be dispatched against a mismatched program shape.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "MeshIterationExecutable",
    "aot_cache_key",
    "aot_serialization_supported",
    "compile_iteration",
    "load_executable",
    "save_executable",
]

_PAYLOAD_VERSION = 1


def aot_serialization_supported() -> bool:
    """Whether this jax build can serialize compiled executables."""
    try:
        from jax.experimental.serialize_executable import (  # noqa: F401
            deserialize_and_load,
            serialize,
        )
    except ImportError:
        return False
    return True


def aot_cache_key(engine, rows: int) -> Optional[str]:
    """Executable identity: canonical options fingerprint (None for
    uncacheable configs — opaque callables etc., same rules as the serve
    executable cache) + the geometry the program was lowered at."""
    from ..api.checkpoint import options_fingerprint

    fp = options_fingerprint(engine.options)
    if fp is None:
        return None
    geom = (
        f"{fp}|nfeat={engine.nfeatures}|rows={int(rows)}"
        f"|islands={engine.cfg.n_islands * engine.n_island_shards}"
        f"|shards={engine.n_island_shards}"
        f"|dtype={jnp.dtype(engine.dtype).name}"
        f"|backend={jax.default_backend()}|jax={jax.__version__}"
    )
    return hashlib.sha256(geom.encode()).hexdigest()


@dataclasses.dataclass
class MeshIterationExecutable:
    """A compiled (never-retracing) mesh iteration program."""

    compiled: Any               # jax.stages.Compiled
    cache_key: Optional[str]
    n_devices: int
    # graftgauge footprint summary (footprint.summarize_compiled),
    # harvested at compile time and persisted into the serialized
    # envelope: a replica that *loads* the executable still reports the
    # same memory/cost analysis even where the deserialized Compiled
    # can't produce one (backend-optional introspection).
    analysis: Optional[dict] = None

    def run(self, state, data, cur_maxsize):
        """Dispatch one iteration. ``cur_maxsize`` must already be a
        device int32 scalar (the compiled program has no weak-type
        coercion); the input state is donated exactly when the engine's
        jit path donates (MeshPlan.resolve_donation)."""
        return self.compiled(state, data, cur_maxsize)

    def cost_analysis(self):
        try:
            out = self.compiled.cost_analysis()
        except Exception:  # noqa: BLE001 - backend-optional introspection
            out = None
        if out is None and self.analysis is not None:
            # stamped-envelope fallback: a plain dict (flops / "bytes
            # accessed"), not the live analysis object
            return self.analysis.get("cost") or None
        return out

    def memory_analysis(self):
        try:
            out = self.compiled.memory_analysis()
        except Exception:  # noqa: BLE001 - backend-optional introspection
            out = None
        if out is None and self.analysis is not None:
            # stamped-envelope fallback: a plain *_in_bytes dict
            return self.analysis.get("memory") or None
        return out


def _harvest_analysis(engine, compiled, rows: int) -> Optional[dict]:
    """Flatten the compiled program's static analyses into the
    JSON/pickle-able envelope stamp (graftgauge), including the ledger
    identity (fingerprint + geometry) so a loading replica can re-record
    the footprint without the engine in hand. Never raises."""
    try:
        from ..api.checkpoint import options_fingerprint
        from ..gauge.footprint import geometry_key, summarize_compiled

        summary = summarize_compiled(compiled)
        if summary is None:
            return None
        memory = {k: v for k, v in summary.items()
                  if k.endswith("_in_bytes")}
        cost = {}
        if "flops" in summary:
            cost["flops"] = summary["flops"]
        if "bytes_accessed" in summary:
            cost["bytes accessed"] = summary["bytes_accessed"]
        nfeatures = int(engine.nfeatures)
        return {
            "summary": summary,
            "memory": memory or None,
            "cost": cost or None,
            "fingerprint": options_fingerprint(engine.options),
            "geometry": geometry_key(rows=rows, nfeatures=nfeatures),
            "rows": int(rows),
            "nfeatures": nfeatures,
        }
    except Exception:  # noqa: BLE001 - observability is best-effort
        return None


def _record_footprint(analysis: Optional[dict], *, source: str) -> None:
    """Record a harvested/loaded analysis stamp into the process-wide
    graftgauge footprint ledger. Never raises."""
    if not analysis or not analysis.get("summary"):
        return
    try:
        from ..gauge.footprint import global_ledger

        global_ledger().record(
            analysis.get("fingerprint"), analysis.get("geometry") or "",
            analysis.get("summary"), source=source,
            rows=analysis.get("rows"), nfeatures=analysis.get("nfeatures"),
            nout=1,
        )
    except Exception:  # noqa: BLE001 - observability is best-effort
        pass


def compile_iteration(engine, state, data, cur_maxsize=None
                      ) -> MeshIterationExecutable:
    """AOT-compile the engine's single-launch iteration program against
    the concrete avals of ``(state, data)``.

    Works for both the legacy Engine and MeshEngine (the jitted
    ``_iteration`` is the override point); the compiled program bakes in
    the engine's current launch geometry, so a graftshield degrade
    (which rebuilds the jits) invalidates it — build a fresh one.

    The compile also harvests the program's memory/cost analysis into
    the graftgauge footprint ledger (source ``mesh_aot``) and stamps it
    onto the executable for the serialized envelope.
    """
    if cur_maxsize is None:
        cur_maxsize = jnp.int32(engine.cfg.maxsize)
    elif not isinstance(cur_maxsize, jax.Array):
        cur_maxsize = jnp.int32(cur_maxsize)
    lowered = engine._iteration.lower(state, data, cur_maxsize)
    compiled = lowered.compile()
    rows = int(data.y.shape[0])
    analysis = _harvest_analysis(engine, compiled, rows)
    _record_footprint(analysis, source="mesh_aot")
    return MeshIterationExecutable(
        compiled=compiled,
        cache_key=aot_cache_key(engine, rows=rows),
        n_devices=getattr(engine, "n_island_shards", 1),
        analysis=analysis,
    )


def save_executable(ex: MeshIterationExecutable, path: str) -> str:
    """Serialize a compiled iteration to ``path`` (raises RuntimeError
    when the jax build cannot serialize executables)."""
    if not aot_serialization_supported():
        raise RuntimeError(
            "this jax build cannot serialize compiled executables "
            "(jax.experimental.serialize_executable missing)")
    from jax.experimental.serialize_executable import serialize

    payload, in_tree, out_tree = serialize(ex.compiled)
    blob = pickle.dumps({
        "version": _PAYLOAD_VERSION,
        "cache_key": ex.cache_key,
        "n_devices": ex.n_devices,
        "payload": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
        # additive graftgauge stamp (version stays 1: old loaders use
        # rec.get and old payloads load with analysis=None)
        "analysis": ex.analysis,
    })
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_executable(path: str, expect_key: Optional[str] = None
                    ) -> MeshIterationExecutable:
    """Load a serialized iteration executable. ``expect_key`` (from
    :func:`aot_cache_key` on the engine you intend to drive) guards
    against dispatching a program lowered for a different config,
    geometry, backend, or jax version."""
    from jax.experimental.serialize_executable import deserialize_and_load

    with open(path, "rb") as f:
        rec = pickle.load(f)
    if rec.get("version") != _PAYLOAD_VERSION:
        raise ValueError(
            f"{path}: unknown AOT payload version {rec.get('version')!r}")
    if expect_key is not None and rec.get("cache_key") != expect_key:
        raise ValueError(
            f"{path}: executable cache key mismatch (serialized for a "
            f"different options/geometry/backend) — recompile instead")
    compiled = deserialize_and_load(
        rec["payload"], rec["in_tree"], rec["out_tree"])
    analysis = rec.get("analysis")
    if isinstance(analysis, dict):
        # a loaded replica reports the footprint too — both through the
        # executable's analysis fallbacks and on this process's ledger
        _record_footprint(analysis, source="aot_load")
    else:
        analysis = None
    return MeshIterationExecutable(
        compiled=compiled,
        cache_key=rec.get("cache_key"),
        n_devices=int(rec.get("n_devices", 1)),
        analysis=analysis,
    )
