"""graftmesh — the first-class shard_map island mesh runtime.

The legacy path (``parallel/mesh.py`` + ``evolve/engine.Engine``) leans
on GSPMD to infer collectives for the cross-island phases and forfeits
finalize-dedup whenever the island axis is sharded. This package makes
the execution plan explicit:

- :class:`MeshPlan` — the mesh axes, per-leaf ``PartitionSpec``s for
  ``SearchDeviceState``/``DeviceData``, donation and dedup-exchange
  policy, in one inspectable object.
- :class:`MeshEngine` — an :class:`~..evolve.engine.Engine` whose whole
  iteration (evolve scan AND epilogue) runs inside ``shard_map`` with
  explicit collectives: ``all_gather`` for the hall-of-fame merge and
  the migration pool, ``psum`` for eval counters and running stats, and
  per-shard finalize-dedup re-enabled (the win the legacy engine
  forfeits under sharding), plus a periodic all-gather dedup-key
  exchange emitted as ``graftscope.v1`` ``mesh`` events.
- :mod:`.aot` — AOT ``jit(...).lower().compile()`` mesh executables
  with serialization hooks (the serve compile-storm feeder).
- :mod:`.dryrun` — the fast CI dryrun tier on a virtual CPU mesh (the
  MULTICHIP artifact producer).

See docs/SCALING.md.
"""

from .plan import MeshPlan
from .engine import MeshEngine

__all__ = ["MeshPlan", "MeshEngine"]
