"""The mesh dryrun tier: fast, budgeted, artifact-producing.

``python -m symbolicregression_jl_tpu.mesh.dryrun --devices 8 --out f``
runs the mesh runtime end-to-end on an 8-device mesh — self-provisioning
a virtual CPU mesh (``--xla_force_host_platform_device_count``) in a
subprocess when the current process has fewer devices — and writes the
MULTICHIP-artifact JSON (``n_devices`` / ``rc`` / ``ok`` / ``legs``)
that ``bench trend`` folds into the trajectory.

Legs (each under a graftshield watchdog budget, SR_DRYRUN_LEG_BUDGET
seconds, so a compile runaway aborts with a thread dump instead of an
opaque external rc=124 — the MULTICHIP_r05 failure mode):

- ``mesh-jnp``       — jnp-interpreter iteration inside shard_map over
  all devices; asserts finite populations, a decodable hall of fame,
  and cross-shard migration mixing (the explicit all-gather provably
  moved genomes between shards).
- ``mesh-turbo-dedup`` — fused (Pallas, interpret off-TPU) kernels
  inside shard_map WITH per-shard finalize-dedup enabled (the legacy
  engine forfeits it under sharding), plus a cross-shard dedup-key
  exchange with its invariants checked.
- ``mesh-aot``       — AOT ``lower().compile()`` of the mesh iteration,
  one dispatched iteration through the executable, and (where the
  backend supports it) a serialize→load round-trip.
- ``legacy-turbo`` / ``legacy-template`` / ``legacy-datagrid`` — the
  DEFAULT (mesh_runtime=False) GSPMD runtime's sharded layouts the
  pre-mesh dryrun covered: plain and template expressions on the fused
  path under island sharding, and the (island, data) grid whose loss
  reduction lowers to a psum over the data axis.

This is the CI tier: small shapes, per-leg budgets kept. The measured
scaling curve lives in profiling/mesh_scaling.py (docs/SCALING.md).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["make_dryrun_problem", "run_dryrun",
           "virtual_cpu_mesh_env", "main"]

def _leg_budget_s() -> float:
    return float(os.environ.get("SR_DRYRUN_LEG_BUDGET", "240"))


def _legs(fast: bool):
    legs = [("mesh-jnp", _leg_jnp)]
    if not fast:
        legs += [("mesh-turbo-dedup", _leg_turbo_dedup),
                 ("mesh-staged", _leg_staged),
                 ("mesh-aot", _leg_aot),
                 ("legacy-turbo", _leg_legacy_turbo),
                 ("legacy-template", _leg_legacy_template),
                 ("legacy-datagrid", _leg_legacy_datagrid)]
    return legs


def _total_budget_s(fast: bool) -> float:
    """Whole-dryrun backstop (subprocess startup included). Derived
    from the per-leg budget so raising SR_DRYRUN_LEG_BUDGET can never
    make legally-budgeted legs exceed the total and reproduce the
    opaque rc=124 this tier exists to eliminate; SR_DRYRUN_BUDGET
    overrides explicitly."""
    explicit = float(os.environ.get("SR_DRYRUN_BUDGET", "0"))
    if explicit > 0:
        return explicit
    return max(1800.0, len(_legs(fast)) * _leg_budget_s() + 300.0)


def make_dryrun_problem(n_rows: int, nfeatures: int = 5, seed: int = 0):
    """The bench-family synthetic problem (same formula as bench.py's
    headline workload) — the ONE copy shared by the dryrun legs,
    ``__graft_entry__``, and ``profiling/mesh_scaling.py``, so all
    three tiers measure the same problem."""
    import numpy as np

    rng = np.random.default_rng(seed)
    X = rng.uniform(-3.0, 3.0, (n_rows, nfeatures)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
    ).astype(np.float32)
    return X, y


def virtual_cpu_mesh_env(n_devices: int, base_env=None) -> Dict[str, str]:
    """A child-process env forcing an ``n_devices`` virtual CPU mesh:
    any existing host-device-count flag is replaced, JAX_PLATFORMS is
    pinned to cpu. Shared by the dryrun subprocess and the scaling
    harness (profiling/mesh_scaling.py) so the two can't drift."""
    env = dict(base_env if base_env is not None else os.environ)
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _options(n_island_shards: int, turbo: bool, expression_spec=None,
             **extra):
    from ..core.options import Options

    return Options(
        expression_spec=expression_spec,
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos"],
        # Shapes sized for the ~5 min driver budget: compile time
        # dominates this artifact and scales with maxsize (scan depth)
        # and per-island width; the assertions only need non-trivial
        # populations (same sizing rationale as the legacy dryrun).
        maxsize=10,
        populations=2 * n_island_shards,  # 2 islands per shard
        population_size=32,
        ncycles_per_iteration=3,
        tournament_selection_n=8,
        optimizer_probability=0.5,
        optimizer_iterations=2,
        optimizer_nrestarts=1,
        # heavy migration so the cross-shard mixing assertion has teeth
        fraction_replaced=0.3,
        save_to_file=False,
        turbo=turbo,
        **extra,
    )


def _build(n_island_shards: int, turbo: bool, sharded_dedup: bool = True,
           **opt_extra):
    import jax

    from ..core.dataset import make_dataset
    from .engine import MeshEngine
    from .plan import MeshPlan

    from .. import search_key

    options = _options(n_island_shards, turbo, **opt_extra)
    X, y = make_dryrun_problem(512)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    plan = MeshPlan.build(
        jax.devices()[:n_island_shards], n_island_shards=n_island_shards,
        sharded_dedup=sharded_dedup,
    )
    engine = MeshEngine(options, ds.nfeatures, plan)
    state = engine.init_state(search_key(0), ds.data, options.populations)
    state = plan.place_state(state)
    data = plan.place_data(ds.data)
    return engine, state, data, options


def _check_populations(state, options, template: bool = False) -> None:
    import numpy as onp

    import jax

    from ..ops.encoding import decode_tree

    cost = onp.asarray(jax.device_get(state.pops.cost))
    loss = onp.asarray(jax.device_get(state.pops.loss))
    assert not onp.isnan(cost).any(), "NaN costs after mesh iteration"
    assert not onp.isnan(loss).any(), "NaN losses after mesh iteration"
    assert onp.isfinite(cost).mean() > 0.5, (
        f"only {onp.isfinite(cost).mean():.0%} finite costs"
    )
    hof = jax.device_get(state.hof)
    exists = onp.asarray(hof.exists)
    assert exists.any(), "hall of fame empty after 2 mesh iterations"
    for ci in onp.nonzero(exists)[0]:
        if template:
            # template members carry a [K, L] key axis: decode each
            # subexpression row
            for k in range(onp.asarray(hof.trees.arity).shape[1]):
                decode_tree(
                    onp.asarray(hof.trees.arity[ci, k]),
                    onp.asarray(hof.trees.op[ci, k]),
                    onp.asarray(hof.trees.feat[ci, k]),
                    onp.asarray(hof.trees.const[ci, k]),
                    int(hof.trees.length[ci, k]),
                    options.operators,
                )
            continue
        tree = decode_tree(
            onp.asarray(hof.trees.arity[ci]),
            onp.asarray(hof.trees.op[ci]),
            onp.asarray(hof.trees.feat[ci]),
            onp.asarray(hof.trees.const[ci]),
            int(hof.trees.length[ci]),
            options.operators,
        )  # raises on malformed encodings
        assert tree.count_nodes() == int(hof.trees.length[ci])
    assert onp.isfinite(
        onp.asarray(hof.cost)[exists]).all(), "non-finite HoF costs"


def _check_migration_mixed(state, options, n_island_shards: int) -> None:
    """Identical non-trivial trees must appear on islands of DIFFERENT
    shards after 2 heavy-migration iterations — the explicit pool
    all-gather provably moved genomes across the mesh."""
    import numpy as onp

    import jax

    tr = jax.device_get(state.pops.trees)
    I = options.populations
    per_shard = I // n_island_shards
    keys = set()
    arity, op, feat, length = (
        onp.asarray(tr.arity), onp.asarray(tr.op), onp.asarray(tr.feat),
        onp.asarray(tr.length))
    for i in range(arity.shape[0]):
        for p in range(arity.shape[1]):
            ln = int(length[i, p])
            if ln <= 1:
                continue  # trivial leaves collide by chance
            keys.add((
                i // per_shard,
                tuple(arity[i, p][:ln].tolist()),
                tuple(op[i, p][:ln].tolist()),
                tuple(feat[i, p][:ln].tolist()),
            ))
    by_tree: Dict[tuple, set] = {}
    for shard, *rest in keys:
        by_tree.setdefault(tuple(rest), set()).add(shard)
    crossed = sum(1 for s in by_tree.values() if len(s) > 1)
    assert crossed > 0, (
        "no identical non-trivial trees shared across island shards — "
        "mesh migration does not mix across the mesh"
    )


def _leg_jnp(n_devices: int) -> None:
    import jax

    engine, state, data, options = _build(n_devices, turbo=False)
    for _ in range(2):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    _check_populations(state, options)
    if n_devices > 1:
        _check_migration_mixed(state, options, n_devices)


def _leg_staged(n_devices: int) -> None:
    """graftstage on the mesh runtime (docs/PRECISION.md): staged
    sample-then-rescore candidate eval inside shard_map. The population
    checks below pin the staged contract — every population/HoF cost is
    a finite FULL-dataset value (no NaN-cost unrescored candidate ever
    replaced a parent), migration still mixes across shards."""
    import jax

    engine, state, data, options = _build(
        n_devices, turbo=True,
        staged_eval=True, staged_sample_fraction=0.25,
        rescore_fraction=0.3,
    )
    assert engine.cfg.staged_eval, "staged leg must run the staged path"
    for _ in range(2):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    _check_populations(state, options)
    if n_devices > 1:
        _check_migration_mixed(state, options, n_devices)


def _leg_turbo_dedup(n_devices: int) -> None:
    import jax
    import numpy as onp

    engine, state, data, options = _build(n_devices, turbo=True)
    assert engine.cfg.turbo, "turbo leg must run the fused path"
    assert engine._use_dedup(sharded=n_devices > 1), (
        "mesh runtime must keep finalize-dedup enabled under sharding"
    )
    for _ in range(2):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    _check_populations(state, options)
    # dedup on/off must be result-NEUTRAL (duplicates copy their group
    # leader's bit-identical result): rerun the identical search with
    # sharded_dedup off and compare bit-for-bit
    engine2, state2, data2, _ = _build(
        n_devices, turbo=True, sharded_dedup=False)
    assert not engine2._use_dedup(sharded=n_devices > 1)
    for _ in range(2):
        state2 = engine2.run_iteration(state2, data2, options.maxsize)
    jax.block_until_ready(state2.pops.cost)
    a = jax.device_get((state.pops, state.hof, state.num_evals))
    b = jax.device_get((state2.pops, state2.hof, state2.num_evals))
    for xa, xb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert onp.array_equal(onp.asarray(xa), onp.asarray(xb)), (
            "sharded finalize-dedup changed the search result"
        )
    print("dryrun dedup on/off: bit-identical")
    ex = engine.dedup_exchange(state)
    assert ex["global_unique"] <= ex["shard_unique"] <= ex["rows"], ex
    assert ex["rows"] == options.populations * options.population_size, ex
    print(f"dryrun dedup exchange: {ex['rows']} rows, "
          f"{ex['shard_unique']} shard-unique, "
          f"{ex['global_unique']} global-unique, "
          f"{ex['exchanged_bytes']} B in {ex['exchange_time_s']:.3f}s")


def _run_legacy_runtime(n_devices: int, *, mode: str) -> None:
    """The DEFAULT (mesh_runtime=False) runtime's sharded layouts the
    pre-mesh dryrun covered and every user still gets: templates on the
    fused path under island sharding, and the (island, data) grid whose
    loss reduction lowers to a psum over the data axis. A regression in
    the legacy GSPMD runtime must redden the MULTICHIP artifact too.
    (Three separately-budgeted legs — together they exceed one default
    leg budget.)"""
    import jax

    from .. import search_key
    from ..core.dataset import make_dataset
    from ..evolve.engine import Engine
    from ..models import template_spec
    from ..parallel.mesh import (
        make_mesh,
        shard_device_data,
        shard_search_state,
    )

    def run_one(n_island_shards: int, n_data_shards: int,
                turbo: bool, template: bool) -> None:
        mesh = make_mesh(
            jax.devices()[: n_island_shards * n_data_shards],
            n_island_shards=n_island_shards, n_data_shards=n_data_shards)
        spec = None
        if template:
            spec = template_spec(expressions=("f", "g"))(
                lambda f, g, x1, x2, x3, x4, x5: f(x1, x2) + g(x3))
        options = _options(n_island_shards, turbo, expression_spec=spec)
        X, y = make_dryrun_problem(512)
        ds = make_dataset(X, y)
        ds.update_baseline_loss(options.elementwise_loss)
        engine = Engine(options, ds.nfeatures,
                        n_data_shards=n_data_shards,
                        n_island_shards=n_island_shards, mesh=mesh,
                        template=spec.structure if spec else None)
        if turbo:
            assert engine.cfg.turbo and engine._shard_islands, (
                "legacy turbo leg must take the fused shard_map path"
            )
        data = shard_device_data(ds.data, mesh)
        state = engine.init_state(
            search_key(0), data, options.populations)
        state = shard_search_state(state, mesh)
        for _ in range(2):
            state = engine.run_iteration(state, data, options.maxsize)
        jax.block_until_ready(state.pops.cost)
        _check_populations(state, options, template=template)

    if mode == "turbo":
        # plain expressions on the fused path under island sharding —
        # the default runtime every mesh_runtime=False TPU user gets
        run_one(n_devices, 1, turbo=True, template=False)
    elif mode == "template":
        # templates on the fused path under island sharding (round-4
        # verdict item 8: no sharded layout loses the fused path)
        run_one(n_devices, 1, turbo=True, template=True)
    elif n_devices >= 4 and n_devices % 2 == 0:
        # the (island, data) grid on the jnp path: rows sharded over
        # the data axis, loss reduction -> psum over ICI
        run_one(n_devices // 2, 2, turbo=False, template=False)


def _leg_legacy_turbo(n_devices: int) -> None:
    _run_legacy_runtime(n_devices, mode="turbo")


def _leg_legacy_template(n_devices: int) -> None:
    _run_legacy_runtime(n_devices, mode="template")


def _leg_legacy_datagrid(n_devices: int) -> None:
    _run_legacy_runtime(n_devices, mode="datagrid")


def _leg_aot(n_devices: int) -> None:
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as onp

    from .aot import (
        aot_serialization_supported,
        compile_iteration,
        load_executable,
        save_executable,
    )

    engine, state, data, options = _build(n_devices, turbo=False)
    ex = compile_iteration(engine, state, data)
    out = ex.run(state, data, jnp.int32(options.maxsize))
    jax.block_until_ready(out.pops.cost)
    assert not onp.isnan(onp.asarray(jax.device_get(out.pops.cost))).any()
    if not aot_serialization_supported():
        print("dryrun aot: serialization unsupported on this jax build; "
              "compile+dispatch only")
        return
    with tempfile.TemporaryDirectory() as d:
        path = save_executable(ex, os.path.join(d, "iteration.aotx"))
        ex2 = load_executable(path, expect_key=ex.cache_key)
        # a fresh state: the executable donates its input
        engine2, state2, data2, _ = _build(n_devices, turbo=False)
        del engine2
        out2 = ex2.run(state2, data2, jnp.int32(options.maxsize))
        jax.block_until_ready(out2.pops.cost)
        assert not onp.isnan(
            onp.asarray(jax.device_get(out2.pops.cost))).any()
    print(f"dryrun aot: serialize/load round-trip OK "
          f"(key {ex.cache_key and ex.cache_key[:12]})")


def _impl(n_devices: int, fast: bool = False,
          on_abort=None) -> List[Tuple[str, float]]:
    """Run the legs in-process (devices must already exist). Returns
    [(leg, seconds)]; raises (or os._exit via the watchdog) on failure."""
    import jax

    from ..shield.watchdog import Watchdog

    devices = jax.devices()
    assert len(devices) >= n_devices, (
        f"need {n_devices} devices, found {len(devices)}"
    )
    assert jax.process_count() == 1, (
        "the dryrun tier is single-process; multi-host readiness is "
        "parallel/multihost.py + the same SPMD program (docs/SCALING.md)"
    )

    leg_budget = _leg_budget_s()

    def abort(dump: str) -> None:
        sys.stderr.write(dump)
        sys.stderr.flush()
        if on_abort is not None:
            try:
                on_abort(dump)
            except Exception:  # the red artifact is best-effort here
                pass
        os._exit(3)

    legs = _legs(fast)
    wd = Watchdog(on_timeout=abort)
    timings: List[Tuple[str, float]] = []
    for name, leg in legs:
        t0 = time.time()
        with wd.phase(name, leg_budget):
            leg(n_devices)
        dt = time.time() - t0
        timings.append((name, dt))
        print(f"dryrun leg {name}: {dt:.1f}s (budget {leg_budget:.0f}s)",
              flush=True)
    wd.stop()
    return timings


def _child_env(n_devices: int) -> Dict[str, str]:
    env = virtual_cpu_mesh_env(n_devices)
    # compile-bound correctness artifact, never a perf measurement:
    # trade XLA optimization effort for compile time (see
    # api/search._apply_compile_effort's measurements)
    env.setdefault("SR_XLA_EFFORT", "-1.0")
    return env


def _write_artifact(path: str, rec: Dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)


def run_dryrun(n_devices: int = 8, fast: bool = False,
               out: Optional[str] = None) -> Dict:
    """Run the dryrun (subprocess-provisioning a virtual CPU mesh when
    this process lacks devices) and return the MULTICHIP artifact
    record. ``out``: artifact path — written by the caller on return,
    AND by the in-process watchdog abort handler (which os._exits and
    would otherwise leave a real-hardware timeout with no artifact at
    all)."""
    import jax

    rec: Dict = {"n_devices": n_devices, "rc": 0, "ok": True,
                 "skipped": False, "tail": "", "legs": {}}
    if len(jax.devices()) >= n_devices:
        def on_abort(dump: str) -> None:
            red = dict(rec)
            red.update(rc=3, ok=False, tail=dump[-2000:])
            if out:
                _write_artifact(out, red)

        try:
            rec["legs"] = dict(
                _impl(n_devices, fast=fast, on_abort=on_abort))
        except Exception as e:  # noqa: BLE001 - artifact must record it
            # (KeyboardInterrupt/SystemExit propagate: an operator's
            # Ctrl-C must abort, not write a misleading red artifact)
            rec.update(rc=1, ok=False, tail=f"{type(e).__name__}: {e}")
        return rec

    cmd = [sys.executable, "-m", "symbolicregression_jl_tpu.mesh.dryrun",
           "--child", "--devices", str(n_devices)]
    if fast:
        cmd.append("--fast")
    total_budget = _total_budget_s(fast)
    try:
        proc = subprocess.run(
            cmd, env=_child_env(n_devices), capture_output=True, text=True,
            timeout=total_budget,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = (e.stdout or b"").decode() if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        err = f"dryrun subprocess budget {total_budget:.0f}s exceeded"
    for line in out.splitlines():
        if line.startswith("dryrun "):
            print(line, flush=True)
        if line.startswith("dryrun leg "):
            try:
                name = line.split("dryrun leg ", 1)[1].split(":", 1)[0]
                secs = float(line.split(":", 1)[1].split("s", 1)[0])
                rec["legs"][name] = secs
            except (IndexError, ValueError):
                pass
    rec.update(
        rc=rc, ok=(rc == 0),
        tail=(err[-2000:] if rc != 0 else err[-500:]),
    )
    return rec


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.mesh.dryrun",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="write the MULTICHIP artifact JSON here")
    ap.add_argument("--fast", action="store_true",
                    help="mesh-jnp leg only (the tools/check.sh tier)")
    ap.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)  # internal subprocess entry
    args = ap.parse_args(argv)

    if args.child:
        # Force the virtual CPU mesh before first jax use — some
        # environments ship a sitecustomize that force-registers an
        # accelerator platform over JAX_PLATFORMS (same re-pin the
        # legacy dryrun child does).
        import jax

        jax.config.update("jax_platforms", "cpu")
        from ..api.search import _apply_compile_effort

        try:
            _apply_compile_effort()
        except AttributeError:  # jax too old for the effort knob
            pass
        _impl(args.devices, fast=args.fast)
        print(f"mesh dryrun({args.devices}) OK (virtual CPU mesh)")
        return 0

    rec = run_dryrun(args.devices, fast=args.fast, out=args.out)
    if args.out:
        _write_artifact(args.out, rec)
        print(f"wrote {args.out}")
    status = "green" if rec["ok"] else f"RED rc={rec['rc']}"
    print(f"mesh dryrun: {rec['n_devices']} device(s) [{status}]")
    if not rec["ok"]:
        sys.stderr.write(rec["tail"] + "\n")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
