"""MeshEngine: the whole iteration inside shard_map, collectives explicit.

The legacy :class:`~..evolve.engine.Engine` runs only the island-local
phases under ``shard_map`` (and only on the Pallas path), leaving GSPMD
to infer the cross-island collectives — and forfeits finalize-dedup
whenever the island axis is sharded. The mesh runtime makes the plan
explicit and closes that gap:

- evolve scan AND iteration epilogue run inside ``shard_map`` over the
  :class:`~.plan.MeshPlan`'s island axis, jnp path included;
- cross-shard phases use explicit collectives: ``all_gather`` for the
  hall-of-fame merge inputs and the migration pool, ``psum`` for eval
  counters and telemetry, ``axis_index`` + ``dynamic_slice`` to carve
  the shard's islands back out of the (replicated) migrated pool;
- **sharded finalize-dedup**: each shard dedups its local finalize
  batch every iteration (exact — duplicates copy their group leader's
  bit-identical result, ops/fused_eval.fused_loss_dedup), re-enabling
  the ~1.03–1.15× finalize win the legacy engine forfeits under
  sharding; a periodic cross-shard **dedup-key exchange**
  (:meth:`MeshEngine.dedup_exchange`) all-gathers member identity keys
  to report the residual cross-shard duplication as graftscope ``mesh``
  events.

Determinism contract: all iteration randomness is drawn island-major
before the shard boundary (``Engine._epilogue_draws`` — shared with the
legacy engine), migration's replace/pick draws and pack ranks are
computed replicated from gathered state, and the 1-shard mesh is
bit-identical to the legacy engine (tests/test_mesh_engine.py). Under
>1 shard with the constant optimizer off, the mesh run is bit-identical
to the unsharded legacy run; with the optimizer on, the fused
optimizer's restart key is decorrelated per shard exactly like the
legacy shard_map path.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P_

from ..evolve.engine import (
    Engine,
    RunningStats,
    SearchDeviceState,
    _migrate,
    _move_window,
    _shard_map,
)
from ..evolve.population import PopulationState
from ..evolve.step import _member_take_onehot, update_hof
from ..parallel.mesh import ISLAND_AXIS
from .plan import MeshPlan

__all__ = ["MeshEngine"]


class MeshEngine(Engine):
    """An Engine whose iteration is an explicit shard_map program over
    the plan's island mesh axis (see module docstring)."""

    def __init__(self, options, nfeatures, plan: MeshPlan,
                 dtype=jnp.float32, window_size: int = 100_000,
                 n_params: int = 0, n_classes: int = 0, template=None):
        if plan.n_data_shards != 1:
            raise NotImplementedError(
                "MeshEngine shards the island axis only; data-row "
                "sharded layouts stay on the legacy GSPMD path "
                "(docs/SCALING.md)"
            )
        self.plan = plan
        super().__init__(
            options, nfeatures, dtype=dtype, window_size=window_size,
            n_params=n_params, n_classes=n_classes, template=template,
            n_data_shards=plan.n_data_shards,
            n_island_shards=plan.n_island_shards, mesh=plan.mesh,
        )
        # The mesh runtime always runs island-local phases inside
        # shard_map — jnp interpreter path included (the legacy engine
        # only shard_maps the Pallas path and lets GSPMD partition the
        # rest). Safe to set post-super(): tracing happens at first
        # dispatch, not at jit construction.
        self._shard_islands = True

    def _build_jits(self) -> None:
        super()._build_jits()
        if not self.plan.resolve_donation():
            # Rebuild the iteration WITHOUT input-state donation:
            # XLA:CPU's donated-alias buffers + shard_map collectives
            # deadlock intermittently on virtual multi-device meshes
            # (MeshPlan.donate_state documents the observation), and
            # CPU donation saves nothing. Accelerator backends keep the
            # legacy donating jit.
            self._iteration = jax.jit(self._iteration_impl)

    # ------------------------------------------------------------------
    def _finalize_costs(self, pops, data, cfg, use_dedup):
        """Keep the dedup toggle ARITHMETIC-neutral: the dedup path
        finalizes through the materializing loss→cost chain (the
        in-kernel fused-cost epilogue composes with ``dedup=False``
        only), and at ragged row counts the two chains differ by ~1 ULP
        (the epilogue's claimed bit-identity holds at lane-multiple row
        counts — probed at 48/100 vs 64/128 rows). Without this pin a
        dedup A/B would compare different arithmetic, not different
        scheduling. Whenever dedup is ELIGIBLE the mesh finalize uses
        the materializing chain on or off — which is also exactly what
        the legacy UNSHARDED engine does (its eligible finalize always
        takes the dedup branch), preserving 1-shard bit-identity."""
        if self._dedup_eligible():
            cfg = cfg._replace(fuse_cost=False)
        return super()._finalize_costs(pops, data, cfg, use_dedup)

    def _use_dedup(self, sharded: bool) -> bool:
        """Per-shard finalize-dedup: under shard_map the dedup's sorts
        run on the shard's LOCAL finalize batch — no collective — so
        sharding no longer forfeits the win. ``plan.sharded_dedup``
        gates it for A/B (bit-exact either way)."""
        if not self._dedup_eligible():
            return False
        if not sharded:
            return True
        return self.plan.sharded_dedup

    # ------------------------------------------------------------------
    def _epilogue_part(self, state: SearchDeviceState, data, cur_maxsize,
                       evolved, key, k_opt, k_mig, batch_idx, cfg):
        """The mesh iteration epilogue: one shard_map region covering
        the island-local epilogue AND the cross-island phases, with the
        collectives written out instead of inferred."""
        options = self.options
        I = state.birth.shape[0]          # GLOBAL island count
        P = cfg.population_size
        S = self.plan.n_island_shards
        I_loc = I // S
        eval_fraction = (
            cfg.batch_size / data.y.shape[0] if cfg.batching else 1.0
        )

        if cfg.collect_telemetry:
            pops, best_seen, nev, birth, ref, marks, tele = evolved
        else:
            pops, best_seen, nev, birth, ref, marks = evolved
            tele = None
        simp_mark, opt_mark = marks  # [I, P] bools

        # Identical island-major draws as the legacy engine (shared
        # helper) — the runtime choice cannot change the streams.
        k_sel, scores, gate, ko2 = self._epilogue_draws(k_opt, I)
        sharded = S > 1
        use_dedup = self._use_dedup(sharded=sharded)

        def body(pops, ref, simp_mark, opt_mark, scores, gate, ko2, data,
                 cur_maxsize, batch_idx, birth, best_seen, nev, tele, hof,
                 freq, k_mig, num_evals0):
            # ---- island-LOCAL epilogue on this shard's islands ----
            pops, ref, f_calls = self._island_epilogue(
                pops, ref, simp_mark, opt_mark, scores, gate, ko2, data,
                cur_maxsize, batch_idx, cfg, k_sel, use_dedup,
                sharded=sharded)

            # ---- explicit collectives ----
            ag = lambda t: jax.tree.map(
                lambda x: jax.lax.all_gather(
                    x, ISLAND_AXIS, axis=0, tiled=True), t)
            pops_g = ag(pops)          # [I, P, ...] replicated
            birth_g = jax.lax.all_gather(
                birth, ISLAND_AXIS, axis=0, tiled=True)
            best_g = ag(best_seen)

            # Same f32 accumulation chain as the legacy epilogue (the
            # addends are integer-valued, so the psum split is exact).
            num_evals = num_evals0 + jax.lax.psum(
                jnp.sum(nev), ISLAND_AXIS) * eval_fraction
            num_evals = num_evals + jax.lax.psum(
                jnp.sum(f_calls), ISLAND_AXIS) * eval_fraction
            num_evals = num_evals + I * P  # the finalize re-eval

            # ---- hall-of-fame merge (replicated compute on gathered
            # inputs — bit-identical to the legacy GSPMD merge) ----
            flat_best = jax.tree.map(
                lambda x: x.reshape((I * cfg.maxsize,) + x.shape[2:]),
                best_g)
            hof = update_hof(
                hof,
                PopulationState(
                    trees=flat_best.trees,
                    cost=jnp.where(
                        flat_best.exists, flat_best.cost, jnp.inf),
                    loss=flat_best.loss,
                    complexity=flat_best.complexity,
                    birth=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                    ref=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                    parent=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                    params=flat_best.params,
                ),
                cfg.maxsize,
            )
            flat_pops = jax.tree.map(
                lambda x: x.reshape((I * P,) + x.shape[2:]), pops_g)
            hof = update_hof(hof, flat_pops, cfg.maxsize)

            # ---- migration on the gathered pool: the pool all-gather
            # is THE cross-shard migration collective; draws and the
            # binomial pack rank are replicated so every shard computes
            # the identical migrated population and slices its block ---
            if options.migration:
                topn = min(options.topn, P)
                order = jnp.argsort(pops_g.cost, axis=1)[:, :topn]
                pool = jax.vmap(
                    lambda p, o: _member_take_onehot(p, o, P)
                )(pops_g, order)
                pool = jax.tree.map(
                    lambda x: x.reshape((I * topn,) + x.shape[2:]), pool)
                pool_ok = jnp.isfinite(pool.cost)
                km1, km2, km3, km4 = jax.random.split(k_mig, 4)
                pops_g, birth_g = _migrate(
                    km1, pops_g, pool, options.fraction_replaced,
                    birth_g, I, P, candidate_mask=pool_ok)
                if options.hof_migration:
                    hof_pool = PopulationState(
                        trees=hof.trees,
                        cost=jnp.where(hof.exists, hof.cost, jnp.inf),
                        loss=hof.loss,
                        complexity=hof.complexity,
                        birth=jnp.zeros((cfg.maxsize,), jnp.int32),
                        ref=jnp.zeros((cfg.maxsize,), jnp.int32),
                        parent=jnp.zeros((cfg.maxsize,), jnp.int32),
                        params=hof.params,
                    )
                    pops_g, birth_g = _migrate(
                        km2, pops_g, hof_pool,
                        options.fraction_replaced_hof, birth_g, I, P,
                        candidate_mask=hof.exists)

            # ---- running stats on the global populations ----
            sizes = pops_g.complexity.reshape(-1)
            in_range = (sizes > 0) & (sizes <= cfg.maxsize)
            hist = jnp.zeros((cfg.maxsize,), jnp.float32).at[
                jnp.where(in_range, sizes - 1, 0)
            ].add(in_range.astype(jnp.float32))
            new_freq = _move_window(
                freq + hist, self.window_size, cfg.maxsize)
            stats = RunningStats(
                frequencies=new_freq,
                normalized_frequencies=new_freq / jnp.sum(new_freq),
            )

            # ---- carve this shard's islands back out ----
            shard = jax.lax.axis_index(ISLAND_AXIS)
            start = shard * jnp.int32(I_loc)
            sl = lambda x: jax.lax.dynamic_slice_in_dim(
                x, start, I_loc, axis=0)
            pops_l = jax.tree.map(sl, pops_g)
            birth_l = sl(birth_g)

            telem = None
            if cfg.collect_telemetry:
                from ..telemetry.counters import (
                    IterationTelemetry,
                    loss_histogram,
                    member_dup_stats,
                )

                cyc = jax.tree.map(
                    lambda x: jax.lax.psum(
                        jnp.sum(x, axis=0), ISLAND_AXIS), tele)
                cyc = dataclasses.replace(
                    cyc,
                    eval_rows=cyc.eval_rows + jnp.int32(I * P),
                    eval_launches=cyc.eval_launches + jnp.int32(1),
                )
                # Per-shard dup stats, psum'd over shards: exactly the
                # duplication per-shard dedup exploits (the legacy
                # engine reports zeros here under sharding; at 1 shard
                # this equals its global stats bit-for-bit).
                fr, fu = member_dup_stats(pops_l.trees)
                telem = IterationTelemetry(
                    cycle=cyc,
                    finalize_rows=jax.lax.psum(fr, ISLAND_AXIS),
                    finalize_unique=jax.lax.psum(fu, ISLAND_AXIS),
                    loss_hist=loss_histogram(pops_g.loss),
                    cx_hist=hist.astype(jnp.int32),
                )
            out = (pops_l, birth_l, ref, hof, stats, num_evals)
            if cfg.collect_telemetry:
                out = out + (telem,)
            return out

        isl = lambda t: jax.tree.map(lambda _: P_(ISLAND_AXIS), t)
        rep = lambda t: jax.tree.map(lambda _: P_(), t)
        args = (pops, ref, simp_mark, opt_mark, scores, gate, ko2, data,
                cur_maxsize, batch_idx, birth, best_seen, nev, tele,
                state.hof, state.stats.frequencies, k_mig,
                state.num_evals)
        in_specs = (
            isl(pops), P_(ISLAND_AXIS), P_(ISLAND_AXIS), P_(ISLAND_AXIS),
            None if scores is None else P_(ISLAND_AXIS),
            None if gate is None else P_(ISLAND_AXIS),
            rep(ko2), rep(data), P_(),
            None if batch_idx is None else P_(),
            P_(ISLAND_AXIS), isl(best_seen), P_(ISLAND_AXIS),
            None if tele is None else isl(tele),
            rep(state.hof), P_(), rep(k_mig), P_(),
        )
        out_specs = (
            isl(pops), P_(ISLAND_AXIS), P_(ISLAND_AXIS),
            rep(state.hof), rep(state.stats), P_(),
        )
        if cfg.collect_telemetry:
            out_specs = out_specs + (rep(state.telem),)
        out = _shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)(*args)
        if cfg.collect_telemetry:
            pops_l, birth_l, ref_l, hof, stats, num_evals, telem = out
        else:
            pops_l, birth_l, ref_l, hof, stats, num_evals = out
            telem = None
        return SearchDeviceState(
            pops=pops_l, hof=hof, stats=stats, birth=birth_l, ref=ref_l,
            num_evals=num_evals, key=key, telem=telem,
        )

    # ------------------------------------------------------------------
    def dedup_exchange(self, state: SearchDeviceState) -> Dict[str, Any]:
        """The periodic cross-shard dedup-key exchange (observability
        only — never touches the search state): all-gathers the members'
        identity hash keys (telemetry/counters.member_hash_keys, the
        same keys the dup-stats counter uses) over the island axis and
        reports the duplication split — local to a shard (per-shard
        dedup already exploits it) vs visible only globally (migration
        copies on other shards). One tiny jitted collective, driven by
        the host loop every ``plan.dedup_exchange_every`` iterations;
        the result feeds the graftscope ``mesh`` event."""
        if not hasattr(self, "_exchange_jit"):
            from ..telemetry.counters import (
                member_hash_keys,
                unique_key_count,
            )

            def exchange(trees):
                def ex_body(tr):
                    keys = member_hash_keys(tr)       # 3 x [N_local]
                    local_unique = unique_key_count(keys)
                    gathered = [
                        jax.lax.all_gather(k, ISLAND_AXIS, tiled=True)
                        for k in keys
                    ]
                    global_unique = unique_key_count(gathered)
                    shard_unique_sum = jax.lax.psum(
                        local_unique, ISLAND_AXIS)
                    per_shard = jax.lax.all_gather(
                        local_unique, ISLAND_AXIS)
                    return (jnp.int32(gathered[0].shape[0]),
                            shard_unique_sum, global_unique, per_shard)

                specs = jax.tree.map(lambda _: P_(ISLAND_AXIS), trees)
                return _shard_map(
                    ex_body, mesh=self.mesh, in_specs=(specs,),
                    out_specs=(P_(), P_(), P_(), P_()),
                    check_rep=False)(trees)

            self._exchange_jit = jax.jit(exchange)
        t0 = time.perf_counter()
        rows, shard_u, global_u, per_shard = jax.device_get(
            self._exchange_jit(state.pops.trees))
        dt = time.perf_counter() - t0
        S = self.plan.n_island_shards
        rows, shard_u, global_u = int(rows), int(shard_u), int(global_u)
        ps = [int(v) for v in np.asarray(per_shard).reshape(-1)]
        mean_u = sum(ps) / len(ps) if ps else 0.0
        # graftpulse shard-balance gauge: each shard's eval work scales
        # with the rows it actually evaluates — its unique members under
        # sharded finalize-dedup, its full row slice otherwise (dedup
        # off = every shard evaluates everything it holds, equally).
        # max/min ratio: 1.0 = perfectly balanced; the slowest shard
        # gates the SPMD step, so this bounds the step-time skew the
        # imbalance alone can cause.
        if self.plan.sharded_dedup:
            eval_rows = ps
        else:
            eval_rows = [rows // S] * S if S else []
        ratio = (max(eval_rows) / max(min(eval_rows), 1)
                 if eval_rows else 1.0)
        return {
            "rows": rows,
            "shard_unique": shard_u,
            "global_unique": global_u,
            "local_dup": rows - shard_u,
            "cross_shard_dup": shard_u - global_u,
            "per_shard_unique": ps,
            # >1.0 = some shard carries more distinct genomes than the
            # mean (its finalize dedup saves less than its peers')
            "shard_imbalance": (max(ps) / mean_u) if mean_u else 1.0,
            "per_shard_eval_rows": eval_rows,
            "shard_eval_imbalance": ratio,
            "exchanged_bytes": 3 * 4 * rows * max(S - 1, 0),
            "exchange_time_s": dt,
            "sharded_dedup": bool(self.plan.sharded_dedup),
        }
