"""MeshPlan: the explicit island-mesh execution plan.

One object answers every placement question the search runtime has:
which mesh axes exist, how each leaf of ``SearchDeviceState`` and
``DeviceData`` is partitioned, whether the iteration donates its input
state, and how often the mesh runtime exchanges dedup keys across
shards. ``parallel/mesh.py``'s ``shard_search_state`` /
``shard_device_data`` delegate here, so the ad-hoc helpers and the mesh
runtime can never disagree about placement.

Layout (SURVEY.md §5.8): per-island pytrees (``pops``, ``birth``,
``ref``) shard their leading island axis over the ``island`` mesh axis;
global state (hall of fame, running stats, eval counter, RNG key,
telemetry) replicates; dataset rows shard over the ``data`` axis when it
has more than one shard, else replicate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, ISLAND_AXIS, make_mesh

__all__ = ["MeshPlan"]


def _leaf_bytes(x) -> int:
    return int(getattr(x, "size", 0)) * int(
        getattr(getattr(x, "dtype", None), "itemsize", 0) or 0)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """The island-mesh execution plan (immutable; one per Engine).

    ``sharded_dedup`` gates the per-shard finalize-dedup the mesh
    runtime re-enables under island sharding (bit-exact either way —
    duplicates copy their group leader's result — so the A/B is a pure
    perf toggle). ``dedup_exchange_every`` is the iteration period of
    the cross-shard dedup-key all-gather (0 disables); the exchange is
    observability only and never changes the search.
    """

    mesh: Mesh
    n_island_shards: int
    n_data_shards: int = 1
    # None = auto: donate the iteration's input state on accelerator
    # backends (HBM pressure is real there), do NOT donate on CPU —
    # XLA:CPU's donated-alias buffers combined with shard_map
    # collectives deadlock intermittently on the virtual multi-device
    # mesh (observed ~1-in-4 runs on the 8-virtual-device CI stand-in),
    # and CPU donation buys nothing.
    donate_state: Optional[bool] = None
    sharded_dedup: bool = True
    dedup_exchange_every: int = 8

    # ------------------------------------------------------------------
    @classmethod
    def build(
        cls,
        devices: Optional[Sequence[jax.Device]] = None,
        n_island_shards: Optional[int] = None,
        n_data_shards: int = 1,
        **kw,
    ) -> "MeshPlan":
        """Build the ``(island, data)`` mesh and wrap it in a plan."""
        devices = list(devices if devices is not None else jax.devices())
        if n_island_shards is None:
            n_island_shards = len(devices) // n_data_shards
        mesh = make_mesh(
            devices[: n_island_shards * n_data_shards],
            n_island_shards=n_island_shards,
            n_data_shards=n_data_shards,
        )
        return cls(mesh=mesh, n_island_shards=n_island_shards,
                   n_data_shards=n_data_shards, **kw)

    def replace(self, **kw) -> "MeshPlan":
        return dataclasses.replace(self, **kw)

    # ------------------------------------------------------------------
    # Per-leaf PartitionSpecs
    # ------------------------------------------------------------------
    def island_spec(self) -> P:
        """Leading-axis island sharding (trailing dims replicated)."""
        return P(ISLAND_AXIS)

    def replicated_spec(self) -> P:
        return P()

    def state_specs(self, state) -> Any:
        """A ``SearchDeviceState``-shaped pytree of ``PartitionSpec``:
        pops/birth/ref island-sharded on their leading axis, everything
        global (hof, stats, num_evals, key, telem) replicated."""
        isl = lambda t: jax.tree.map(lambda _: P(ISLAND_AXIS), t)
        rep = lambda t: jax.tree.map(lambda _: P(), t)
        return dataclasses.replace(
            state,
            pops=isl(state.pops),
            hof=rep(state.hof),
            stats=rep(state.stats),
            birth=P(ISLAND_AXIS),
            ref=P(ISLAND_AXIS),
            num_evals=P(),
            key=P(),
            telem=rep(state.telem),
        )

    def data_specs(self, data) -> Any:
        """A ``DeviceData``-shaped pytree of ``PartitionSpec``: row axes
        over the ``data`` mesh axis when it has >1 shard, else
        replicated (scalars and unit vectors always replicate)."""
        if self.n_data_shards == 1:
            return jax.tree.map(lambda _: P(), data)
        row0 = P(DATA_AXIS)
        return dataclasses.replace(
            data,
            Xt=P(None, DATA_AXIS),
            y=None if data.y is None else row0,
            weights=None if data.weights is None else row0,
            class_idx=None if data.class_idx is None else row0,
            baseline_loss=P(),
            use_baseline=P(),
            x_dims=None if data.x_dims is None else P(),
            y_dims=None if data.y_dims is None else P(),
        )

    # ------------------------------------------------------------------
    # Placement
    # ------------------------------------------------------------------
    def _place(self, tree, specs):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            tree, specs,
        )

    def place_state(self, state):
        """Place a SearchDeviceState on the mesh per ``state_specs``."""
        return self._place(state, self.state_specs(state))

    def place_data(self, data):
        """Place a DeviceData on the mesh per ``data_specs``."""
        return self._place(data, self.data_specs(data))

    # ------------------------------------------------------------------
    # Introspection (telemetry / docs)
    # ------------------------------------------------------------------
    def exchange_bytes(self, state) -> Dict[str, int]:
        """Static per-iteration collective volume estimate (bytes): what
        the explicit all-gathers move. ``pops``+``birth`` feed both the
        hall-of-fame merge and the migration pool; ``best_seen`` is the
        per-island mini-HoF (same leaf shapes as the HoF, one per
        island)."""
        pops_b = sum(_leaf_bytes(x) for x in jax.tree.leaves(state.pops))
        hof_b = sum(_leaf_bytes(x) for x in jax.tree.leaves(state.hof))
        I = int(state.birth.shape[0])
        S = self.n_island_shards
        # all_gather moves each shard's block to the S-1 other shards
        factor = max(S - 1, 0) / max(S, 1)
        return {
            "pops_bytes": int(pops_b * factor),
            "best_seen_bytes": int(hof_b * I * factor),
            "birth_bytes": int(I * 4 * factor),
        }

    def resolve_donation(self) -> bool:
        """The effective donation policy (see ``donate_state``)."""
        if self.donate_state is not None:
            return bool(self.donate_state)
        return jax.default_backend() != "cpu"

    def describe(self) -> Dict[str, Any]:
        return {
            "axes": {ISLAND_AXIS: self.n_island_shards,
                     DATA_AXIS: self.n_data_shards},
            "n_devices": self.n_island_shards * self.n_data_shards,
            "devices": [str(d) for d in self.mesh.devices.flat],
            "donate_state": self.resolve_donation(),
            "sharded_dedup": self.sharded_dedup,
            "dedup_exchange_every": self.dedup_exchange_every,
        }
