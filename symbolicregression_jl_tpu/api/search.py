"""`equation_search` — the top-level search API and its host loop.

TPU re-design of the reference pipeline
(/root/reference/src/SymbolicRegression.jl:475-624): the async head-node
scheduler over Distributed.jl workers collapses into a synchronous bulk
iteration — all islands evolve in one jitted XLA program per iteration,
sharded over the device mesh (SURVEY.md §7 design delta 2). The host loop
handles only what must be host-side: iteration count, maxsize warmup,
early stopping, checkpoint CSVs, progress/logging, warm start.
"""

from __future__ import annotations

import dataclasses
import math
import os
import re
import sys
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset, make_dataset
from ..core.options import Options
from ..evolve.engine import Engine, SearchDeviceState
from ..ops.encoding import TreeBatch, encode_population
from ..ops.tree import Node, parse_expression
from ..parallel.mesh import make_mesh, shard_device_data, shard_search_state
from ..telemetry.hub import (
    IterationContext,
    LoggerSink,
    ProgressSink,
    RecorderSink,
    Telemetry,
)
from ..ledger.context import mint_run_trace
from ..ledger.ledger import CostLedger
from ..telemetry.spans import host_span, set_span_observer, step_span
from ..utils.progress import ProgressBar
from ..utils.recorder import Recorder
from .hall_of_fame import (
    HallOfFame,
    save_hall_of_fame_csv,
    string_dominating_pareto_curve,
)

__all__ = ["RuntimeOptions", "SearchState", "equation_search"]


def _default_run_id() -> str:
    # timestamp + random suffix (src/SearchUtils.jl:236-240)
    stamp = time.strftime("%Y%m%d_%H%M%S")
    return f"{stamp}_{uuid.uuid4().hex[:6]}"


# The shape _default_run_id produces — used to recognize a run_id nobody
# chose explicitly (multi-host runs must not keep per-host random ids).
_DEFAULT_RUN_ID_RE = re.compile(r"^\d{8}_\d{6}_[0-9a-f]{6}$")


@dataclasses.dataclass
class RuntimeOptions:
    """Execution (not hyper) parameters (src/SearchUtils.jl:79-234).

    The reference's parallelism modes (serial/multithreading/
    multiprocessing + numprocs) become device placement: ``devices``
    selects the accelerator set, islands are sharded across them in one
    SPMD program.
    """

    niterations: int = 40
    devices: Optional[Sequence[jax.Device]] = None
    n_data_shards: int = 1
    # graftmesh (docs/SCALING.md): run the search on the first-class
    # shard_map island mesh runtime (mesh/MeshEngine) instead of the
    # legacy GSPMD path. Island-axis sharding only (n_data_shards must
    # stay 1); re-enables finalize-dedup under sharding (gate it with
    # ``mesh_dedup`` for A/B — bit-identical either way) and emits
    # periodic cross-shard dedup-key-exchange ``mesh`` telemetry every
    # ``mesh_exchange_every`` iterations (0 disables; only when
    # options.telemetry is on — the exchange is observability only and
    # never changes the search).
    mesh_runtime: bool = False
    mesh_dedup: bool = True
    mesh_exchange_every: int = 8
    verbosity: int = 1
    progress: bool = False
    run_id: str = dataclasses.field(default_factory=_default_run_id)
    return_state: bool = False
    seed: Optional[int] = None
    logger: Optional[Any] = None  # SRLogger-compatible
    log_every_n: int = 1
    # Interactive-quit stream (reference StdinReader,
    # src/SearchUtils.jl:336-385). None = sys.stdin, engaged only when it
    # is a TTY; pass a stream object to force-engage (tests).
    input_stream: Optional[Any] = None
    # Full-state checkpoint cadence (iterations) when save_to_file is on;
    # the final/stopping iteration always checkpoints.
    checkpoint_every_n: int = 5
    # External stop hook (the graftserve layer's cancellation/deadline
    # wire, docs/SERVING.md): polled once per iteration AT THE BOUNDARY
    # — like the preemption guard, and unlike user_quit/timeout, it is
    # deliberately NOT polled between evolve chunks, so a stop never
    # truncates an iteration mid-flight and the checkpointed state stays
    # on the bit-identical resume="auto" trajectory. Return a
    # stop_reason string (e.g. "cancelled", "deadline") to stop; None
    # to continue.
    stop_hook: Optional[Callable[[], Optional[str]]] = None
    # Compiled-engine cache (serve/cache.py ExecutableCache): when set,
    # engine construction first consults the cache so repeat requests
    # with an equivalent canonical Options + dataset shape share one
    # Engine instance — and therefore one set of compiled XLA
    # executables (the jit caches live on the engine's callables).
    # get_engine returning None falls back to a fresh Engine
    # (uncacheable config: templates, un-fingerprintable callables).
    engine_cache: Optional[Any] = None
    # graftpulse (docs/OBSERVABILITY.md): active diagnostics riding the
    # telemetry hub. ``pulse`` keeps a flight-recorder ring of the last
    # ``pulse_ring`` iterations plus an anomaly detector, and dumps a
    # graftpulse.bundle.v1 JSON next to the run artifacts on any fault
    # or nonzero exit. All host-side, bit-neutral to the search.
    pulse: bool = True
    pulse_ring: int = 32
    # Profiler capture windows (jax.profiler traces): pulse_trace_on
    # arms one at the first iteration; anomalies and SIGUSR2 arm more,
    # each spanning pulse_trace_iterations iterations, at most
    # pulse_trace_budget per run. Traces need an output dir (the run's
    # output_directory / serve artifact dir) to land in.
    pulse_trace_on: bool = False
    pulse_trace_iterations: int = 2
    pulse_trace_budget: int = 2
    # graftledger (docs/OBSERVABILITY.md): per-request cost attribution
    # + causal tracing. ``trace`` is the request's TraceContext —
    # minted (and journaled) by SearchServer.submit(); None mints a
    # deterministic context from run_id, so every graftscope.v2 event
    # carries trace ids either way. ``ledger`` writes the
    # graftledger.v1 per-phase cost account to <run_dir>/ledger.jsonl
    # (save_to_file runs only). Host-side and bit-neutral, pinned by
    # the on/off A/B in tests/test_ledger.py.
    trace: Optional[Any] = None  # ledger.context.TraceContext
    ledger: bool = True
    # graftgauge (docs/OBSERVABILITY.md, "Capacity & memory"): device
    # capacity observability. ``gauge`` samples live-array bytes (and
    # allocator stats where the backend exposes memory_stats) every
    # iteration, feeds the pulse leak tripwire, records dispatch-latency
    # histograms, and emits ``gauge`` events — all host-side and
    # bit-neutral (on/off A/B pinned in tests/test_gauge.py). The
    # memory sampler only arms when something consumes it — an open
    # telemetry stream or the proactive degrader — because the
    # live-array walk is O(arrays alive in the process); the latency
    # histogram (two perf_counter calls per launch) is always on.
    gauge: bool = True
    # Opt-in footprint probe: AOT-compiles the iteration program once
    # per engine purely to harvest its memory/cost analysis into the
    # footprint ledger (an extra XLA compile — off by default; mesh AOT
    # compiles self-record without this knob).
    gauge_footprint: bool = False
    # Proactive degrade (shield ladder, docs/ROBUSTNESS.md): when set,
    # a device-memory watermark crossing this fraction of the limit
    # steps eval_tile_rows down BEFORE any OOM fires. None disables —
    # the step-down changes results, so it is opt-in, unlike the rest
    # of gauge.
    gauge_headroom_fraction: Optional[float] = None
    # Byte limit the headroom fraction applies to; None uses the
    # backend's memory_stats bytes_limit (so on CPU — no memory_stats —
    # the proactive ladder stays dormant unless a limit is given).
    gauge_limit_bytes: Optional[int] = None


@dataclasses.dataclass
class SearchState:
    """Host-side search state for warm starts (the `saved_state` analogue,
    src/SymbolicRegression.jl:760-821).

    ``num_evals`` is the cumulative total across all prior runs; the
    per-device counters inside ``device_states`` are reset when the state
    is resumed (they only track the current run's evals).
    """

    device_states: List[SearchDeviceState]  # one per output
    hofs: List[HallOfFame]
    options: Options
    num_evals: float = 0.0
    # Per-output dataset feature counts: saved trees index features
    # positionally, so resuming against a dataset with a different
    # feature count would silently mis-evaluate.
    nfeatures: Optional[List[int]] = None
    # Iterations already completed when this state was captured.
    # ``equation_search(resume=...)`` treats ``niterations`` as the
    # TOTAL target and runs only the remainder — which is what makes a
    # preempted-and-resumed search bit-identical to an uninterrupted
    # one. (Plain ``saved_state=`` warm starts keep the historical
    # semantics: run ``niterations`` MORE iterations.)
    iterations_done: int = 0


def _resolve_datasets(
    X,
    y,
    weights,
    variable_names,
    display_variable_names,
    y_variable_names,
    X_units,
    y_units,
    extra,
    dtype,
) -> List[Dataset]:
    """Build one Dataset per output (construct_datasets,
    src/SearchUtils.jl:673-715). ``y`` may be [n] or [nout, n]."""
    if isinstance(X, Dataset):
        return [X]
    if isinstance(X, (list, tuple)) and X and isinstance(X[0], Dataset):
        return list(X)
    y_arr = np.asarray(y)
    multi = y_arr.ndim == 2
    ys = y_arr if multi else y_arr[None, :]
    nout = ys.shape[0]
    datasets = []
    for j in range(nout):
        if y_variable_names is None:
            y_name = "y" if nout == 1 else f"y{j + 1}"
        elif isinstance(y_variable_names, str):
            y_name = y_variable_names
        else:
            y_name = y_variable_names[j]
        datasets.append(
            make_dataset(
                X,
                ys[j],
                weights=weights,
                variable_names=variable_names,
                display_variable_names=display_variable_names,
                y_variable_name=y_name,
                X_units=X_units,
                y_units=(
                    y_units[j]
                    if (y_units is not None and not isinstance(y_units, str))
                    else y_units
                ),
                extra=extra,
                index=j + 1,
                dtype=dtype,
            )
        )
    return datasets


def get_cur_maxsize(
    maxsize: int, warmup_maxsize_by: float, total_cycles: int, cycles_remaining: int
) -> int:
    """Maxsize warmup curriculum 3 -> maxsize over the first
    ``warmup_maxsize_by`` fraction of cycles (src/SearchUtils.jl:657-671)."""
    if warmup_maxsize_by <= 0:
        return maxsize
    cycles_elapsed = total_cycles - cycles_remaining
    fraction_elapsed = cycles_elapsed / total_cycles
    in_warmup = fraction_elapsed <= warmup_maxsize_by
    if in_warmup:
        return 3 + int((maxsize - 3) * fraction_elapsed / warmup_maxsize_by)
    return maxsize


def _parse_guess(
    guess, operators, variable_names, nfeatures: int
) -> Node:
    if isinstance(guess, Node):
        return guess
    return parse_expression(str(guess), operators, variable_names=variable_names)


def _encode_template_seeds(
    engine: Engine, items, operators
) -> Tuple[TreeBatch, List[Optional[np.ndarray]]]:
    """Encode template guesses — HostTemplateExpression, template
    strings ('f = ...; g = ...'), or {key: expr} dicts — into a
    [n, K, L] TreeBatch plus per-seed parameter vectors."""
    from ..models.template import (
        HostTemplateExpression,
        parse_template_expression,
        template_from_dict,
    )

    st = engine.template
    if not items:
        return None, []
    encs, params = [], []
    for expr, gp in items:
        if isinstance(expr, HostTemplateExpression):
            h = expr
        elif isinstance(expr, str):
            h = parse_template_expression(expr, st, operators)
        elif isinstance(expr, dict):
            h = template_from_dict(expr, st, operators)
        else:
            raise TypeError(
                f"Template guess must be a template string, dict, or "
                f"HostTemplateExpression; got {type(expr).__name__}"
            )
        encs.append(h.encode(engine.cfg.max_nodes, dtype=np.dtype(engine.dtype)))
        params.append(gp if gp is not None else h.params)
    batch = TreeBatch(
        arity=jnp.stack([e.arity for e in encs]),
        op=jnp.stack([e.op for e in encs]),
        feat=jnp.stack([e.feat for e in encs]),
        const=jnp.stack([e.const for e in encs]),
        length=jnp.stack([e.length for e in encs]),
    )
    return batch, params


def _seed_population(
    engine: Engine,
    state: SearchDeviceState,
    trees: Sequence[Node],
    data,
    mode: str,
    params: Optional[Sequence[Optional[np.ndarray]]] = None,
    encoded: Optional[TreeBatch] = None,
) -> SearchDeviceState:
    """Inject host trees into the device population (guess seeding /
    initial_population, src/SearchUtils.jl:738-835 and the fork's
    src/SymbolicRegression.jl:789-874).

    ``mode='replace_worst'`` replaces the worst members of island 0 with
    the seeds (guess semantics: seeds then migrate outward);
    ``mode='tile'`` tiles seeds across all islands' member slots
    (initial_population semantics). ``params``: optional per-seed fitted
    parameter banks (flat or (n_params, n_classes)); seeds without one
    get fresh randn banks. ``encoded``: pre-encoded seed TreeBatch
    (template members) — bypasses host-Node encoding.
    """
    if encoded is None and not trees:
        return state
    cfg = engine.cfg
    I = state.birth.shape[0]
    P = cfg.population_size
    if encoded is None:
        # Oversized seeds (an LLM proposer or hand-typed guess beyond
        # maxsize) are skipped with a warning, mirroring the reference's
        # random-fallback-with-warning for invalid seed populations
        # (src/SymbolicRegression.jl:835-857) — a bad seed must not
        # abort the search.
        # Filter oversized seeds FIRST, then truncate to the islands x
        # population_size capacity — a rejected seed early in the list
        # must not push a valid one past the cutoff.
        kept, kept_params = [], []
        ps = list(params) if params is not None else None
        for i, t in enumerate(trees):
            if len(kept) >= I * P:
                break
            n = t.count_nodes()
            if n > cfg.max_nodes:
                import warnings

                warnings.warn(
                    f"seed expression has {n} nodes > max_nodes="
                    f"{cfg.max_nodes} (maxsize); skipping it")
                continue
            kept.append(t)
            if ps is not None:
                kept_params.append(ps[i] if i < len(ps) else None)
        if not kept:
            return state
        trees = kept
        if params is not None:
            params = kept_params
    enc = (
        encoded
        if encoded is not None
        else encode_population(
            list(trees)[: I * P], cfg.max_nodes, cfg.operators,
            np.dtype(engine.dtype),
        )
    )
    n_seed = enc.length.shape[0]
    # Parametric: seeds get fresh randn parameter banks (extra_init_params
    # with prototype=None, /root/reference/src/ParametricExpression.jl:35-51)
    # unless a fitted bank is provided (CSV warm-start round trip).
    from ..evolve.population import init_params

    k_seed, k_next = jax.random.split(state.key)
    state = dataclasses.replace(state, key=k_next)
    seed_params = init_params(
        k_seed, (n_seed,), engine.n_params, engine.n_classes, engine.dtype
    )
    if params is not None and engine.n_params > 0:
        sp = np.array(seed_params)  # writable host copy
        for i, p in enumerate(list(params)[:n_seed]):
            if p is None:
                continue
            p = np.asarray(p, sp.dtype).reshape(
                engine.n_params, engine.n_classes
            )
            sp[i] = p
        seed_params = jnp.asarray(sp)
    cost, loss, cx = engine._eval_cost(enc, data, seed_params)

    if mode == "replace_worst":
        # Guesses also enter the hall of fame directly (the reference
        # injects parsed guesses into the HoF before migrating them into
        # populations, src/SymbolicRegression.jl:779-787) — otherwise an
        # exact seed can be evolved over before any per-cycle HoF update
        # records it.
        from ..evolve.population import PopulationState
        from ..evolve.step import update_hof

        seeds_pop = PopulationState(
            trees=enc,
            cost=cost,
            loss=loss,
            complexity=cx,
            birth=jnp.zeros((n_seed,), jnp.int32),
            ref=jnp.zeros((n_seed,), jnp.int32),
            parent=jnp.full((n_seed,), -1, jnp.int32),
            params=seed_params,
        )
        state = dataclasses.replace(
            state, hof=update_hof(state.hof, seeds_pop, engine.cfg.maxsize)
        )

    pops = state.pops
    if mode == "tile":
        idx = jnp.arange(I * P) % n_seed

        def tile(seeded):
            return jnp.take(seeded, idx, axis=0).reshape(
                (I, P) + seeded.shape[1:]
            )

        new_trees = TreeBatch(
            arity=tile(enc.arity),
            op=tile(enc.op),
            feat=tile(enc.feat),
            const=tile(enc.const),
            length=tile(enc.length),
        )
        pops = dataclasses.replace(
            pops,
            trees=new_trees,
            cost=jnp.take(cost, idx).reshape(I, P),
            loss=jnp.take(loss, idx).reshape(I, P),
            complexity=jnp.take(cx, idx).reshape(I, P),
            params=tile(seed_params),
        )
    else:  # replace_worst on island 0
        k = min(n_seed, P)
        order = jnp.argsort(pops.cost[0])  # best..worst
        targets = order[P - k :]

        def put(dst, src):
            # dst may be a host numpy array (resuming from a
            # device_get'ed SearchState): jit entry points accept those
            # transparently, but .at[] indexed update is jax-only.
            return jnp.asarray(dst).at[0, targets].set(src[:k])

        pops = dataclasses.replace(
            pops,
            trees=TreeBatch(
                arity=put(pops.trees.arity, enc.arity),
                op=put(pops.trees.op, enc.op),
                feat=put(pops.trees.feat, enc.feat),
                const=put(pops.trees.const, enc.const),
                length=put(pops.trees.length, enc.length),
            ),
            cost=put(pops.cost, cost),
            loss=put(pops.loss, loss),
            complexity=put(pops.complexity, cx),
            params=put(pops.params, seed_params),
        )
    return dataclasses.replace(state, pops=pops)


def _enable_default_compile_cache() -> None:
    """Turn on JAX's persistent compilation cache unless the user (or
    the test harness) configured one already.

    A cold quickstart fit at the device-scale config pays ~3-4 minutes
    of XLA compiles (the iteration epilogue alone is ~2 minutes);
    repeat runs with the same shapes load from the cache in seconds.
    Opt out with SR_NO_COMPILE_CACHE=1 or by setting
    ``jax_compilation_cache_dir`` yourself.
    """
    if os.environ.get("SR_NO_COMPILE_CACHE"):
        return
    if jax.config.jax_compilation_cache_dir is not None:
        return
    # CPU backends: compiles are fast and XLA:CPU's AOT cache entries
    # are keyed loosely enough that a cache written under one host's
    # machine-feature set loads (with loud cpu_aot_loader errors and a
    # SIGILL risk) on another — observed with +prefer-no-gather
    # pseudo-features. The cache exists for minute-scale TPU compiles;
    # leave CPU runs uncached unless the user opts in themselves.
    if jax.default_backend() == "cpu":
        return
    # Respect a user-tuned cache threshold: only overwrite the value if
    # it still sits at JAX's own default (1.0s).
    min_secs_default = (
        getattr(jax.config, "jax_persistent_cache_min_compile_time_secs", 1.0)
        == 1.0
    )
    # User-owned cache dir (NOT a predictable /tmp path: the persistent
    # cache deserializes executables, so the directory must not be
    # pre-creatable by another local user).
    base = os.environ.get(
        "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache"))
    path = os.path.join(base, "symbolicregression_jl_tpu", "xla_cache")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:  # unwritable home: skip caching rather than risk /tmp
        return
    jax.config.update("jax_compilation_cache_dir", path)
    if min_secs_default:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def _apply_compile_effort() -> None:
    """Honor ``SR_XLA_EFFORT`` (e.g. ``-1.0``): forwards to JAX's
    ``jax_exec_time_optimization_effort``, trading XLA optimization
    effort for compile time. Measured at the device-scale quickstart
    (profiling/compile_breakdown.py): effort -1.0 cuts the cold-start
    compile from ~220 s to ~164 s (evolve program 138→47 s, init
    48→8.5 s; the epilogue's Pallas/Mosaic kernels are unaffected) —
    but costs ~3× steady-state throughput (bench 507k → 165k evals/s;
    -0.5 measures the same), so it is ONLY for compile-bound contexts
    like CI smoke runs, never production fits. Process-global, like
    the persistent-cache setup above; left at JAX's default unless the
    env var is set.
    """
    eff = os.environ.get("SR_XLA_EFFORT")
    if not eff:
        return
    if jax.config.jax_exec_time_optimization_effort != 0.0:
        return  # user already configured it programmatically
    try:
        value = float(eff)
    except ValueError:
        import warnings

        warnings.warn(
            f"SR_XLA_EFFORT={eff!r} is not a float; ignoring it")
        return
    jax.config.update("jax_exec_time_optimization_effort", value)


def equation_search(
    X,
    y=None,
    *,
    options: Optional[Options] = None,
    niterations: int = 40,
    weights=None,
    variable_names: Optional[Sequence[str]] = None,
    display_variable_names: Optional[Sequence[str]] = None,
    y_variable_names=None,
    X_units=None,
    y_units=None,
    extra: Optional[Dict[str, Any]] = None,
    guesses: Optional[Sequence] = None,
    initial_population: Optional[Sequence] = None,
    saved_state: Optional[Union[SearchState, str]] = None,
    resume: Optional[str] = None,
    runtime_options: Optional[RuntimeOptions] = None,
    verbosity: Optional[int] = None,
    progress: Optional[bool] = None,
    run_id: Optional[str] = None,
    return_state: bool = False,
    seed: Optional[int] = None,
    dtype=None,
) -> Union[List[HallOfFame], HallOfFame, Tuple[SearchState, Any]]:
    """Run the full symbolic-regression search.

    Mirrors the reference `equation_search` kwargs
    (src/SymbolicRegression.jl:359-474) with TPU-native execution. Returns
    the hall of fame (list for multi-output), or ``(state, hof)`` when
    ``return_state=True``.

    ``resume="auto"`` discovers the newest valid checkpoint under the
    output base (falling back past corrupt files to older rolling
    generations) and continues it, treating ``niterations`` as the
    TOTAL target — a preempted-and-resumed search is bit-identical to
    an uninterrupted one. ``resume=<path>`` names a checkpoint file or
    run directory explicitly. ``saved_state=`` keeps the historical
    warm-start semantics (run ``niterations`` MORE iterations). See
    docs/ROBUSTNESS.md for the full graftshield failure model.

    Process-global side effect: unless opted out (SR_NO_COMPILE_CACHE=1)
    or already configured, the first call on a non-CPU backend enables
    JAX's persistent compilation cache for the whole process
    (``jax_compilation_cache_dir``
    under ``~/.cache``; ``jax_persistent_cache_min_compile_time_secs`` is
    raised to 2.0s only if still at JAX's default) — this also affects
    unrelated JAX code running in the same process.
    """
    options = options or Options()
    _enable_default_compile_cache()
    _apply_compile_effort()
    # Copy so the caller's RuntimeOptions is never mutated (it may be
    # reused across searches).
    ropt = (
        dataclasses.replace(runtime_options)
        if runtime_options is not None
        else RuntimeOptions(niterations=niterations)
    )
    # Explicit kwargs override the RuntimeOptions fields either way — a
    # caller passing both runtime_options and e.g. seed=42 must not have
    # the seed silently dropped.
    if verbosity is not None:
        ropt.verbosity = verbosity
    if progress is not None:
        ropt.progress = progress
    if run_id is not None:
        ropt.run_id = run_id
    if return_state:
        ropt.return_state = True
    if seed is not None:
        ropt.seed = seed
    elif ropt.seed is None:
        ropt.seed = options.seed
    if options.deterministic and ropt.seed is None:
        # The device evolution is always deterministic given the key; the
        # only nondeterminism is the np.random seed fallback below. The
        # reference enforces the same pairing (deterministic=true requires
        # a seed, /root/reference/src/Configure.jl:64-66).
        raise ValueError(
            "deterministic=True requires a seed (pass seed= or Options(seed=...))"
        )

    if resume is not None and saved_state is not None:
        raise ValueError("pass either resume= or saved_state=, not both")

    # The one place the default checkpoint/CSV output base is computed:
    # resume="auto" discovery and out_dir below MUST agree on it.
    out_base = options.output_directory or (
        "outputs" if not os.environ.get("SYMBOLIC_REGRESSION_IS_TESTING")
        else os.path.join(os.environ.get("TMPDIR", "/tmp"), "sr_outputs")
    )

    # Multi-host: every rank must write its checkpoint shard into the
    # SAME run directory, but the default run_id carries a per-process
    # random suffix — each host would invent its own directory and the
    # rank-shard set could never reassemble. Replace a defaulted id with
    # a seed-derived deterministic one (an SPMD-correct multi-host run
    # already requires the same seed on every host; the device RNG key
    # comes from it).
    if (jax.process_count() > 1
            and _DEFAULT_RUN_ID_RE.match(ropt.run_id)):
        if ropt.seed is None:
            raise ValueError(
                "multi-host runs need a deterministic identity shared by "
                "every rank: pass run_id= (same on every host) or a seed"
            )
        ropt.run_id = f"multihost_seed{ropt.seed}"

    if isinstance(saved_state, (str, os.PathLike)):
        # On-disk checkpoint resume (the cross-process analogue of the
        # reference's saved-output reload, src/SymbolicRegression.jl:760-821).
        from .checkpoint import load_search_state

        saved_state = load_search_state(os.fspath(saved_state), options)

    # ---- graftshield resume (docs/ROBUSTNESS.md) ----
    # resume="auto" discovers the newest run directory with a checkpoint
    # under the output base; resume=<path> names a checkpoint file or
    # run directory. Either way the load walks the rolling set and falls
    # back past corrupt files to the newest VALID one, and niterations
    # becomes the TOTAL target: only the remaining iterations run, so a
    # preempted-then-resumed search is bit-identical to an uninterrupted
    # one (tests/test_shield.py pins this).
    start_iter = 0
    resume_events: List[Dict[str, Any]] = []
    if resume is not None:
        from ..shield.checkpoints import (
            discover_resume_path,
            load_newest_valid,
        )

        search_base = out_base if resume == "auto" else os.fspath(resume)
        # max(): a checkpoint_keep raised mid-project must still reach
        # older generations written under the larger setting, and a
        # lowered one must not blind resume to files already on disk.
        candidates = discover_resume_path(
            search_base, keep=max(8, options.checkpoint_keep))
        if candidates is None:
            if resume != "auto":
                raise FileNotFoundError(
                    f"resume={resume!r}: no checkpoint found there"
                )
            if ropt.verbosity >= 1:
                print(
                    f"resume='auto': no checkpoint under {search_base}; "
                    "starting fresh"
                )
        else:
            corrupt_log: List[Tuple[str, str]] = []
            saved_state, used_path = load_newest_valid(
                candidates, options, corrupt_log=corrupt_log)
            for bad_path, err in corrupt_log:
                resume_events.append({
                    "kind": "checkpoint_corrupt",
                    "detail": {"path": bad_path, "error": err[:500]},
                })
            start_iter = int(saved_state.iterations_done)
            resume_events.append({
                "kind": "resume",
                "detail": {"path": used_path,
                           "iterations_done": start_iter},
            })

    datasets = _resolve_datasets(
        X, y, weights, variable_names, display_variable_names,
        y_variable_names, X_units, y_units, extra,
        dtype or options.eval_dtype,
    )
    for ds in datasets:
        ds.update_baseline_loss(options.elementwise_loss)

    n_islands = options.populations
    devices = list(ropt.devices if ropt.devices is not None else jax.devices())
    # The island axis shards must divide the island count; use the largest
    # divisor that fits the available devices (spare devices idle rather
    # than forcing a resize of the user's `populations`).
    max_shards = max(len(devices) // ropt.n_data_shards, 1)
    n_island_shards = max(
        d for d in range(1, max_shards + 1) if n_islands % d == 0
    )
    mesh = make_mesh(
        devices[: n_island_shards * ropt.n_data_shards],
        n_island_shards=n_island_shards,
        n_data_shards=ropt.n_data_shards,
    )
    mesh_plan = None
    if ropt.mesh_runtime:
        if ropt.n_data_shards != 1:
            raise ValueError(
                "mesh_runtime shards the island axis only; data-row "
                "sharding (n_data_shards > 1) stays on the legacy GSPMD "
                "path (docs/SCALING.md)"
            )
        from ..mesh import MeshPlan

        mesh_plan = MeshPlan(
            mesh=mesh, n_island_shards=n_island_shards,
            n_data_shards=ropt.n_data_shards,
            sharded_dedup=ropt.mesh_dedup,
            dedup_exchange_every=max(int(ropt.mesh_exchange_every), 0),
        )

    from .. import search_key

    key = search_key(
        ropt.seed if ropt.seed is not None else np.random.randint(0, 2**31 - 1)
    )

    out_dir = None
    # Multi-host: every rank computes the SAME run directory (full-state
    # checkpoints need every rank to write its own `.rank{k}` shard
    # file, api/checkpoint.py), but only rank 0 writes the CSVs and the
    # telemetry stream — those would race on identical content.
    is_rank0 = jax.process_index() == 0
    if options.save_to_file:
        out_dir = os.path.join(out_base, ropt.run_id)

    total_cycles = ropt.niterations * options.ncycles_per_iteration
    engines: List[Engine] = []
    states: List[SearchDeviceState] = []
    datas = []
    from ..models.spec import ParametricExpressionSpec, TemplateExpressionSpec

    for j, ds in enumerate(datasets):
        n_params = 0
        n_classes = 0
        template = None
        if isinstance(options.expression_spec, ParametricExpressionSpec):
            if ds.data.class_idx is None:
                raise ValueError(
                    "ParametricExpressionSpec requires a `class` column: "
                    "pass extra={'class': ...} (the reference routes "
                    "dataset.extra.class to the parameter gather, "
                    "src/ParametricExpression.jl:88-100)"
                )
            n_params = options.expression_spec.max_parameters
            n_classes = ds.n_classes
        elif isinstance(options.expression_spec, TemplateExpressionSpec):
            template = options.expression_spec.structure
            if ds.nfeatures != template.n_variables:
                raise ValueError(
                    f"Template combiner consumes {template.n_variables} "
                    f"variables but the dataset has {ds.nfeatures} features"
                )
        # graftserve executable cache: an equivalent canonical config
        # reuses a prior request's Engine (and its compiled programs)
        # instead of re-tracing ~minutes of XLA per request. A None
        # return (no cache, or uncacheable config) builds fresh.
        engine = None
        if mesh_plan is not None:
            # graftmesh runtime: explicit shard_map plan. Skips the
            # serve executable cache — its key does not distinguish the
            # runtimes, and mixing compiled programs across them would
            # silently serve the wrong executable.
            from ..mesh import MeshEngine

            engine = MeshEngine(options, ds.nfeatures, mesh_plan,
                                dtype=_np_dtype(options.eval_dtype),
                                n_params=n_params, n_classes=n_classes,
                                template=template)
        if engine is None and ropt.engine_cache is not None:
            engine = ropt.engine_cache.get_engine(
                options, nfeatures=ds.nfeatures,
                dtype=_np_dtype(options.eval_dtype),
                n_params=n_params, n_classes=n_classes, template=template,
                n_data_shards=ropt.n_data_shards,
                n_island_shards=n_island_shards, mesh=mesh,
                rows=int(ds.X.shape[0]),
            )
        if engine is None:
            engine = Engine(options, ds.nfeatures,
                            dtype=_np_dtype(options.eval_dtype),
                            n_params=n_params, n_classes=n_classes,
                            template=template,
                            n_data_shards=ropt.n_data_shards,
                            n_island_shards=n_island_shards, mesh=mesh)
        data = (mesh_plan.place_data(ds.data) if mesh_plan is not None
                else shard_device_data(ds.data, mesh))
        key, k_init = jax.random.split(key)
        if saved_state is not None and j < len(saved_state.device_states):
            issues = options.check_warm_start_compatibility(saved_state.options)
            if issues:
                raise ValueError(
                    f"Warm start incompatible; changed options: {issues}"
                )
            if (
                saved_state.nfeatures is not None
                and saved_state.nfeatures[j] != ds.nfeatures
            ):
                raise ValueError(
                    f"Warm start incompatible: saved state was fitted on "
                    f"{saved_state.nfeatures[j]} features but the dataset "
                    f"has {ds.nfeatures} (trees index features positionally)"
                )
            state = saved_state.device_states[j]
            # The saved per-device counters are already folded into
            # saved_state.num_evals (num_evals0); reset them so the
            # total isn't double-counted after resume.
            state = dataclasses.replace(state, num_evals=jnp.float32(0.0))
            if n_classes:
                # Saved parametric banks are positional over the fitted
                # class set; a different class count (or silently
                # different class values) would misalign every learned
                # parameter column.
                saved_classes = state.pops.params.shape[-1]
                if saved_classes != ds.n_classes:
                    raise ValueError(
                        f"Warm start incompatible: saved parametric state "
                        f"has {saved_classes} classes but the dataset has "
                        f"{ds.n_classes}"
                    )
        else:
            state = engine.init_state(k_init, data, n_islands)
            if initial_population:
                if template is not None:
                    enc, gparams = _encode_template_seeds(
                        engine, [(g, None) for g in initial_population],
                        options.operators,
                    )
                    state = _seed_population(
                        engine, state, [], data, mode="tile",
                        params=gparams, encoded=enc,
                    )
                else:
                    trees = [
                        _parse_guess(g, options.operators, ds.variable_names,
                                     ds.nfeatures)
                        for g in initial_population
                    ]
                    state = _seed_population(
                        engine, state, trees, data, mode="tile"
                    )
        if guesses is not None:
            gs = guesses[j] if _is_nested(guesses, len(datasets)) else guesses
            # A guess is an expression (string/Node/template string), or
            # a tuple (expression, fitted_params) — the shape produced by
            # load_hall_of_fame_csv(return_params=True).
            items = []
            for g in gs:
                if _is_guess_pair(g):
                    items.append(g)
                else:
                    items.append((g, None))
            if template is not None:
                enc, gparams = _encode_template_seeds(
                    engine, items, options.operators
                )
                state = _seed_population(
                    engine, state, [], data, mode="replace_worst",
                    params=gparams, encoded=enc,
                )
            else:
                trees = [
                    _parse_guess(expr, options.operators, ds.variable_names,
                                 ds.nfeatures)
                    for expr, _ in items
                ]
                state = _seed_population(
                    engine, state, trees, data, mode="replace_worst",
                    params=[gp for _, gp in items],
                )
        state = (mesh_plan.place_state(state) if mesh_plan is not None
                 else shard_search_state(state, mesh))
        engines.append(engine)
        states.append(state)
        datas.append(data)

    hofs: List[HallOfFame] = [HallOfFame(entries=[]) for _ in datasets]
    if saved_state is not None:
        # A resumed search that runs zero further iterations (target
        # already reached) must still return the saved hall of fame, and
        # the quarantine/telemetry paths want a decoded HoF from the
        # first boundary on.
        for j, engine in enumerate(engines):
            if j < len(states):
                hofs[j] = HallOfFame.from_device(
                    states[j].hof, options.operators,
                    template=engine.template,
                )
    start_time = time.time()
    num_evals0 = saved_state.num_evals if saved_state is not None else 0.0
    stop_reason = None
    cycles_remaining = total_cycles - start_iter * options.ncycles_per_iteration

    # ---- graftledger causal context (ledger/, docs/OBSERVABILITY.md) --
    # A served request threads its journaled root TraceContext in
    # through RuntimeOptions; the search runs under a deterministic
    # child span of it. Plain searches mint a root from run_id. Either
    # way every graftscope.v2 event the hub emits carries the ids.
    search_trace = (
        ropt.trace.child("search") if ropt.trace is not None
        else mint_run_trace(ropt.run_id)
    )

    # ---- graftscope telemetry hub (telemetry/hub.py) ----
    # One object owns every per-iteration consumer — the SRLogger, the
    # genealogy Recorder, the ProgressBar — as registered sinks, plus
    # the schema-versioned JSONL stream when options.telemetry is set.
    hub = Telemetry(
        options,
        run_id=ropt.run_id,
        out_dir=out_dir,
        niterations=ropt.niterations,
        nout=len(datasets),
        trace=search_trace,
        engine_info=[
            {
                "output": j + 1,
                "turbo": bool(e.cfg.turbo),
                "fuse_cost": bool(e.cfg.fuse_cost),
                "collect_telemetry": bool(e.cfg.collect_telemetry),
                "n_islands": int(n_islands),
                "n_island_shards": int(n_island_shards),
                "nfeatures": int(e.nfeatures),
                "mesh_runtime": bool(ropt.mesh_runtime),
            }
            for j, e in enumerate(engines)
        ],
    )
    recorder = None
    if options.use_recorder and is_rank0:
        rec_path = (
            os.path.join(out_dir, options.recorder_file)
            if out_dir is not None
            else options.recorder_file
        )
        # stream_path caps recorder_verbosity>=2 memory: iteration event
        # batches spill to disk as they are assembled (utils/recorder.py)
        # and merge back into the reference JSON layout at write().
        recorder = Recorder(options, stream_path=rec_path + ".stream")
        hub.add_sink(
            RecorderSink(
                recorder, [ds.variable_names for ds in datasets], rec_path
            )
        )
    if ropt.logger is not None:
        hub.add_sink(LoggerSink(ropt.logger, every=ropt.log_every_n))
    bar = ProgressBar(ropt.niterations) if ropt.progress else None
    if bar is not None:
        hub.add_sink(ProgressSink(bar))

    # ---- graftpulse active diagnostics (pulse/, docs/OBSERVABILITY.md) --
    # Flight recorder: sink (per-iteration ring) + watcher (fault/
    # anomaly/pulse events; a fault triggers the bundle dump — the
    # watcher fires before the watchdog's os._exit can discard the
    # evidence). Anomaly detector: rolling stats over signals the loop
    # already materialized, arming the budgeted profiler capture.
    # Everything is host-side and bit-neutral to the search.
    from ..pulse import AnomalyDetector, FlightRecorder, SignalArm, TraceCapture

    pulse_rec = pulse_cap = pulse_sig = None
    if ropt.pulse and is_rank0:
        pulse_rec = FlightRecorder(
            capacity=ropt.pulse_ring,
            path=(os.path.join(out_dir, "pulse_bundle.json")
                  if out_dir is not None else None),
            run_id=ropt.run_id,
            hub=hub,
        )
        hub.add_sink(pulse_rec)
        hub.add_watcher(pulse_rec.on_event)
        if out_dir is not None:
            # Captures need somewhere to land; dir-less runs still get
            # the detector + recorder ring (dump path also None — the
            # ring then only feeds a caller-provided dump path).
            pulse_cap = TraceCapture(
                out_dir, hub=hub,
                window_iterations=ropt.pulse_trace_iterations,
                max_captures=ropt.pulse_trace_budget,
            )
            if ropt.pulse_trace_on:
                pulse_cap.arm("option", 0)
            pulse_sig = SignalArm().install()
        pulse_det = AnomalyDetector(
            hub,
            on_anomaly=(pulse_cap.arm if pulse_cap is not None else None),
            expected_rescore_fraction=(
                float(getattr(options, "rescore_fraction", 0.0))
                if getattr(options, "staged_eval", False) else None
            ),
        )
        hub.add_sink(pulse_det)
    else:
        pulse_det = None

    # ---- graftledger cost account (ledger/ledger.py) ----
    # One account segment per search attempt, appended to
    # <run_dir>/ledger.jsonl: device/host seconds per iteration,
    # compile seconds (jax.monitoring diffs), the timed host-phase
    # spans (thread-local observer — concurrent serve workers each see
    # only their own search), and checkpoint bytes. Read-only over
    # values the loop already materialized; bit-neutral.
    ledger_sink = None
    if ropt.ledger and is_rank0:
        ledger_sink = CostLedger(
            (os.path.join(out_dir, "ledger.jsonl")
             if out_dir is not None else None),
            run_id=ropt.run_id,
            trace=search_trace,
            hub=hub,
        )
        hub.add_sink(ledger_sink)
        set_span_observer(ledger_sink.note_phase)

    # ---- graftgauge capacity observability (gauge/, docs/OBSERVABILITY.md
    # "Capacity & memory") ----
    # Memory sampler: per-iteration live-array bytes + backend-guarded
    # allocator stats, watermarks, the pulse leak tripwire, and the
    # flight-recorder's deterministic memory snapshots. Dispatch-latency
    # histogram: host-side timing around the iteration launch. Proactive
    # degrader (opt-in via gauge_headroom_fraction): steps
    # eval_tile_rows down when the watermark crosses the headroom line
    # — before the OOM, not after it.
    from ..gauge import DispatchLatency, MemorySampler, ProactiveDegrader
    from ..gauge import global_latency as _gauge_global_latency

    gauge_sampler = gauge_lat = None
    # The sampler's jax.live_arrays() walk is O(total live arrays in
    # the process) — cheap in a serving or bench process, but a
    # long-lived array-heavy host (one process running many searches
    # back to back with nothing consuming the samples) would pay it
    # every iteration for nothing. So the sampler only arms when
    # something reads it: an open telemetry stream (hub.path) or the
    # proactive headroom degrader. The dispatch-latency histogram is
    # two perf_counter calls per launch and stays on whenever gauge is.
    gauge_wanted = (hub.path is not None
                    or ropt.gauge_headroom_fraction is not None)
    if ropt.gauge and is_rank0:
        gauge_degrader = None
        if ropt.gauge_headroom_fraction is not None:
            def _degrade_all_engines():
                new_rows = None
                for _e in engines:
                    r = _e.degrade_eval_tile_rows()
                    if r is not None:
                        new_rows = r
                return new_rows

            gauge_degrader = ProactiveDegrader(
                _degrade_all_engines,
                headroom_fraction=ropt.gauge_headroom_fraction,
                limit_bytes=ropt.gauge_limit_bytes,
                hub=hub,
            )
        if gauge_wanted:
            gauge_sampler = MemorySampler(
                hub, detector=pulse_det, recorder=pulse_rec,
                degrader=gauge_degrader,
            )
            hub.add_sink(gauge_sampler)
        gauge_lat = DispatchLatency()
        if ropt.gauge_footprint:
            # opt-in: AOT-compile each engine's iteration program once
            # purely to harvest its memory/cost analysis (an extra XLA
            # compile per engine; geometries the ledger already knows
            # are skipped inside the probe)
            from ..gauge import probe_engine_iteration

            for _j, (_eng, _st, _dt) in enumerate(
                    zip(engines, states, datas)):
                entry = probe_engine_iteration(_eng, _st, _dt)
                if entry is not None:
                    hub.gauge("footprint", iteration=0,
                              output=_j + 1, **entry)
        if gauge_sampler is not None:
            if ledger_sink is not None:
                # one thread-local span-observer slot: chain the
                # ledger's phase accounting with the sampler's
                # per-phase watermarks
                def _observe_span(name, seconds,
                                  _ledger=ledger_sink, _smp=gauge_sampler):
                    _ledger.note_phase(name, seconds)
                    _smp.note_phase(name, seconds)

                set_span_observer(_observe_span)
            else:
                set_span_observer(gauge_sampler.note_phase)

    # ---- graftshield supervision (shield/ package, docs/ROBUSTNESS.md) --
    # Preemption guard: SIGTERM/SIGINT set a flag the budget poll reads;
    # the loop then stops at the iteration boundary with
    # stop_reason="preempted" and the end-of-loop write becomes the
    # emergency checkpoint. Watchdog: per-phase deadlines on the device
    # dispatch (compile_budget on compile-bearing iterations,
    # iteration_deadline warm). Runner: transient-failure retry/backoff
    # + eval-shape degradation. Quarantine: NaN-storm island reseed.
    from ..shield.degrade import ShieldRunner
    from ..shield.faults import active_injector
    from ..shield.quarantine import IslandQuarantine
    from ..shield.signals import PreemptionGuard
    from ..shield.watchdog import Watchdog

    shield_on = bool(options.shield)
    guard = PreemptionGuard()
    if shield_on:
        guard.install()
    watchdog = Watchdog(
        dump_path=(os.path.join(out_dir, "watchdog_dump.txt")
                   if out_dir is not None and is_rank0 else None),
        telemetry=hub,
    ) if shield_on else None
    runner = ShieldRunner(
        max_retries=options.max_retries, backoff=options.retry_backoff,
        telemetry=hub,
    ) if shield_on else None
    # Quarantine is single-process only for now: the [I] invalid-
    # fraction vector is island-sharded, and fetching it from a process
    # that does not address every shard raises. (A multi-host variant
    # needs an in-graph allgather of the mask — documented limitation,
    # docs/ROBUSTNESS.md.)
    quarantine = IslandQuarantine(
        threshold=options.quarantine_invalid_fraction, telemetry=hub,
    ) if (shield_on and options.island_quarantine
          and jax.process_count() == 1) else None
    injector = active_injector(telemetry=hub) if shield_on else None
    for ev in resume_events:
        hub.fault(ev["kind"], iteration=start_iter, **ev["detail"])
    # Rolling full-state checkpoints (digest-verified, last
    # options.checkpoint_keep generations; shield/checkpoints.py). All
    # ranks construct it: multi-host saves write one rank-shard file per
    # host (api/checkpoint.py).
    from ..shield.checkpoints import RollingCheckpointer

    ckpt = (
        RollingCheckpointer(
            os.path.join(out_dir, "search_state.pkl"),
            keep=options.checkpoint_keep,
        )
        if out_dir is not None else None
    )

    last_ckpt_it = -1

    def _checkpoint_state() -> "SearchState":
        return SearchState(
            device_states=list(states),
            hofs=hofs,
            options=options,
            num_evals=num_evals0 + sum(float(s.num_evals) for s in states),
            nfeatures=[ds.nfeatures for ds in datasets],
            iterations_done=it,
        )

    def _note_checkpoint_bytes(saved_path: Optional[str]) -> None:
        # graftledger: bytes_checkpointed per request (wall subtree —
        # re-saves after a resume make the count schedule-dependent)
        if ledger_sink is None or not saved_path:
            return
        try:
            ledger_sink.note_checkpoint(os.path.getsize(saved_path))
        except OSError:
            pass

    # Interactive quit ('q' / ctrl-d on stdin; StdinReader analogue).
    from ..utils.stdin_quit import StdinQuitWatcher

    it = start_iter  # also the exception-dump iteration before the loop
    try:
        # Engage the stdin watcher only for an injected test stream or a
        # genuinely interactive session (Options(interactive_quit=True)
        # AND a real TTY). Headless/batch/server runs get the disabled
        # form: no background thread reading stdin per request, no
        # termios fiddling (the multi-tenant server would otherwise leak
        # one watcher thread per request).
        if ropt.input_stream is not None:
            watcher = StdinQuitWatcher(ropt.input_stream, force=True)
        elif options.interactive_quit and _stdin_is_tty():
            watcher = StdinQuitWatcher()
        else:
            watcher = StdinQuitWatcher.disabled()

        def _budget_stop(pending_evals=None) -> Optional[str]:
            """``pending_evals``: optional thunk for not-yet-landed evals of a
            partially-run iteration (only forced when max_evals is set).

            Deliberately does NOT poll the preemption guard: this
            predicate also runs between evolve chunks, and a preempt
            that truncated an iteration mid-flight would checkpoint a
            state no uninterrupted run ever reaches — breaking the
            resume="auto" bit-identity contract. The guard is checked
            once per iteration, at the boundary (below), which is also
            where the emergency checkpoint is defined to happen."""
            if watcher.check():
                return "user_quit"
            if (
                options.timeout_in_seconds is not None
                and time.time() - start_time > options.timeout_in_seconds
            ):
                return "timeout"
            if options.max_evals is not None:
                evals = (
                    num_evals0
                    + (pending_evals() if pending_evals is not None else 0.0)
                    + sum(float(s.num_evals) for s in states)
                )
                if evals >= options.max_evals:
                    return "max_evals"
            return None

        # ALWAYS split each iteration's evolve phase into chunks with the
        # budget polled between launches, so a timeout / max_evals /
        # user-quit can't overshoot by a whole iteration (the reference
        # checks once per dispatched cycle batch,
        # src/SymbolicRegression.jl:1202-1209). The chunk count adapts to
        # the measured iteration time, targeting ~1 s stop latency; launch
        # machinery is a small fraction of device time at these counts. The
        # engine keeps chunked and single-launch iterations bit-identical
        # (global cycle indices; one epilogue), so chunking — and re-chunking
        # between iterations — changes only check granularity, not results.
        _STOP_LATENCY_TARGET_S = 1.0
        _MAX_CHUNKS = 16
        n_chunks = min(4, options.ncycles_per_iteration)

        def _chunk_sizes():
            # EQUAL chunks whose length divides ncycles: uneven splits
            # (e.g. 13+12) compile one evolve program per distinct length,
            # and every adaptation of n_chunks would add more — measured as
            # ~minutes of XLA compiles in a quickstart fit at the
            # device-scale config. With divisor-sized chunks each
            # adaptation costs at most one new program, often zero.
            nc = options.ncycles_per_iteration
            target = max(nc // n_chunks, 1)
            length = next((d for d in range(target, nc + 1) if nc % d == 0), nc)
            # Chunk-count bound (round-4 advisor concern, resolved by proof
            # rather than a guard): length >= max(nc // n_chunks, 1) implies
            # nc // length <= 2 * n_chunks for every nc, n_chunks >= 1
            # (brute-force verified over nc, n_chunks in 1..2000), so the
            # divisor search can never return more than twice the requested
            # chunk count — no degenerate host-dispatch blow-up exists.
            if length <= 2 * target or n_chunks == 1:
                return [length] * (nc // length)
            # No divisor near the target (prime-ish nc): fall back to
            # near-equal chunks so mid-iteration budget polling stays live
            # (two compiled lengths instead of one — still bounded).
            base, rem = divmod(nc, n_chunks)
            sizes = [base + (1 if c < rem else 0) for c in range(n_chunks)]
            return [c for c in sizes if c > 0]

        def _budget_hit(pending_evals=None) -> bool:
            nonlocal stop_reason
            if stop_reason is None:
                stop_reason = _budget_stop(pending_evals)
            return stop_reason is not None

        # Host-overhead tracking (ResourceMonitor analogue,
        # src/SearchUtils.jl:411-438).
        from ..utils.monitor import ResourceMonitor

        monitor = ResourceMonitor()
        host_t0 = time.time()

        it = start_iter
        used_chunk_sets = set()
        # Device-side cur_maxsize cache: the value only changes while the
        # maxsize warmup ramps, so upload it on change instead of paying a
        # (tiny, but per-iteration) host→device scalar transfer in the hot
        # loop — keeps the loop clean under graftlint's no_transfer guard.
        cur_maxsize_host: Optional[int] = None
        cur_maxsize_dev = None
        while it < ropt.niterations and stop_reason is None:
            cur_maxsize = get_cur_maxsize(
                options.maxsize, options.warmup_maxsize_by, total_cycles,
                cycles_remaining,
            )
            if cur_maxsize != cur_maxsize_host:
                cur_maxsize_host = cur_maxsize
                cur_maxsize_dev = jnp.int32(cur_maxsize)
            dev_t0 = time.time()
            monitor_host = dev_t0 - host_t0  # bookkeeping since last iteration
            chunk_sizes = _chunk_sizes()
            fresh_compile = tuple(chunk_sizes) not in used_chunk_sets
            used_chunk_sets.add(tuple(chunk_sizes))
            iter_events = [None] * len(engines)
            # Watchdog budgets: compile-bearing dispatches (first of
            # this process, a fresh chunk-size set, or any re-attempt
            # after retry/degrade — a degrade drops the compiled
            # programs) are bounded by compile_budget; warm dispatches
            # by iteration_deadline. Each ATTEMPT gets its own phase so
            # the shield's recovery work between attempts (backoff
            # sleeps, the degrade recompile decision) is never inside a
            # supervised window — the watchdog must not kill the exact
            # recovery it coexists with. None budgets = unsupervised.
            compiling = fresh_compile or it == start_iter
            dispatch_count = {"n": 0}

            def _phase_for_attempt():
                import contextlib

                if watchdog is None:
                    return contextlib.nullcontext()
                comp = compiling or dispatch_count["n"] > len(engines)
                budget = (options.compile_budget if comp
                          else options.iteration_deadline)
                return watchdog.phase("compile" if comp else "iteration",
                                      budget, iteration=it + 1)

            # graftpulse capture boundary: open an armed trace window
            # before this iteration's device work so the window covers
            # whole iterations (SIGUSR2 arms here too — the handler only
            # set a flag, per GL007).
            if pulse_cap is not None:
                if pulse_sig is not None and pulse_sig.consume():
                    pulse_cap.arm("sigusr2", it + 1)
                pulse_cap.maybe_start(it + 1)
            # sr:iteration span: one profiler step per search iteration,
            # so a perfetto/xplane capture lines up device work with
            # iterations; the graftledger ids make the capture joinable
            # with the JSONL streams and the exported timeline.
            with step_span(it + 1, trace_id=search_trace.trace_id,
                           span_id=search_trace.span_id):
                for j, (engine, data) in enumerate(zip(engines, datas)):
                    def one(j=j, engine=engine, data=data):
                        dispatch_count["n"] += 1
                        with _phase_for_attempt():
                            # inside the supervised phase so an injected
                            # hang is seen by the watchdog deadline
                            if injector is not None:
                                injector.on_dispatch(it + 1)
                            return engine.run_iteration(
                                states[j], data, cur_maxsize_dev,
                                chunk_sizes=(chunk_sizes
                                             if len(chunk_sizes) > 1
                                             else None),
                                should_stop=_budget_hit,
                            )
                    # graftgauge dispatch latency: the launch call
                    # (enqueue, not device execution — the blocking
                    # sync is below). perf_counter around a call the
                    # loop makes anyway; bit-neutral.
                    lat_t0 = time.perf_counter() if gauge_lat is not None \
                        else None
                    if runner is not None:
                        out = runner.run(one, iteration=it + 1,
                                         engine=engine, output=j + 1)
                    else:
                        out = one()
                    if lat_t0 is not None:
                        lat_dt = time.perf_counter() - lat_t0
                        gauge_lat.observe(lat_dt)
                        _gauge_global_latency().observe(lat_dt)
                    if engine.cfg.record_events:
                        states[j], iter_events[j] = out
                    else:
                        states[j] = out
                with _phase_for_attempt():
                    jax.block_until_ready(states[-1].pops.cost)
            host_t0 = time.time()
            # Adapt chunk count toward the stop-latency target using this
            # iteration's measured device time, quantized to powers of two —
            # each distinct chunk-size set compiles its own evolve program
            # (tens of seconds at device-scale configs), so the count must
            # not wander with timing noise, and an iteration that COMPILED a
            # new set must never feed the adaptation (its wall time is
            # compile-dominated; adapting off it churned chunk lengths and
            # recompiled every iteration). The first iteration is skipped
            # for the same reason.
            if it >= 1 and not fresh_compile:  # 0 == first iteration
                target = (host_t0 - dev_t0) / _STOP_LATENCY_TARGET_S
                cap = min(options.ncycles_per_iteration, _MAX_CHUNKS)
                n_chunks = 1
                while n_chunks < cap and n_chunks * 2 <= target:
                    n_chunks *= 2
            monitor.record(host_t0 - dev_t0, monitor_host)
            monitor.check_and_warn(ropt.verbosity)
            cycles_remaining -= options.ncycles_per_iteration
            it += 1

            # graftshield boundary work: fault injection hooks fire
            # first (a poisoned island must be visible to the quarantine
            # scan below, the same ordering a real storm has), then the
            # quarantine reseeds any collapsed islands from the HoF.
            if injector is not None:
                states = injector.on_iteration_end(it, states)
            if quarantine is not None:
                for j, engine in enumerate(engines):
                    states[j] = quarantine.check_and_reseed(
                        engine, states[j], iteration=it, output=j + 1
                    )
            if guard.requested and stop_reason is None:
                stop_reason = "preempted"
                hub.fault(
                    "preempt_signal", iteration=it,
                    signal=guard.signal_name,
                )
            # External stop hook (serve cancellation/deadline): boundary-
            # only, same contract as the preemption guard above — the
            # state checkpointed after this stop is one an uninterrupted
            # run also reaches, keeping resume="auto" bit-identical.
            if stop_reason is None and ropt.stop_hook is not None:
                hook_reason = ropt.stop_hook()
                if hook_reason:
                    stop_reason = str(hook_reason)
                    hub.fault(
                        "external_stop", iteration=it, reason=stop_reason,
                    )

            # Host-side bookkeeping once per iteration (not per cycle).
            total_evals = num_evals0 + sum(
                float(s.num_evals) for s in states
            )
            with host_span("hof_decode"):
                for j, engine in enumerate(engines):
                    hofs[j] = HallOfFame.from_device(
                        states[j].hof, options.operators,
                        template=engine.template,
                    )
            with host_span("checkpoint"):
                for j, ds in enumerate(datasets):
                    if out_dir is not None and is_rank0:
                        fname = (
                            "hall_of_fame.csv"
                            if len(datasets) == 1
                            else f"hall_of_fame_output{j + 1}.csv"
                        )
                        save_hall_of_fame_csv(
                            os.path.join(out_dir, fname), hofs[j],
                            options.operators,
                            variable_names=ds.variable_names,
                        )
                if ckpt is not None and it % ropt.checkpoint_every_n == 0:
                    # Periodic full-state checkpoint next to the CSVs:
                    # kill the process at a checkpoint boundary and
                    # resume with equation_search(resume="auto") (or
                    # saved_state=<path>). Rolling last-K, digest-
                    # verified (shield/checkpoints.py). Not every
                    # iteration — the population pytree is much larger
                    # than the HoF CSVs; the final/stopping state is
                    # written once after the loop.
                    _note_checkpoint_bytes(ckpt.save(_checkpoint_state()))
                    last_ckpt_it = it

            # One hub dispatch replaces the old ad-hoc recorder/logger/bar
            # wiring: fetch device counters, merge timings + compile events,
            # maybe emit the JSONL iteration event, run every sink.
            elapsed = time.time() - start_time
            best_loss = min(
                (e.loss for h in hofs for e in h.entries), default=np.inf
            )
            rate = total_evals / max(elapsed, 1e-9)
            hub.iteration(IterationContext(
                iteration=it,
                states=states,
                hofs=hofs,
                options=options,
                num_evals=total_evals,
                elapsed=elapsed,
                best_loss=best_loss,
                evals_per_sec=rate,
                device_s=host_t0 - dev_t0,
                host_s=monitor_host,
                host_fraction=monitor.estimate_work_fraction(),
                events=iter_events,
            ))
            # Close the trace window once it has covered its iterations
            # (after hub.iteration so the capture includes the host-side
            # sink spans of its last iteration).
            if pulse_cap is not None:
                pulse_cap.maybe_stop(it)
            # graftmesh: periodic cross-shard dedup-key exchange →
            # ``mesh`` telemetry events. Stream-gated (the exchange is
            # one small collective; pay it only when someone records
            # it) and observability-only — it never touches the state,
            # so the search trajectory is identical with it on or off.
            if (mesh_plan is not None and hub.path is not None
                    and mesh_plan.dedup_exchange_every > 0
                    and it % mesh_plan.dedup_exchange_every == 0):
                for j, engine in enumerate(engines):
                    hub.mesh(
                        iteration=it, shards=mesh_plan.n_island_shards,
                        output=j + 1, **engine.dedup_exchange(states[j]),
                    )
            if ropt.verbosity >= 2:
                print(
                    f"[iter {it}/{ropt.niterations}] "
                    f"best_loss={best_loss:.6g} evals={total_evals:.3g} "
                    f"({rate:.3g}/s, host "
                    f"{monitor.estimate_work_fraction():.0%})"
                )

            # ---- early stopping (src/SearchUtils.jl:387-409) ----
            if options.early_stop_condition is not None:
                hit = any(
                    options.early_stop_condition(e.loss, e.complexity)
                    for h in hofs
                    for e in h.entries
                )
                if hit:
                    stop_reason = "early_stop_condition"
            if stop_reason is None:
                stop_reason = _budget_stop()

        watcher.stop()
        if ckpt is not None and it > start_iter and it != last_ckpt_it:
            # `it > start_iter`, not `it > 0`: a resume that ran zero
            # further iterations (target already reached) must not
            # re-save an identical state — each such save would rotate
            # away one distinct older generation of the rolling set.
            # Guarantee the final/stopping state is checkpointed even when
            # the stop was detected after the periodic write (early-stop
            # condition, end-of-loop budget check, or a preemption
            # signal — for "preempted" this IS the emergency checkpoint
            # the SIGTERM handler deferred to the iteration boundary).
            # Skipped only when this exact iteration already saved (it
            # would duplicate the state and burn a rolling generation).
            _note_checkpoint_bytes(ckpt.save(_checkpoint_state()))
        if ckpt is not None and it > 0 and stop_reason == "preempted":
            hub.fault(
                "emergency_checkpoint", iteration=it,
                path=ckpt.base, iterations_done=it,
            )
        # graftgauge end-of-run records, while the stream is still open
        # (hub.finish writes run_end; sink on_end output would land
        # after it, so these are emitted explicitly here): the memory
        # watermark summary and the dispatch-latency histogram.
        if gauge_sampler is not None:
            gauge_sampler.emit_final(iteration=int(it))
        if gauge_lat is not None and gauge_lat.count:
            hub.gauge("dispatch_latency", iteration=int(it),
                      **gauge_lat.to_detail())
        # Flush any partial telemetry interval, emit run_end, close sinks
        # (ProgressBar close, Recorder final-state + write).
        hub.finish(
            stop_reason=stop_reason or "niterations",
            num_evals=num_evals0 + sum(float(s.num_evals) for s in states),
            elapsed=time.time() - start_time,
        )
    finally:
        # graftpulse teardown first, while the hub is still open: dump
        # the flight-recorder ring when the run is exiting on an error
        # (the fault-watcher path already covered shield-visible
        # failures; this catches everything else), force-close any open
        # trace window, release SIGUSR2.
        exc_type = sys.exc_info()[0]
        if pulse_rec is not None and exc_type is not None:
            pulse_rec.dump(trigger={
                "reason": "exception",
                "kind": exc_type.__name__,
                "iteration": int(it),
            })
        if pulse_cap is not None:
            pulse_cap.close(int(it))
        if pulse_sig is not None:
            pulse_sig.uninstall()
        # A failing or interrupted search must still release the
        # hub's process-global jax.monitoring compile listener
        # (idempotent after a clean finish) and the graftshield
        # process-globals (signal handlers, watchdog thread).
        hub.close()
        guard.uninstall()
        if watchdog is not None:
            watchdog.stop()
        if ledger_sink is not None or gauge_sampler is not None:
            # clear this thread's span observer — a serve worker thread
            # runs many searches back to back, and the next one must
            # not bill its phases to this request's ledger (or this
            # run's gauge watermarks)
            set_span_observer(None)

    if ropt.verbosity >= 1:
        for j, (hof, ds) in enumerate(zip(hofs, datasets)):
            if len(datasets) > 1:
                print(f"Output {j + 1} ({ds.y_variable_name}):")
            print(
                string_dominating_pareto_curve(
                    hof, options.operators,
                    variable_names=ds.display_variable_names,
                    loss_scale=options.loss_scale,
                )
            )
        if stop_reason:
            print(f"Search stopped early: {stop_reason}")

    result: Any = hofs if len(datasets) > 1 else hofs[0]
    if ropt.return_state:
        host_state = SearchState(
            device_states=[jax.device_get(s) for s in states],
            hofs=hofs,
            options=options,
            num_evals=num_evals0 + sum(float(s.num_evals) for s in states),
            nfeatures=[ds.nfeatures for ds in datasets],
            iterations_done=it,
        )
        return host_state, result
    return result


def warmup(
    options: Optional[Options] = None,
    *,
    nfeatures: int = 2,
    n_rows: int = 10_000,
    niterations: int = 4,
    dtype=None,
    seed: int = 0,
) -> None:
    """Pre-compile the search programs for a config, warming the
    persistent XLA cache so the first real ``fit`` at the same shapes
    starts in seconds instead of minutes.

    XLA compiles are keyed on program *shapes*: islands × population
    (``options.populations`` / ``population_size``), ``maxsize``, the
    operator set, ``nfeatures``, dataset rows, and batch size. Call
    this with the same ``Options`` and data shape you will fit with —
    e.g. once on a build machine, or at service start-up — and the
    cold-start compile (~2.5 min at the device-scale config,
    profiling/compile_breakdown.py) is paid here instead of in the
    user-facing fit. Nothing is written to disk (saving is disabled on
    a copy of ``options``); the random fitting data never matters —
    only shapes do.

    Chunk-count adaptation picks evolve-chunk lengths from measured
    iteration time (quantized powers of two over divisor-stable
    sizes), so the default 4 iterations let warmup adapt the same way
    a real fit on this machine would and pre-compile the adapted
    chunk program too, not just the initial one.

    On CPU backends the default persistent cache is disabled (see
    ``_enable_default_compile_cache``: XLA:CPU AOT cache entries can
    SIGILL across machine-feature sets), so warmup there warms nothing
    unless you set ``jax_compilation_cache_dir`` yourself — it exists
    for the TPU cold-start, which is where the minutes are.

    ``SR_XLA_EFFORT=-1`` cuts the one-time compile a further ~25%
    but costs ~3× steady-state device throughput (measured, both
    -0.5 and -1.0: bench 507k → 165-169k evals/s) — only worth it
    for compile-only contexts (CI smoke runs), never for real fits,
    and note the persistent cache keys on compile options, so a
    warmup at one effort level does not warm fits at another.
    """
    import copy

    options = copy.copy(options) if options is not None else Options()
    options.save_to_file = False
    rng = np.random.default_rng(seed)
    X = rng.uniform(-3.0, 3.0, (int(n_rows), int(nfeatures)))
    y = rng.uniform(-1.0, 1.0, (int(n_rows),))
    equation_search(
        X, y, options=options, niterations=niterations,
        verbosity=0, progress=False, seed=seed, dtype=dtype,
    )


def _stdin_is_tty() -> bool:
    try:
        return sys.stdin is not None and sys.stdin.isatty()
    except (AttributeError, ValueError, OSError):
        return False


def _is_guess_pair(g) -> bool:
    """An (expression, fitted_params) guess — the element shape produced
    by load_hall_of_fame_csv(return_params=True). The expression may be
    a string, Node, {key: expr} template dict, or HostTemplateExpression."""
    from ..models.template import HostTemplateExpression

    return (
        isinstance(g, tuple)
        and len(g) == 2
        and isinstance(g[0], (str, Node, dict, HostTemplateExpression))
        and (g[1] is None or isinstance(g[1], (np.ndarray, list)))
    )


def _is_nested(guesses, nout: int) -> bool:
    """Per-output nested guesses (list of per-output guess lists) — an
    (expr, params) pair is a single guess, never a nesting level."""
    return (
        nout > 1
        and isinstance(guesses, (list, tuple))
        and len(guesses) == nout
        and all(
            isinstance(g, (list, tuple)) and not _is_guess_pair(g)
            for g in guesses
        )
    )


def _np_dtype(name: str):
    import jax.numpy as jnp

    return {"float32": jnp.float32, "float64": jnp.float64,
            "bfloat16": jnp.bfloat16}[str(name)]
