"""Sklearn-style regressor API (TPU analogue of src/MLJInterface.jl).

`SRRegressor` mirrors every `Options` kwarg as a constructor kwarg
(the reference auto-generates its model struct the same way,
/root/reference/src/MLJInterface.jl:68-126), runs `equation_search` on
`fit`, supports warm-start refits that run only the *delta* iterations
(/root/reference/src/MLJInterface.jl:292-294), and predicts with the
`choose_best` selection rule (:611-630) or a user-chosen equation index.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..core.options import Options
from ..ops.encoding import encode_population
from ..ops.eval import eval_tree_batch
from ..ops.tree import Node, string_tree
from .hall_of_fame import HallOfFame, calculate_pareto_frontier, compute_scores
from .search import RuntimeOptions, SearchState, equation_search

__all__ = ["SRRegressor", "MultitargetSRRegressor", "choose_best"]


def _coerce_table(X):
    """(values [n, F], column_names | None) from array-likes or column
    tables (pandas DataFrame, dict of columns) — the MLJ table-input
    analogue (src/MLJInterface.jl:366-380)."""
    if hasattr(X, "columns") and hasattr(X, "to_numpy"):  # DataFrame
        return X.to_numpy(), [str(c) for c in X.columns]
    if isinstance(X, dict):
        names = list(X)
        cols = [np.asarray(X[k]).reshape(-1) for k in names]
        return np.stack(cols, axis=1), [str(n) for n in names]
    return np.asarray(X), None


def choose_best(
    *, trees, losses, scores, complexities, options: Optional[Options] = None
) -> int:
    """Max score among equations with loss below 1.5x the minimum loss
    (src/MLJInterface.jl:611-630; same as PySR's model_selection='best').
    Linear loss_scale falls back to plain argmin(loss)."""
    losses = np.asarray(losses, dtype=float)
    if options is not None and options.loss_scale == "linear":
        return int(np.argmin(losses))
    threshold = 1.5 * np.min(losses)
    masked_scores = [
        s if l <= threshold else -np.inf for s, l in zip(scores, losses)
    ]
    return int(np.argmax(masked_scores))


@dataclasses.dataclass
class EquationRecord:
    """One row of the fitted report (equations_ table)."""

    complexity: int
    loss: float
    score: float
    equation: str
    tree: Optional[Node]
    # (n_params, n_classes) for parametric expressions, else None.
    params: Optional[np.ndarray] = None
    # HostTemplateExpression for template specs (tree is None then).
    template_expr: Optional[Any] = None


class SRRegressor:
    """Symbolic-regression estimator with the sklearn fit/predict contract.

    Examples
    --------
    >>> model = SRRegressor(niterations=5, binary_operators=["+", "*"])
    >>> model.fit(X, y)
    >>> model.predict(X)
    """

    _MULTITARGET = False

    def __init__(
        self,
        *,
        niterations: int = 40,
        selection_method: Callable = choose_best,
        seed: Optional[int] = None,
        verbosity: int = 0,
        progress: bool = False,
        run_id: Optional[str] = None,
        warm_start: bool = True,
        devices=None,
        n_data_shards: int = 1,
        device_scale: Union[str, bool] = "auto",
        **option_kwargs: Any,
    ):
        self.niterations = int(niterations)
        self.selection_method = selection_method
        self.seed = seed
        self.verbosity = verbosity
        self.progress = progress
        self.run_id = run_id
        self.warm_start = bool(warm_start)
        self.devices = devices
        self.n_data_shards = int(n_data_shards)
        self.device_scale = device_scale
        self.option_kwargs = dict(option_kwargs)

        # Fitted state:
        self.options_: Optional[Options] = None
        self.state_: Optional[SearchState] = None
        self.hofs_: Optional[List[HallOfFame]] = None
        self.equations_: Optional[Any] = None
        self.best_idx_: Optional[Any] = None
        self.nout_: int = 1
        self.nfeatures_: Optional[int] = None
        self.variable_names_: Optional[Sequence[str]] = None
        self.fitted_iterations_: int = 0
        self.classes_: Optional[np.ndarray] = None
        self.y_units_ = None
        self._named_fit_ = False

    # TPU-native search scale (profiling/config_sweep.py optimum on
    # v5e-1; ~12x the chip throughput of the reference's 31x27 default,
    # quality-validated head-to-head in profiling/quality_results.json —
    # the tpunative leg vs the reference-config tpu31 leg).
    _DEVICE_SCALE_CONFIG = dict(
        populations=512,
        population_size=256,
        tournament_selection_n=16,
        ncycles_per_iteration=100,
    )

    # ------------------------------------------------------------------
    def _make_options(self) -> Options:
        kwargs = dict(self.option_kwargs)
        self.device_scaled_ = False
        if self.device_scale in ("auto", True):
            import jax

            # The reference's defaults (populations=31 x 27,
            # /root/reference/src/Options.jl:1161-1208) idle a TPU at
            # ~8% of its demonstrated throughput. Unless the user pins
            # any of the scale knobs, quickstarts on a TPU backend get
            # the config-sweep optimum instead.
            pinned = set(self._DEVICE_SCALE_CONFIG) & set(kwargs)
            if jax.default_backend() == "tpu" and not pinned:
                kwargs.update(self._DEVICE_SCALE_CONFIG)
                self.device_scaled_ = True
        return Options(seed=self.seed, **kwargs)

    def fit(
        self,
        X,
        y,
        *,
        weights=None,
        variable_names: Optional[Sequence[str]] = None,
        X_units=None,
        y_units=None,
        category=None,
        resume: Optional[str] = None,
    ) -> "SRRegressor":
        """Run the search. ``resume="auto"`` (or a checkpoint/run-dir
        path) continues a preempted search from the newest valid
        graftshield checkpoint, treating ``niterations`` as the total
        target — see ``equation_search`` / docs/ROBUSTNESS.md."""
        X, table_names = _coerce_table(X)
        if variable_names is None and table_names is not None:
            variable_names = table_names
        self._named_fit_ = variable_names is not None
        y = np.asarray(y)
        if self._MULTITARGET:
            if y.ndim != 2:
                raise ValueError("MultitargetSRRegressor requires 2D y")
            # sklearn convention (n, nout) -> internal (nout, n)
            y_internal = y.T
            self.nout_ = y_internal.shape[0]
        else:
            if y.ndim != 1:
                raise ValueError("SRRegressor requires 1D y; use Multitarget")
            y_internal = y
            self.nout_ = 1

        new_options = self._make_options()
        saved_state = None
        if resume is None and self.warm_start and self.state_ is not None:
            issues = new_options.check_warm_start_compatibility(self.options_)
            if issues:
                raise ValueError(
                    "Warm-start refit with changed incompatible options: "
                    f"{issues}. Pass warm_start=False or reset the model."
                )
            saved_state = self.state_
        self.options_ = new_options
        self.nfeatures_ = X.shape[1]
        self.variable_names_ = (
            list(variable_names)
            if variable_names is not None
            else [f"x{i + 1}" for i in range(X.shape[1])]
        )
        self.y_units_ = y_units

        extra = None
        self.classes_ = None
        if category is not None:
            cat = np.asarray(category)
            extra = {"class": cat}
            # Training class -> parameter-column mapping (mirrors
            # make_dataset's searchsorted encoding) for predict-time reuse.
            self.classes_ = np.unique(cat)

        # Warm-start refits run only the *delta* iterations
        # (src/MLJInterface.jl:292-294): fitting twice with the same
        # niterations runs no extra work; raising niterations runs the
        # difference.
        niterations = self.niterations
        if saved_state is not None:
            niterations = max(self.niterations - self.fitted_iterations_, 0)
        if saved_state is not None and niterations == 0:
            self._build_report()
            return self

        ropt = RuntimeOptions(
            niterations=niterations,
            devices=self.devices,
            n_data_shards=self.n_data_shards,
            verbosity=self.verbosity,
            progress=self.progress,
            seed=self.seed,
            return_state=True,
        )
        if self.run_id is not None:
            ropt.run_id = self.run_id
        state, hof = equation_search(
            X,
            y_internal,
            options=new_options,
            weights=weights,
            variable_names=variable_names,
            X_units=X_units,
            y_units=y_units,
            extra=extra,
            saved_state=saved_state,
            resume=resume,
            runtime_options=ropt,
        )
        self.state_ = state
        self.hofs_ = hof if isinstance(hof, list) else [hof]
        if saved_state is None:
            self.fitted_iterations_ = niterations  # cold fit resets the count
        else:
            self.fitted_iterations_ += niterations
        self._build_report()
        return self

    # ------------------------------------------------------------------
    def _build_report(self) -> None:
        # sr:host:report span (telemetry/spans.py): pareto scoring +
        # equation stringification shows up as a named host phase in
        # profiler captures alongside the search's sr:iteration steps.
        from ..telemetry.spans import host_span

        with host_span("report"):
            self._build_report_inner()

    def _build_report_inner(self) -> None:
        tables: List[List[EquationRecord]] = []
        best_idx: List[int] = []
        for hof in self.hofs_:
            frontier = compute_scores(
                calculate_pareto_frontier(hof.entries), self.options_.loss_scale
            )
            recs = [
                EquationRecord(
                    complexity=e.complexity,
                    loss=e.loss,
                    score=e.score,
                    equation=e.equation_string(
                        variable_names=self.variable_names_
                    ),
                    tree=e.tree,
                    params=e.params,
                    template_expr=e.template_expr,
                )
                for e in frontier
            ]
            tables.append(recs)
            if recs:
                best_idx.append(
                    self.selection_method(
                        trees=[r.tree for r in recs],
                        losses=[r.loss for r in recs],
                        scores=[r.score for r in recs],
                        complexities=[r.complexity for r in recs],
                        options=self.options_,
                    )
                )
            else:
                best_idx.append(0)
        if self._MULTITARGET:
            self.equations_ = tables
            self.best_idx_ = best_idx
        else:
            self.equations_ = tables[0]
            self.best_idx_ = best_idx[0]

    def _check_fitted(self) -> None:
        if self.equations_ is None:
            raise RuntimeError("This SRRegressor instance is not fitted yet.")

    # ------------------------------------------------------------------
    def _predict_one(self, recs, idx, X, category=None) -> np.ndarray:
        import jax.numpy as jnp

        rec = recs[idx]
        if rec.template_expr is not None:
            out = rec.template_expr(X)
            if np.any(~np.isfinite(out)):
                # prediction_fallback: zeros on invalid eval
                # (src/MLJInterface.jl:431-456)
                out = np.zeros(X.shape[0], out.dtype)
            return out
        tree = rec.tree
        enc = encode_population(
            [tree], max(tree.count_nodes(), 1), self.options_.operators
        )
        params = None
        if rec.params is not None and rec.params.shape[0] > 0:
            if category is None:
                raise ValueError(
                    "This model was fit with a parametric expression spec; "
                    "predict requires `category=`"
                )
            cat = np.asarray(category)
            if cat.shape[0] != X.shape[0]:
                raise ValueError(
                    f"`category` has {cat.shape[0]} entries but X has "
                    f"{X.shape[0]} rows — one category per row is required"
                )
            cls = np.searchsorted(self.classes_, cat)
            cls = np.clip(cls, 0, rec.params.shape[1] - 1)
            unseen = self.classes_[cls] != cat
            if np.any(unseen):
                raise ValueError(
                    "predict got categories not seen during fit: "
                    f"{np.unique(cat[unseen])!r} (known: {self.classes_!r})"
                )
            # Per-row parameter values p[k, row] = params[k, class[row]].
            params = jnp.asarray(rec.params[:, cls])[None]
        y, valid = eval_tree_batch(
            enc, jnp.asarray(X.T), self.options_.operators, params=params
        )
        out = np.asarray(y[0])
        if not bool(valid[0]):
            # prediction_fallback: zeros on invalid eval
            # (src/MLJInterface.jl:431-456)
            out = np.zeros(X.shape[0], out.dtype)
        return out

    def predict(self, X, idx: Optional[Union[int, Sequence[int]]] = None,
                *, category=None, with_units: bool = False):
        """Predict with the selected (or ``idx``-chosen) equation.

        Column tables (pandas DataFrames / dicts of columns) are
        accepted and reordered by the fitted variable names. With
        ``with_units=True`` (and ``y_units`` given at fit) the result is
        a :class:`~..core.units.QuantityArray` echoing those units —
        the unit-typed predict round-trip of the reference
        (src/MLJInterface.jl:366-380).
        """
        self._check_fitted()
        X, table_names = _coerce_table(X)
        if table_names is not None and self.variable_names_ is not None:
            if set(self.variable_names_) <= set(table_names):
                order = [table_names.index(n) for n in self.variable_names_]
                X = X[:, order]
            elif self._named_fit_:
                # The fit was name-aware: a silent positional fallback
                # would feed columns into the wrong variables (the MLJ
                # reference errors on name mismatch too).
                raise ValueError(
                    f"Prediction table columns {table_names} do not cover "
                    f"the fitted variable names {list(self.variable_names_)}"
                )
        if self._MULTITARGET:
            if idx is None:
                idxs = list(self.best_idx_)
            elif np.ndim(idx) == 0:
                idxs = [int(idx)] * len(self.equations_)
            else:
                idxs = list(idx)
            outs = [
                self._predict_one(recs, i, X, category)
                for recs, i in zip(self.equations_, idxs)
            ]
            out = np.stack(outs, axis=1)
        else:
            i = int(idx) if idx is not None else int(self.best_idx_)
            out = self._predict_one(self.equations_, i, X, category)
        if with_units and self.y_units_ is not None:
            from ..core.units import QuantityArray

            return QuantityArray(out, self.y_units_)
        return out

    def score(self, X, y, *, sample_weight=None, category=None) -> float:
        """Coefficient of determination R^2 (sklearn convention)."""
        self._check_fitted()
        y = np.asarray(y)
        pred = self.predict(X, category=category)
        if self._MULTITARGET:
            pred = pred.reshape(y.shape)
        w = (
            np.ones_like(y, dtype=float)
            if sample_weight is None
            else np.asarray(sample_weight, dtype=float)
        )
        ss_res = float(np.sum(w * (y - pred) ** 2))
        ss_tot = float(np.sum(w * (y - np.average(y, weights=w)) ** 2))
        if ss_tot == 0:
            return 0.0 if ss_res > 0 else 1.0
        return 1.0 - ss_res / ss_tot

    # ------------------------------------------------------------------
    def get_best(self):
        """The selected equation record (report row)."""
        self._check_fitted()
        if self._MULTITARGET:
            return [
                recs[i] for recs, i in zip(self.equations_, self.best_idx_)
            ]
        return self.equations_[self.best_idx_]

    @staticmethod
    def _export_tree(rec):
        if rec.tree is None:
            raise NotImplementedError(
                "sympy export is not supported for template expressions — "
                "use the record's `.equation` string (per-subexpression "
                "strings via .template_expr) or .latex()"
            )
        return rec.tree

    def _latex_one(self, rec) -> str:
        from ..utils.export import template_to_latex, to_latex

        if rec.template_expr is not None:
            return template_to_latex(rec.template_expr)
        return to_latex(rec.tree, variable_names=self.variable_names_)

    def latex(self, idx: Optional[int] = None) -> Union[str, List[str]]:
        """LaTeX form of the selected equation(s); template expressions
        render as an aligned per-component block."""
        self._check_fitted()
        if self._MULTITARGET:
            return [
                self._latex_one(recs[i if idx is None else idx])
                for recs, i in zip(self.equations_, self.best_idx_)
            ]
        i = int(idx) if idx is not None else int(self.best_idx_)
        return self._latex_one(self.equations_[i])

    def sympy(self, idx: Optional[int] = None):
        """SymPy expression of the selected equation (requires sympy)."""
        from ..utils.export import to_sympy

        self._check_fitted()
        if self._MULTITARGET:
            return [
                to_sympy(self._export_tree(recs[i if idx is None else idx]),
                         variable_names=self.variable_names_)
                for recs, i in zip(self.equations_, self.best_idx_)
            ]
        i = int(idx) if idx is not None else int(self.best_idx_)
        return to_sympy(self._export_tree(self.equations_[i]),
                        variable_names=self.variable_names_)

    def __repr__(self) -> str:  # pragma: no cover
        fitted = "fitted" if self.equations_ is not None else "unfitted"
        return (
            f"{type(self).__name__}(niterations={self.niterations}, "
            f"{fitted})"
        )


class MultitargetSRRegressor(SRRegressor):
    """Multi-output variant: ``y`` has shape (n, nout); one hall of fame
    and one selected equation per output (src/MLJInterface.jl MTSR)."""

    _MULTITARGET = True
