"""On-disk checkpoint/resume of the full search state.

The reference resumes from saved output by re-parsing hall-of-fame CSVs
and recomputing losses (/root/reference/src/SymbolicRegression.jl:760-821,
SearchUtils.jl:532-555). The TPU engine's state is a pytree of arrays, so
the full state (populations, hall of fame, adaptive-parsimony stats, RNG
key) serializes exactly — resume continues the *identical* search, not a
re-parse approximation. The CSV dumps remain alongside for
interoperability.

Format: one pickle file holding numpy-ified device states plus a
compatibility header (the same fields the in-memory warm start checks,
src/OptionsStruct.jl:314-336) so an incompatible resume fails with a
clear error before any state is touched.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import warnings
from typing import TYPE_CHECKING, List

import jax
import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ..core.options import Options
    from .search import SearchState

__all__ = ["save_search_state", "load_search_state", "options_compat_header"]

_FORMAT_VERSION = 1


def options_compat_header(options: "Options") -> dict:
    """Comparable summary of the warm-start-compatibility fields.

    Callables (custom operators, template combiners) can't be compared
    across processes; we compare by name/shape instead.
    """
    spec = options.expression_spec
    spec_desc: object = type(spec).__name__ if spec is not None else None
    if spec is not None and hasattr(spec, "max_parameters"):
        spec_desc = (spec_desc, spec.max_parameters)
    if spec is not None and hasattr(spec, "structure"):
        st = spec.structure
        spec_desc = (
            spec_desc, st.expr_keys, st.num_features, st.param_keys,
            st.num_params, st.n_variables,
        )
    # Best-effort combiner fingerprint: a structurally identical template
    # with a *different* combine function would otherwise pass the check
    # and silently resume under a new objective. Bytecode isn't stable
    # across Python versions, so mismatches warn rather than fail.
    fp = None
    if spec is not None and hasattr(spec, "structure"):
        fn = spec.structure.combine
        code = getattr(fn, "__code__", None)
        digest = _code_digest(code) if code is not None else None
        fp = (getattr(fn, "__qualname__", repr(fn)), digest)
    # Field list comes from the same source as the in-memory warm-start
    # check (Options._WARM_START_FIELDS) so the two can't drift — for
    # disk resumes this header IS the compatibility check (the loaded
    # SearchState carries the *new* options).
    header = {
        f: getattr(options, f)
        for f in type(options)._WARM_START_FIELDS
        if f != "expression_spec"
    }
    header["operators"] = (
        tuple(op.name for op in options.operators.unary),
        tuple(op.name for op in options.operators.binary),
    )
    header["expression_spec"] = spec_desc
    header["template_combiner_fp"] = fp
    return header


def _code_digest(code) -> str:
    """Process-stable digest of a code object.

    Recurses into nested code objects in co_consts (lambdas, genexprs):
    their repr embeds a memory address, which would make every resume
    look like a changed combiner."""
    h = hashlib.sha1(code.co_code)
    for c in code.co_consts:
        if hasattr(c, "co_code"):
            h.update(_code_digest(c).encode())
        else:
            h.update(repr(c).encode())
    return h.hexdigest()[:16]


_KNOWN_KEY_IMPLS = ("threefry2x32", "rbg", "unsafe_rbg")


def _to_numpy_state(ds):
    """Device state -> picklable numpy pytree (typed PRNG key unwrapped)."""
    ds = dataclasses.replace(ds, key=jax.random.key_data(ds.key))
    return jax.tree.map(np.asarray, jax.device_get(ds))


def _to_device_state(ds, key_impl: str = "threefry2x32"):
    if key_impl not in _KNOWN_KEY_IMPLS:
        raise ValueError(
            f"Checkpoint uses unknown PRNG key impl {key_impl!r}; "
            f"known: {_KNOWN_KEY_IMPLS}"
        )
    return dataclasses.replace(
        ds, key=jax.random.wrap_key_data(
            jax.numpy.asarray(ds.key), impl=key_impl
        )
    )


def _key_impl_name(state: "SearchState") -> str:
    """Record the *actual* key impl so a non-default key (e.g. rbg)
    round-trips instead of being silently reinterpreted on resume."""
    if not state.device_states:
        return "threefry2x32"
    return str(jax.random.key_impl(state.device_states[0].key))


def save_search_state(path: str, state: "SearchState") -> None:
    """Serialize a SearchState (the ``return_state=True`` result) to disk.

    Double-write (tmp + atomic replace) matching the CSV checkpoint
    discipline (src/SearchUtils.jl:605-649).

    Multi-process runs skip the pickle: the state is island-sharded
    across all hosts' devices, this function runs on rank 0 only, and
    any cross-host gather here would be a one-sided collective (deadlock).
    The per-iteration hall-of-fame CSVs remain the multi-host artifact.
    """
    if jax.process_count() > 1:
        warnings.warn(
            "save_search_state: skipping full-state pickle in a "
            "multi-process run (island shards span non-addressable "
            "devices); hall-of-fame CSVs are still written.",
            stacklevel=2,
        )
        return
    payload = {
        "format_version": _FORMAT_VERSION,
        "compat": options_compat_header(state.options),
        "num_evals": float(state.num_evals),
        "key_impl": _key_impl_name(state),
        "nfeatures": state.nfeatures,
        "device_states": [_to_numpy_state(ds) for ds in state.device_states],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".bak"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load_search_state(path: str, options: "Options") -> "SearchState":
    """Load a checkpoint for resumption under ``options``.

    Raises ValueError when the saved state is incompatible with the
    given options (same contract as the in-memory warm start,
    src/OptionsStruct.jl:314-336).
    """
    from .search import SearchState

    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("format_version") != _FORMAT_VERSION:
        raise ValueError(
            f"Unsupported checkpoint format: {payload.get('format_version')}"
        )
    saved = payload["compat"]
    now = options_compat_header(options)
    issues = [k for k in now
              if k != "template_combiner_fp" and saved.get(k) != now[k]]
    if issues:
        raise ValueError(
            f"Checkpoint incompatible with current options; changed: {issues}"
        )
    if ("template_combiner_fp" in saved
            and saved["template_combiner_fp"] != now.get(
                "template_combiner_fp")):
        warnings.warn(
            "Checkpoint was saved under a template combine function whose "
            "fingerprint differs from the current one; resuming will score "
            "carried-over losses under the new objective.",
            stacklevel=2,
        )
    device_states = [
        _to_device_state(ds, payload.get("key_impl", "threefry2x32"))
        for ds in payload["device_states"]
    ]
    return SearchState(
        device_states=device_states,
        hofs=[],  # rebuilt from device state on the first iteration
        options=options,
        num_evals=float(payload["num_evals"]),
        nfeatures=payload.get("nfeatures"),
    )
