"""On-disk checkpoint/resume of the full search state.

The reference resumes from saved output by re-parsing hall-of-fame CSVs
and recomputing losses (/root/reference/src/SymbolicRegression.jl:760-821,
SearchUtils.jl:532-555). The TPU engine's state is a pytree of arrays, so
the full state (populations, hall of fame, adaptive-parsimony stats, RNG
key) serializes exactly — resume continues the *identical* search, not a
re-parse approximation. The CSV dumps remain alongside for
interoperability.

Format (v2): one pickle file holding an outer envelope
``{"format": "srckpt.v2", "sha256": <hex>, "payload": <bytes>}`` whose
payload bytes are the v1 payload dict (numpy-ified device states plus a
compatibility header — the same fields the in-memory warm start checks,
src/OptionsStruct.jl:314-336). The digest is verified on write (the tmp
file is re-read before the atomic replace) and on load, so a truncated
or bit-flipped checkpoint raises :class:`CheckpointCorruptError` instead
of crashing mid-unpickle — the graftshield fallback machinery
(shield/checkpoints.py) catches it and walks back to the newest *valid*
rolling checkpoint. v1 files (bare payload pickle) still load.

Multi-host runs write one file per host — ``path.rank{k}`` holding that
host's addressable shards of every island-sharded array — and any host
(or a later single-host process) reassembles the full state by reading
all rank files from the shared run directory. No cross-host collectives
are involved in either direction.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import os
import pickle
import types
import warnings
from typing import TYPE_CHECKING, Any, List, Optional

import jax
import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from ..core.options import Options
    from .search import SearchState

__all__ = [
    "CheckpointCorruptError",
    "save_search_state",
    "load_search_state",
    "options_compat_header",
    "options_fingerprint",
    "rank_shard_paths",
]

_FORMAT_VERSION = 2
_ENVELOPE_MAGIC = "srckpt.v2"


class CheckpointCorruptError(ValueError):
    """A checkpoint file exists but cannot be consumed: truncated,
    bit-flipped (digest mismatch), unpicklable, or an unknown format
    version. Subclasses ValueError so pre-shield callers that caught
    ValueError keep working; the shield's fallback loader catches this
    specifically and falls back to the next rolling checkpoint."""


def options_compat_header(options: "Options") -> dict:
    """Comparable summary of the warm-start-compatibility fields.

    Callables (custom operators, template combiners) can't be compared
    across processes; we compare by name/shape instead.
    """
    spec = options.expression_spec
    spec_desc: object = type(spec).__name__ if spec is not None else None
    if spec is not None and hasattr(spec, "max_parameters"):
        spec_desc = (spec_desc, spec.max_parameters)
    if spec is not None and hasattr(spec, "structure"):
        st = spec.structure
        spec_desc = (
            spec_desc, st.expr_keys, st.num_features, st.param_keys,
            st.num_params, st.n_variables,
        )
    # Best-effort combiner fingerprint: a structurally identical template
    # with a *different* combine function would otherwise pass the check
    # and silently resume under a new objective. Bytecode isn't stable
    # across Python versions, so mismatches warn rather than fail.
    fp = None
    if spec is not None and hasattr(spec, "structure"):
        fn = spec.structure.combine
        code = getattr(fn, "__code__", None)
        digest = _code_digest(code) if code is not None else None
        fp = (getattr(fn, "__qualname__", repr(fn)), digest)
    # Field list comes from the same source as the in-memory warm-start
    # check (Options._WARM_START_FIELDS) so the two can't drift — for
    # disk resumes this header IS the compatibility check (the loaded
    # SearchState carries the *new* options).
    header = {
        f: getattr(options, f)
        for f in type(options)._WARM_START_FIELDS
        if f != "expression_spec"
    }
    header["operators"] = (
        tuple(op.name for op in options.operators.unary),
        tuple(op.name for op in options.operators.binary),
    )
    header["expression_spec"] = spec_desc
    header["template_combiner_fp"] = fp
    return header


# Options fields that only shape HOST-side supervision/IO — never the
# device programs or search numerics — so two configs differing only
# here may share one compiled engine (serve/cache.py).
_HOST_ONLY_OPTION_FIELDS = frozenset({
    "output_directory", "save_to_file", "use_recorder", "recorder_file",
    "recorder_verbosity", "verbosity", "print_precision", "progress",
    "telemetry", "telemetry_file", "telemetry_interval",
    "interactive_quit", "checkpoint_keep", "max_retries", "retry_backoff",
    "iteration_deadline", "compile_budget", "shield",
    "early_stop_condition", "timeout_in_seconds", "max_evals",
    # the seed feeds the host-made PRNG key at run time; the compiled
    # programs are seed-agnostic (the key is a traced input)
    "seed",
})


class _Unfingerprintable(Exception):
    """A value with no process-stable canonical form (e.g. a C callable
    or an arbitrary object) — the config is uncacheable, not an error."""


def _global_name_reads(code) -> set:
    """All names a code object (and its nested genexprs/lambdas/
    comprehensions, which carry their own code objects in co_consts)
    may resolve through globals — the guard in _value_fp must see reads
    from the inner frames too, or a `W*(p-t)` inside a genexpr escapes
    it."""
    names = set(code.co_names)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            names |= _global_name_reads(const)
    return names


def _value_fp(v) -> str:
    """Stable stringification of one Options field value."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return repr(v)
    if isinstance(v, np.ndarray):
        return f"nd:{v.dtype}:{v.shape}:{hashlib.sha1(v.tobytes()).hexdigest()[:16]}"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_value_fp(x) for x in v) + "]"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(sorted(_value_fp(x) for x in v)) + "}"
    if isinstance(v, dict):
        items = sorted(
            ((_value_fp(k), _value_fp(x)) for k, x in v.items()))
        return "{" + ",".join(f"{k}:{x}" for k, x in items) + "}"
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return type(v).__name__ + _value_fp(dataclasses.asdict(v))
    if callable(v):
        code = getattr(v, "__code__", None)
        if code is None:
            # Library callables (jnp.cos, np.abs, math.erf) carry no
            # Python code object but are process-stable by dotted name —
            # the same trust the closure guard below extends to module
            # references. The dotted name must resolve back to THIS
            # object: instance callables of library wrapper classes
            # (np.vectorize(lambda ...)) report the library module but
            # carry per-instance behavior, so they stay opaque.
            mod = getattr(v, "__module__", None) or ""
            qn = getattr(v, "__qualname__", None) or getattr(
                v, "__name__", None)
            if qn and mod.split(".")[0] in ("jax", "numpy", "math"):
                import sys

                target = sys.modules.get(mod)
                for part in qn.split("."):
                    target = getattr(target, part, None)
                if target is v:
                    return f"lib:{mod}.{qn}"
            raise _Unfingerprintable(repr(v))
        # a non-module global read (module-level constant, helper fn)
        # has no process-stable canonical form: it can be rebound
        # between runs without changing the code object, so two
        # behaviorally different callables would collide. Module
        # references (jnp, np, math) are fine — the code digest pins
        # how the names are used.
        g = getattr(v, "__globals__", None)
        if g is not None:
            for name in _global_name_reads(code):
                if name in g and not isinstance(
                        g[name], types.ModuleType):
                    raise _Unfingerprintable(
                        f"{getattr(v, '__qualname__', v)!r} reads "
                        f"global {name!r}")
        # closure cells + defaults (positional AND keyword-only):
        # huber_loss(delta=1.0) and huber_loss(delta=2.0) share co_code
        # but are different losses
        extras = ""
        cells = getattr(v, "__closure__", None)
        if cells:
            extras += ":c" + _value_fp(
                tuple(c.cell_contents for c in cells))
        if getattr(v, "__defaults__", None):
            extras += ":d" + _value_fp(v.__defaults__)
        if getattr(v, "__kwdefaults__", None):
            extras += ":k" + _value_fp(v.__kwdefaults__)
        # a bound method's behavior depends on its receiver's state;
        # an unfingerprintable __self__ makes the config uncacheable
        if getattr(v, "__self__", None) is not None:
            extras += ":s" + _value_fp(v.__self__)
        return (f"fn:{getattr(v, '__qualname__', '?')}:"
                f"{_code_digest(code)}{extras}")
    raise _Unfingerprintable(repr(v))


def options_fingerprint(options: "Options") -> Optional[str]:
    """Canonical digest of everything in an ``Options`` that can affect
    the compiled search programs or the search numerics.

    Two Options instances with equal fingerprints run the identical
    device search; host-only supervision/IO fields
    (:data:`_HOST_ONLY_OPTION_FIELDS`) are excluded, so e.g. a different
    output directory or telemetry cadence still shares a compiled
    engine. Returns None when any load-bearing field has no
    process-stable canonical form (a C callable, an arbitrary object) —
    callers must then treat the config as uncacheable rather than risk
    a silent hyperparameter collision.
    """
    parts = []
    try:
        for name in sorted(vars(options)):
            if name in _HOST_ONLY_OPTION_FIELDS:
                continue
            value = getattr(options, name)
            if name == "operators":
                # fingerprint by (name, arity, fn-code): two same-named
                # custom ops with different bodies must not collide
                value = {
                    d: [(op.name, op.arity, getattr(op, "fn", None))
                        for op in ops]
                    for d, ops in value.ops.items()
                }
            elif name == "expression_spec":
                # the compat header already canonicalizes specs (incl.
                # the template-combiner code fingerprint)
                header = options_compat_header(options)
                value = (header["expression_spec"],
                         header["template_combiner_fp"])
            parts.append(f"{name}={_value_fp(value)}")
    except _Unfingerprintable:
        return None
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


def _code_digest(code) -> str:
    """Process-stable digest of a code object.

    Recurses into nested code objects in co_consts (lambdas, genexprs):
    their repr embeds a memory address, which would make every resume
    look like a changed combiner."""
    h = hashlib.sha1(code.co_code)
    for c in code.co_consts:
        if hasattr(c, "co_code"):
            h.update(_code_digest(c).encode())
        else:
            h.update(repr(c).encode())
    return h.hexdigest()[:16]


_KNOWN_KEY_IMPLS = ("threefry2x32", "rbg", "unsafe_rbg")


def _to_numpy_state(ds):
    """Device state -> picklable numpy pytree (typed PRNG key unwrapped)."""
    ds = dataclasses.replace(ds, key=jax.random.key_data(ds.key))
    return jax.tree.map(np.asarray, jax.device_get(ds))


def _to_device_state(ds, key_impl: str = "threefry2x32"):
    if key_impl not in _KNOWN_KEY_IMPLS:
        raise ValueError(
            f"Checkpoint uses unknown PRNG key impl {key_impl!r}; "
            f"known: {_KNOWN_KEY_IMPLS}"
        )
    return dataclasses.replace(
        ds, key=jax.random.wrap_key_data(
            jax.numpy.asarray(ds.key), impl=key_impl
        )
    )


def _key_impl_name(state: "SearchState") -> str:
    """Record the *actual* key impl so a non-default key (e.g. rbg)
    round-trips instead of being silently reinterpreted on resume."""
    if not state.device_states:
        return "threefry2x32"
    return str(jax.random.key_impl(state.device_states[0].key))


# ---------------------------------------------------------------------------
# Envelope (digest-verified) writing and reading
# ---------------------------------------------------------------------------


def _write_envelope(path: str, payload: dict) -> None:
    """tmp + digest + verify-on-write + atomic replace.

    The tmp file is re-read and its digest checked *before* the replace,
    so a torn write (disk full, crash mid-flush) can never clobber the
    previous good checkpoint with a bad one — the replace only happens
    once the bytes on disk round-trip."""
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(blob).hexdigest()
    envelope = {"format": _ENVELOPE_MAGIC, "sha256": digest, "payload": blob}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".bak"
    with open(tmp, "wb") as f:
        pickle.dump(envelope, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    try:  # verify on write: the readback itself can hit the torn bytes
        with open(tmp, "rb") as f:
            back = pickle.load(f)
        ok = hashlib.sha256(back["payload"]).hexdigest() == digest
    except _UNPICKLE_ERRORS as e:
        raise CheckpointCorruptError(
            f"checkpoint readback of {tmp} failed after write "
            f"({type(e).__name__}: {e}) — torn write or failing disk; "
            "previous checkpoint left intact"
        ) from e
    if not ok:
        raise CheckpointCorruptError(
            f"checkpoint digest mismatch immediately after writing {tmp} "
            "(torn write or failing disk); previous checkpoint left intact"
        )
    os.replace(tmp, path)


# Unpickling a hostile/garbled stream can raise nearly anything; these
# are the ones corrupt-but-honest files actually produce.
_UNPICKLE_ERRORS = (
    pickle.UnpicklingError, EOFError, AttributeError, ImportError,
    IndexError, KeyError, TypeError, ValueError, MemoryError,
    UnicodeDecodeError, OSError,
)


def _read_payload(path: str) -> dict:
    """Read + digest-verify one checkpoint file -> the payload dict.

    Raises CheckpointCorruptError for anything short of a well-formed,
    digest-matching file of a known format version; FileNotFoundError
    passes through untouched (absent != corrupt)."""
    try:
        with open(path, "rb") as f:
            outer = pickle.load(f)
    except FileNotFoundError:
        raise
    except _UNPICKLE_ERRORS as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} is unreadable (truncated or corrupt "
            f"pickle): {type(e).__name__}: {e}"
        ) from e
    if isinstance(outer, dict) and outer.get("format") == _ENVELOPE_MAGIC:
        blob = outer.get("payload")
        if not isinstance(blob, (bytes, bytearray)) or (
            hashlib.sha256(blob).hexdigest() != outer.get("sha256")
        ):
            raise CheckpointCorruptError(
                f"checkpoint {path} failed sha256 digest verification "
                "(bit rot or partial write)"
            )
        try:
            payload = pickle.loads(blob)
        except _UNPICKLE_ERRORS as e:  # digest ok but payload unloadable
            raise CheckpointCorruptError(
                f"checkpoint {path} payload failed to unpickle: "
                f"{type(e).__name__}: {e}"
            ) from e
    else:
        payload = outer  # format v1: bare payload pickle, no digest
    if not isinstance(payload, dict) or (
        payload.get("format_version") not in (1, _FORMAT_VERSION)
    ):
        got = payload.get("format_version") if isinstance(payload, dict) \
            else type(payload).__name__
        raise CheckpointCorruptError(
            f"checkpoint {path} has unsupported format_version {got!r} "
            f"(this build reads 1..{_FORMAT_VERSION})"
        )
    return payload


# ---------------------------------------------------------------------------
# Multi-host addressable-shard serialization
# ---------------------------------------------------------------------------


class _ShardRec:
    """One island-sharded array leaf as seen by one host: the global
    shape/dtype plus this host's (index, data) addressable shards.
    Plain picklable object (slices pickle fine)."""

    def __init__(self, shape, dtype, shards):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.shards = shards  # List[Tuple[index-tuple-of-slices, ndarray]]


def rank_shard_paths(path: str, process_count: Optional[int] = None
                     ) -> List[str]:
    """The per-host shard file names for a base checkpoint path.

    With ``process_count`` None, globs for whatever rank files exist
    (load side); otherwise enumerates the expected set (save side)."""
    if process_count is None:
        found = []
        # glob.escape: an output_directory/run_id containing [ ? * must
        # not be read as a glob pattern (it would hide real rank files)
        for p in glob.glob(glob.escape(path) + ".rank*"):
            # strictly `.rank<int>` — tmp files from a torn write
            # (`.rank2.bak`) or rolled names must NOT count as shards
            try:
                rank = int(p.rsplit(".rank", 1)[1])
            except ValueError:
                continue
            found.append((rank, p))
        # numeric sort, not lexicographic (rank10 after rank9)
        return [p for _, p in sorted(found)]
    return [f"{path}.rank{k}" for k in range(process_count)]


def _to_shard_state(ds):
    """Device state -> picklable pytree where non-fully-addressable
    arrays become _ShardRec (this host's shards only) and everything
    else becomes numpy."""
    ds = dataclasses.replace(ds, key=jax.random.key_data(ds.key))

    def rec(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return _ShardRec(
                x.shape, np.asarray(x.addressable_shards[0].data).dtype,
                [(s.index, np.asarray(s.data))
                 for s in x.addressable_shards],
            )
        return np.asarray(jax.device_get(x))

    return jax.tree.map(rec, ds)


def _reassemble_states(rank_states: List[Any]) -> Any:
    """Merge per-rank shard pytrees (same structure, _ShardRec leaves)
    into one full-numpy pytree. Raises CheckpointCorruptError when the
    rank set does not cover every element of a sharded array (a missing
    or mismatched rank file)."""
    leaves_per_rank = [jax.tree.flatten(
        s, is_leaf=lambda x: isinstance(x, _ShardRec)) for s in rank_states]
    leaves0, treedef = leaves_per_rank[0]
    merged: List[Any] = []
    for i, leaf in enumerate(leaves0):
        if not isinstance(leaf, _ShardRec):
            merged.append(leaf)
            continue
        out = np.empty(leaf.shape, dtype=leaf.dtype)
        seen = np.zeros(leaf.shape, dtype=bool)
        for leaves, _ in leaves_per_rank:
            r = leaves[i]
            if not isinstance(r, _ShardRec) or r.shape != leaf.shape:
                raise CheckpointCorruptError(
                    "multi-host checkpoint rank files disagree on array "
                    f"structure at leaf {i}"
                )
            for index, data in r.shards:
                out[index] = data
                seen[index] = True
        if not seen.all():
            raise CheckpointCorruptError(
                f"multi-host checkpoint is missing shards for leaf {i}: "
                f"only {seen.mean():.0%} of elements covered — a rank "
                "file is absent or was written by a different topology"
            )
        merged.append(out)
    return jax.tree.unflatten(treedef, merged)


# ---------------------------------------------------------------------------
# Public save / load
# ---------------------------------------------------------------------------


def _base_payload(state: "SearchState") -> dict:
    return {
        "format_version": _FORMAT_VERSION,
        "compat": options_compat_header(state.options),
        "num_evals": float(state.num_evals),
        "iterations_done": int(getattr(state, "iterations_done", 0)),
        "key_impl": _key_impl_name(state),
        "nfeatures": state.nfeatures,
    }


def save_search_state(path: str, state: "SearchState") -> None:
    """Serialize a SearchState (the ``return_state=True`` result) to disk.

    Double-write (tmp + digest verify + atomic replace) extending the CSV
    checkpoint discipline (src/SearchUtils.jl:605-649).

    Multi-process runs: EVERY rank must call this with the same ``path``
    on a shared filesystem; rank ``k`` writes ``path.rank{k}`` holding
    its addressable shards of the island-sharded arrays (no cross-host
    collectives, no window where a half-gathered state could deadlock).
    ``load_search_state`` reassembles the full state from the rank set.
    """
    if jax.process_count() > 1:
        shard_payload = dict(_base_payload(state))
        shard_payload.update({
            "multihost": {
                "process_index": int(jax.process_index()),
                "process_count": int(jax.process_count()),
            },
            "device_states": [
                _to_shard_state(ds) for ds in state.device_states
            ],
        })
        _write_envelope(
            f"{path}.rank{jax.process_index()}", shard_payload
        )
        return
    payload = dict(_base_payload(state))
    payload["device_states"] = [
        _to_numpy_state(ds) for ds in state.device_states
    ]
    _write_envelope(path, payload)


def _check_compat(payload: dict, options: "Options", path: str) -> None:
    saved = payload["compat"]
    now = options_compat_header(options)
    issues = [k for k in now
              if k != "template_combiner_fp" and saved.get(k) != now[k]]
    if issues:
        raise ValueError(
            f"Checkpoint incompatible with current options; changed: {issues}"
        )
    if ("template_combiner_fp" in saved
            and saved["template_combiner_fp"] != now.get(
                "template_combiner_fp")):
        warnings.warn(
            "Checkpoint was saved under a template combine function whose "
            "fingerprint differs from the current one; resuming will score "
            "carried-over losses under the new objective.",
            stacklevel=3,
        )


def load_search_state(path: str, options: "Options") -> "SearchState":
    """Load a checkpoint for resumption under ``options``.

    Raises :class:`CheckpointCorruptError` when the file (or any of its
    multi-host rank files) is truncated/corrupt/unknown-format, and
    ValueError when the saved state is incompatible with the given
    options (same contract as the in-memory warm start,
    src/OptionsStruct.jl:314-336). A base path whose ``path.rank{k}``
    files exist loads the multi-host set and reassembles the full state.
    """
    from .search import SearchState

    if not os.path.exists(path):
        rank_files = rank_shard_paths(path)
        if rank_files:
            return _load_multihost(path, rank_files, options)
        raise FileNotFoundError(path)
    payload = _read_payload(path)
    if "multihost" in payload:
        # A rank file passed directly: load the whole set it belongs to.
        base = path.rsplit(".rank", 1)[0]
        return _load_multihost(base, rank_shard_paths(base), options)
    _check_compat(payload, options, path)
    device_states = [
        _to_device_state(ds, payload.get("key_impl", "threefry2x32"))
        for ds in payload["device_states"]
    ]
    return SearchState(
        device_states=device_states,
        hofs=[],  # rebuilt from device state on the first iteration
        options=options,
        num_evals=float(payload["num_evals"]),
        nfeatures=payload.get("nfeatures"),
        iterations_done=int(payload.get("iterations_done", 0)),
    )


def _load_multihost(base: str, rank_files: List[str], options: "Options"
                    ) -> "SearchState":
    from .search import SearchState

    if not rank_files:
        raise FileNotFoundError(base)
    payloads = [_read_payload(p) for p in rank_files]
    counts = {p["multihost"]["process_count"] for p in payloads}
    if len(counts) != 1 or counts.pop() != len(payloads):
        raise CheckpointCorruptError(
            f"multi-host checkpoint {base} has {len(payloads)} rank "
            f"file(s) but they declare process_count "
            f"{sorted(p['multihost']['process_count'] for p in payloads)}"
        )
    # Same GENERATION on every rank: a host that died (or was signaled)
    # at a different iteration than the others leaves shard files from
    # different states — reassembling them would hand resume a chimera
    # population with no error. iterations_done + num_evals pin it.
    gens = {
        (int(p.get("iterations_done", 0)), float(p["num_evals"]))
        for p in payloads
    }
    if len(gens) != 1:
        raise CheckpointCorruptError(
            f"multi-host checkpoint {base} mixes generations: rank files "
            f"disagree on (iterations_done, num_evals): {sorted(gens)} — "
            "fall back to an older rolling generation"
        )
    head = payloads[0]
    _check_compat(head, options, base)
    n_out = len(head["device_states"])
    device_states = []
    for j in range(n_out):
        merged = _reassemble_states(
            [p["device_states"][j] for p in payloads]
        )
        device_states.append(
            _to_device_state(merged, head.get("key_impl", "threefry2x32"))
        )
    return SearchState(
        device_states=device_states,
        hofs=[],
        options=options,
        num_evals=float(head["num_evals"]),
        nfeatures=head.get("nfeatures"),
        iterations_done=int(head.get("iterations_done", 0)),
    )
