"""Host-side hall of fame: pareto frontier, scores, formatting, CSV IO.

TPU analogue of /root/reference/src/HallOfFame.jl. The device-resident
`HofState` (best member per complexity level, evolve/step.py) is decoded
into host `Node` trees here for reporting, selection, and persistence —
these paths never sit in the generation hot loop.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..ops.encoding import decode_tree
from ..ops.operators import OperatorSet
from ..ops.tree import Node, parse_expression, string_tree

__all__ = [
    "HallOfFameEntry",
    "HallOfFame",
    "calculate_pareto_frontier",
    "compute_scores",
    "string_dominating_pareto_curve",
    "save_hall_of_fame_csv",
    "load_hall_of_fame_csv",
]


@dataclasses.dataclass
class HallOfFameEntry:
    """One best-at-complexity member (PopMember analogue on host)."""

    tree: Optional[Node]
    loss: float
    cost: float
    complexity: int
    score: float = 0.0
    # (n_params, n_classes) parameter matrix for parametric expressions
    # (/root/reference/src/ParametricExpression.jl:35-51), else None.
    params: Optional[np.ndarray] = None
    # Template members decode to a HostTemplateExpression (named subtrees
    # + parameter vectors); ``tree`` is None for those.
    template_expr: Optional["object"] = None

    def equation_string(self, variable_names=None, precision: int = 5) -> str:
        if self.template_expr is not None:
            # variable_names don't apply: template subexpressions print
            # their argument slots as #1..#k by definition.
            return self.template_expr.string(precision=precision)
        return string_tree(
            self.tree, variable_names=variable_names, precision=precision
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"HallOfFameEntry(complexity={self.complexity}, "
            f"loss={self.loss:.6g})"
        )


@dataclasses.dataclass
class HallOfFame:
    """Best member per complexity level (src/HallOfFame.jl:26-29)."""

    entries: List[HallOfFameEntry]

    @staticmethod
    def from_device(hof_state, operators: OperatorSet,
                    template=None) -> "HallOfFame":
        """Decode a device HofState into host entries (existing only).

        With ``template`` (a TemplateStructure), tree tensors carry a key
        axis [maxsize, K, L]; each entry becomes a
        HostTemplateExpression of named subtrees + parameter values.
        """
        exists = np.asarray(hof_state.exists)
        cost = np.asarray(hof_state.cost)
        loss = np.asarray(hof_state.loss)
        complexity = np.asarray(hof_state.complexity)
        arity = np.asarray(hof_state.trees.arity)
        op = np.asarray(hof_state.trees.op)
        feat = np.asarray(hof_state.trees.feat)
        const = np.asarray(hof_state.trees.const)
        length = np.asarray(hof_state.trees.length)
        params = np.asarray(hof_state.params)
        parametric = params.shape[-2] > 0 and template is None
        entries = []
        for i in range(exists.shape[0]):
            if not exists[i]:
                continue
            if template is not None:
                from ..models.template import HostTemplateExpression

                trees = {
                    key: decode_tree(
                        arity[i, k], op[i, k], feat[i, k], const[i, k],
                        length[i, k], operators,
                    )
                    for k, key in enumerate(template.expr_keys)
                }
                entries.append(
                    HallOfFameEntry(
                        tree=None,
                        loss=float(loss[i]),
                        cost=float(cost[i]),
                        complexity=int(complexity[i]),
                        template_expr=HostTemplateExpression(
                            trees=trees, structure=template,
                            operators=operators,
                            params=(params[i, :, 0]
                                    if params.shape[-2] > 0 else None),
                        ),
                    )
                )
                continue
            tree = decode_tree(
                arity[i], op[i], feat[i], const[i], length[i], operators
            )
            entries.append(
                HallOfFameEntry(
                    tree=tree,
                    loss=float(loss[i]),
                    cost=float(cost[i]),
                    complexity=int(complexity[i]),
                    params=params[i] if parametric else None,
                )
            )
        entries.sort(key=lambda e: e.complexity)
        return HallOfFame(entries=entries)

    def pareto_frontier(self) -> List[HallOfFameEntry]:
        return calculate_pareto_frontier(self.entries)


def calculate_pareto_frontier(
    entries: Sequence[HallOfFameEntry],
) -> List[HallOfFameEntry]:
    """Members whose loss beats every simpler member
    (src/HallOfFame.jl:96-124: dominating iff loss < all lower-complexity
    losses)."""
    frontier: List[HallOfFameEntry] = []
    best = np.inf
    for e in sorted(entries, key=lambda e: e.complexity):
        if np.isfinite(e.loss) and e.loss < best:
            frontier.append(e)
            best = e.loss
    return frontier


def compute_scores(
    frontier: Sequence[HallOfFameEntry], loss_scale: str = "log"
) -> List[HallOfFameEntry]:
    """Attach score = -Δlog(loss)/Δcomplexity (log scale) or the direct
    negative slope (linear scale), vs. the previous frontier member
    (format_hall_of_fame, src/HallOfFame.jl:217-266)."""
    ZERO_POINT = 1e-12
    out = []
    prev_loss = None
    prev_c = None
    for e in frontier:
        if prev_loss is None:
            score = 0.0
        else:
            dc = max(e.complexity - prev_c, 1)
            if loss_scale == "log":
                cur = max(e.loss, ZERO_POINT)
                prev = max(prev_loss, ZERO_POINT)
                score = -(np.log(cur) - np.log(prev)) / dc
            else:
                score = -(e.loss - prev_loss) / dc
        out.append(dataclasses.replace(e, score=float(score)))
        prev_loss, prev_c = e.loss, e.complexity
    return out


def string_dominating_pareto_curve(
    hof: HallOfFame,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
    loss_scale: str = "log",
    precision: int = 5,
    width: int = 100,
) -> str:
    """Terminal table of the dominating pareto frontier
    (src/HallOfFame.jl:138-215)."""
    frontier = compute_scores(hof.pareto_frontier(), loss_scale)
    sep = "─" * width
    lines = ["┌" + sep + "┐"]
    header = f"{'Complexity':<12}{'Loss':<12}{'Score':<12}Equation"
    lines.append("│ " + header.ljust(width - 2) + " │")
    for e in frontier:
        eq = e.equation_string(variable_names=variable_names, precision=precision)
        row = (
            f"{e.complexity:<12d}{e.loss:<12.4g}{e.score:<12.4g}{eq}"
        )
        # wrap long equations
        while len(row) > width - 4:
            lines.append("│ " + row[: width - 4].ljust(width - 2) + " │")
            row = " " * 36 + row[width - 4 :]
        lines.append("│ " + row.ljust(width - 2) + " │")
    lines.append("└" + sep + "┘")
    return "\n".join(lines)


def save_hall_of_fame_csv(
    path: str,
    hof: HallOfFame,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
    precision: int = 12,
) -> None:
    """Write `Complexity,Loss,Equation` CSV with `.bak` double-write
    (save_to_file, src/SearchUtils.jl:605-649): write the backup first,
    then atomically move it over the target so a crash mid-write never
    corrupts the existing file.

    Parametric entries get an extra `Parameters` column holding the
    fitted (n_params x n_classes) bank as a flat ;-separated list, so the
    CSV warm-start path can restore learned parameters instead of
    reseeding them randomly."""
    parametric = any(e.params is not None for e in hof.entries)
    header = "Complexity,Loss,Equation"
    rows = [header + ",Parameters" if parametric else header]
    for e in hof.entries:
        eq = e.equation_string(variable_names=variable_names, precision=precision)
        row = f'{e.complexity},{e.loss!r},"{eq}"'
        if parametric:
            p = (
                ";".join(repr(float(v)) for v in np.asarray(e.params).ravel())
                if e.params is not None
                else ""
            )
            row += f',"{p}"'
        rows.append(row)
    body = "\n".join(rows) + "\n"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    bak = path + ".bak"
    with open(bak, "w") as f:
        f.write(body)
    os.replace(bak, path)


def load_hall_of_fame_csv(
    path: str,
    operators: OperatorSet,
    variable_names: Optional[Sequence[str]] = None,
    return_params: bool = False,
):
    """Parse a saved hall-of-fame CSV back into trees (warm start path,
    load_saved_hall_of_fame, src/SearchUtils.jl:532-545).

    ``return_params=True`` additionally returns the per-entry flat
    parameter vectors from the `Parameters` column (None where absent),
    so parametric warm starts restore fitted values."""
    import csv as _csv

    trees: List[Node] = []
    params: List[Optional[np.ndarray]] = []
    with open(path) as f:
        reader = _csv.reader(f)
        header = next(reader, None)
        if header is None or not header[0].startswith("Complexity"):
            raise ValueError(f"Not a hall-of-fame CSV: {path}")
        has_params = len(header) > 3 and header[3] == "Parameters"
        for parts in reader:
            if not parts:
                continue
            eq = parts[2].strip()
            trees.append(
                parse_expression(eq, operators, variable_names=variable_names)
            )
            if has_params and len(parts) > 3 and parts[3]:
                params.append(
                    np.asarray([float(v) for v in parts[3].split(";")])
                )
            else:
                params.append(None)
    if return_params:
        return trees, params
    return trees
