"""Island quarantine: detect and reseed NaN-storm-collapsed islands.

An island whose members are all non-finite is blind: tournament
selection cannot rank candidates, every mutation child of a NaN-constant
parent is NaN, and the island burns its share of every eval launch for
the rest of the run producing nothing (graftscope shows it as a
saturated invalid-candidate fraction and an emptying loss histogram).
The quarantine reseeds such islands from the hall of fame — entirely
in-graph (``Engine.reseed_islands``) — and the search keeps going.

Detection is host-side: one tiny jitted reduction
(``Engine.island_invalid_fractions`` → an [I] float vector) per check,
pulled explicitly. That is the only traffic the feature adds, it rides
the per-iteration sync the loop already performs, and it never runs
inside the hot jitted iteration itself — the warm-iteration guarantees
(0 retraces / 0 implicit transfers, tests/test_hot_loop_guards.py) are
untouched.

The default threshold is 1.0 — only a *fully* collapsed island
quarantines, so healthy searches (where early random populations
legitimately carry some non-finite members) are bit-identical with the
feature on or off until a genuine storm hits.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["IslandQuarantine"]


class IslandQuarantine:
    """Per-output quarantine policy driver for the search loop."""

    def __init__(self, threshold: float = 1.0, telemetry=None) -> None:
        self.threshold = float(threshold)
        self.telemetry = telemetry
        self.reseeds_total = 0

    def check_and_reseed(self, engine, state, *, iteration: int = 0,
                         output: int = 1):
        """Returns (possibly reseeded) state. Cheap when healthy: one
        [I]-vector pull; the in-graph reseed only dispatches when at
        least one island crossed the threshold AND the hall of fame has
        at least one entry to reseed from."""
        import jax

        fracs = np.asarray(
            jax.device_get(engine.island_invalid_fractions(state))
        )
        mask = fracs >= self.threshold
        if not mask.any():
            return state
        if not bool(np.asarray(jax.device_get(state.hof.exists)).any()):
            # Nothing to reseed from yet (a storm before the first HoF
            # entry): leave the island alone; evolution's randomize
            # mutations are the only way out.
            return state
        self.reseeds_total += int(mask.sum())
        if self.telemetry is not None:
            self.telemetry.fault(
                "quarantine", iteration=iteration, output=output,
                islands=[int(i) for i in np.nonzero(mask)[0]],
                invalid_fractions=[round(float(f), 4) for f in fracs],
            )
        import jax.numpy as jnp

        return engine.reseed_islands(state, jnp.asarray(mask))
