"""Deterministic fault injection — the harness the recovery tests drive.

Every graftshield recovery path is pinned by injecting the fault it
exists for, at an exact, reproducible point in the search:

- ``raise_on_dispatch=n`` — the n-th supervised device dispatch raises
  :class:`InjectedFault` (message carries ``RESOURCE_EXHAUSTED`` or any
  marker you choose, so the transient classifier and the degradation
  ladder take their production paths). ``raise_count`` consecutive
  dispatches fail, then the fault clears — retries succeed.
- ``sigterm_at_iteration=k`` — delivers a real SIGTERM to this process
  at the end of iteration k (the PreemptionGuard path, end to end).
- ``nan_poison_island=(i, k)`` — at the end of iteration k, island i's
  constants/costs/losses are overwritten with NaN in-graph: a genuine
  NaN storm (subsequent re-evals of the poisoned genomes stay NaN),
  which the quarantine must detect and reseed.
- checkpoint corruption helpers (:func:`truncate_file`,
  :func:`flip_byte`) — applied to written checkpoints by tests to pin
  the digest-verification + rolling-fallback machinery.

Injection is process-local: tests call :func:`install`; headless smoke
runs set ``SR_FAULT_PLAN`` to the plan as JSON. The search loop polls
:func:`active_injector` once per search. No injector, no overhead.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Optional, Tuple

__all__ = [
    "FaultPlan",
    "FaultInjector",
    "InjectedFault",
    "ServeFaultPlan",
    "ServeFaultInjector",
    "install",
    "clear",
    "active_injector",
    "install_serve",
    "clear_serve",
    "active_serve_injector",
    "truncate_file",
    "flip_byte",
]


class InjectedFault(RuntimeError):
    """An injected device failure. The *message* is the classification
    surface (shield/degrade.py matches status markers in text, same as
    for real jaxlib XlaRuntimeErrors)."""


@dataclasses.dataclass
class FaultPlan:
    """A deterministic schedule of faults for one search."""

    # n-th supervised dispatch (1-based, counted across outputs) raises.
    raise_on_dispatch: Optional[int] = None
    raise_count: int = 1
    raise_message: str = "RESOURCE_EXHAUSTED: injected device OOM"
    # Real SIGTERM to this process at the end of iteration k (1-based).
    sigterm_at_iteration: Optional[int] = None
    # (island, iteration): poison island i at the end of iteration k.
    nan_poison_island: Optional[Tuple[int, int]] = None
    # (dispatch, seconds): the n-th supervised dispatch blocks for that
    # long — a deterministic stand-in for a hung device dispatch, the
    # failure mode the shield watchdog exists for (the sleep happens
    # INSIDE the supervised phase, so an armed deadline fires).
    hang_on_dispatch: Optional[Tuple[int, float]] = None

    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        d = json.loads(text)
        for name in ("nan_poison_island", "hang_on_dispatch"):
            if d.get(name) is not None:
                d[name] = tuple(d[name])
        return FaultPlan(**d)


class FaultInjector:
    """Stateful executor of a :class:`FaultPlan` for one search run."""

    def __init__(self, plan: FaultPlan, telemetry=None) -> None:
        self.plan = plan
        self.telemetry = telemetry
        self.dispatches = 0
        self.injected = []  # audit trail of (kind, detail) tuples

    def _record(self, kind: str, iteration: int, **detail) -> None:
        self.injected.append((kind, detail))
        if self.telemetry is not None:
            self.telemetry.fault(
                "injected", iteration=iteration, fault=kind, **detail
            )

    # -- hook: immediately before each supervised device dispatch -------
    def on_dispatch(self, iteration: int) -> None:
        self.dispatches += 1
        p = self.plan
        if p.hang_on_dispatch is not None:
            at, seconds = p.hang_on_dispatch
            if self.dispatches == at:
                self._record("hang_on_dispatch", iteration,
                             dispatch=self.dispatches,
                             seconds=float(seconds))
                time.sleep(float(seconds))
        if p.raise_on_dispatch is None:
            return
        first = p.raise_on_dispatch
        if first <= self.dispatches < first + p.raise_count:
            self._record("raise_on_dispatch", iteration,
                         dispatch=self.dispatches)
            raise InjectedFault(p.raise_message)

    # -- hook: after iteration k's device work landed -------------------
    def on_iteration_end(self, iteration: int, states: list) -> list:
        p = self.plan
        if p.nan_poison_island is not None:
            island, at_it = p.nan_poison_island
            if iteration == at_it:
                self._record("nan_poison_island", iteration, island=island)
                states = [poison_island(s, island) for s in states]
        if p.sigterm_at_iteration == iteration:
            self._record("sigterm", iteration)
            os.kill(os.getpid(), signal.SIGTERM)
        return states


def poison_island(state, island: int):
    """A genuine in-graph NaN storm on one island: constants, costs, and
    losses all go NaN, so even a full-dataset re-eval of the poisoned
    genomes stays non-finite (what a real numerical collapse looks like
    from the host)."""
    import dataclasses as dc

    import jax.numpy as jnp

    pops = state.pops
    nan = jnp.asarray(float("nan"), pops.trees.const.dtype)
    trees = dc.replace(
        pops.trees, const=pops.trees.const.at[island].set(nan)
    )
    pops = dc.replace(
        pops,
        trees=trees,
        cost=pops.cost.at[island].set(jnp.asarray(float("nan"),
                                                  pops.cost.dtype)),
        loss=pops.loss.at[island].set(jnp.asarray(float("nan"),
                                                  pops.loss.dtype)),
    )
    return dc.replace(state, pops=pops)


# ---------------------------------------------------------------------------
# Service-level faults (graftserve, docs/SERVING.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ServeFaultPlan:
    """A deterministic schedule of *service-level* faults for one
    graftserve process — the request-fleet analogue of
    :class:`FaultPlan` (which schedules faults inside one search).

    Driven by the serve smoke (tools/serve_smoke.py) and the serve test
    suite; headless runs set ``SR_SERVE_FAULT_PLAN`` to the plan as
    JSON, exactly like ``SR_FAULT_PLAN``.
    """

    # Deliver kill_signal to this process when the k-th accepted
    # request STARTS running (1-based) — the kill-restart-replay
    # scenario: the journal + per-request shield checkpoints must make
    # a restarted server finish every accepted request bit-identically.
    kill_server_at_request: Optional[int] = None
    kill_signal: str = "SIGTERM"
    # Flip one byte inside the n-th appended journal record (1-based),
    # right after it is written — pins the per-record sha256
    # verification + skip-and-audit replay path.
    corrupt_journal_record: Optional[int] = None
    # (k-th accepted request 1-based, iteration): cancel that request
    # while its search is mid-flight, honored at the next iteration
    # boundary — the cancel-mid-iteration scenario.
    cancel_request_at_iteration: Optional[Tuple[int, int]] = None
    # Smoke-driver knob: number of extra storm submissions thrown at a
    # saturated queue to pin the structured-reject path (consumed by
    # tools/serve_smoke.py, not by the injector hooks).
    queue_overflow_storm: Optional[int] = None

    @staticmethod
    def from_json(text: str) -> "ServeFaultPlan":
        d = json.loads(text)
        if d.get("cancel_request_at_iteration") is not None:
            d["cancel_request_at_iteration"] = tuple(
                d["cancel_request_at_iteration"])
        return ServeFaultPlan(**d)


class ServeFaultInjector:
    """Stateful executor of a :class:`ServeFaultPlan` for one server."""

    def __init__(self, plan: ServeFaultPlan, telemetry=None) -> None:
        self.plan = plan
        self.telemetry = telemetry
        self.journal_records = 0
        self.injected = []  # audit trail of (kind, detail) tuples

    def _record(self, kind: str, **detail) -> None:
        self.injected.append((kind, detail))
        if self.telemetry is not None:
            try:
                d = dict(detail)
                # pop: request_id is serve()'s positional arg — passing
                # it again via ** would TypeError and lose the audit
                rid = d.pop("request_id", "")
                self.telemetry.serve("injected", rid, fault=kind, **d)
            except Exception:  # pragma: no cover - audit is best-effort
                pass

    # -- hook: a request transitioned queued -> running -----------------
    def on_request_start(self, index: int, request_id: str) -> None:
        p = self.plan
        if p.kill_server_at_request is not None and (
                index == p.kill_server_at_request):
            self._record("kill_server", request_id=request_id, index=index,
                         signal=p.kill_signal)
            os.kill(os.getpid(), getattr(signal, p.kill_signal))

    # -- hook: one record was appended to the request journal -----------
    def on_journal_append(self, path: str, record_index: int,
                          offset: int, length: int) -> None:
        p = self.plan
        self.journal_records = record_index
        if p.corrupt_journal_record is not None and (
                record_index == p.corrupt_journal_record):
            self._record("corrupt_journal", record=record_index, path=path)
            # flip a byte in the middle of the record's payload (past
            # the opening brace, before the trailing newline)
            flip_byte(path, offset + max(length // 2, 1))

    # -- hook: per-iteration probe of a running request's search --------
    def should_cancel(self, index: int, iteration: int,
                      request_id: str = "") -> bool:
        p = self.plan
        if p.cancel_request_at_iteration is None:
            return False
        k, at_it = p.cancel_request_at_iteration
        if index == k and iteration >= at_it:
            self._record("cancel_request", request_id=request_id,
                         index=index, iteration=iteration)
            return True
        return False


# ---------------------------------------------------------------------------
# Checkpoint corruption helpers (tests + fault smoke)
# ---------------------------------------------------------------------------


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    size = os.path.getsize(path)
    with open(path, "rb+") as f:
        f.truncate(max(int(size * keep_fraction), 0))


def flip_byte(path: str, offset: int = -64) -> None:
    """XOR one byte (negative offsets index from the end, where the
    payload bytes — not the envelope header — live)."""
    size = os.path.getsize(path)
    pos = offset % size
    with open(path, "rb+") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# Process-local installation
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None
_ACTIVE_SERVE: Optional[ServeFaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = injector
    return injector


def clear() -> None:
    global _ACTIVE
    _ACTIVE = None


def install_serve(injector: ServeFaultInjector) -> ServeFaultInjector:
    global _ACTIVE_SERVE
    _ACTIVE_SERVE = injector
    return injector


def clear_serve() -> None:
    global _ACTIVE_SERVE
    _ACTIVE_SERVE = None


def active_serve_injector(telemetry=None) -> Optional[ServeFaultInjector]:
    """The serve injector the current server should consult: an
    installed one, else one built from ``SR_SERVE_FAULT_PLAN`` (JSON)
    if set, else None."""
    if _ACTIVE_SERVE is not None:
        if telemetry is not None and _ACTIVE_SERVE.telemetry is None:
            _ACTIVE_SERVE.telemetry = telemetry
        return _ACTIVE_SERVE
    env = os.environ.get("SR_SERVE_FAULT_PLAN")
    if env:
        return ServeFaultInjector(
            ServeFaultPlan.from_json(env), telemetry=telemetry)
    return None


def active_injector(telemetry=None) -> Optional[FaultInjector]:
    """The injector the current search should consult: an installed one,
    else one built from ``SR_FAULT_PLAN`` (JSON) if set, else None."""
    if _ACTIVE is not None:
        if telemetry is not None and _ACTIVE.telemetry is None:
            _ACTIVE.telemetry = telemetry
        return _ACTIVE
    env = os.environ.get("SR_FAULT_PLAN")
    if env:
        return FaultInjector(FaultPlan.from_json(env), telemetry=telemetry)
    return None
