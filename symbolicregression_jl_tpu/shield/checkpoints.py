"""Rolling, discoverable, corruption-tolerant checkpoint management.

``api/checkpoint.py`` owns the on-disk format (digest-verified envelope,
multi-host rank shards); this module owns the *policy* around it:

- :class:`RollingCheckpointer` keeps the last K checkpoints
  (``search_state.pkl``, ``.1``, ``.2``, ...), rotating before each
  write so a torn write or a corrupt newest file never strands the run
  — and rotates the multi-host ``.rank{k}`` files as a set.
- :func:`load_newest_valid` walks a candidate list newest-first,
  skipping (with a warning) files that raise
  :class:`~..api.checkpoint.CheckpointCorruptError`.
- :func:`discover_resume_path` implements ``equation_search(resume="auto")``:
  find the newest run directory under the output base that contains a
  checkpoint set.
"""

from __future__ import annotations

import os
import warnings
from typing import List, Optional, Tuple

from ..api.checkpoint import (
    CheckpointCorruptError,
    load_search_state,
    rank_shard_paths,
    save_search_state,
)

__all__ = [
    "RollingCheckpointer",
    "rolled_paths",
    "load_newest_valid",
    "discover_resume_path",
]

CHECKPOINT_BASENAME = "search_state.pkl"


def rolled_paths(base: str, keep: int) -> List[str]:
    """Newest-first candidate paths for a rolling set of size ``keep``."""
    return [base] + [f"{base}.{n}" for n in range(1, keep)]


def _files_for(path: str) -> List[str]:
    """All on-disk files belonging to one checkpoint slot: the base file
    (single-host) and/or its rank shards (multi-host)."""
    out = [path] if os.path.exists(path) else []
    out.extend(rank_shard_paths(path))
    return out


class RollingCheckpointer:
    """Writes ``base`` and keeps the previous ``keep - 1`` generations.

    Rotation happens *before* the new write: ``base.{K-2}`` →
    ``base.{K-1}`` → ... → ``base`` is about to be replaced, so its old
    content moves to ``base.1`` first. If the process dies mid-write,
    ``base.1`` is still the complete previous state and
    :func:`load_newest_valid` falls back to it.
    """

    def __init__(self, base: str, keep: int = 3) -> None:
        self.base = base
        self.keep = max(int(keep), 1)

    def _own_files(self, path: str):
        """The slot files THIS process owns. Multi-host: only this
        rank's shard file — every rank runs the same rotation on a
        shared filesystem, and racing os.replace on other ranks' files
        would corrupt the set."""
        import jax

        if jax.process_count() > 1:
            f = f"{path}.rank{jax.process_index()}"
            return [f] if os.path.exists(f) else []
        return _files_for(path)

    def _rotate(self) -> None:
        if self.keep == 1:
            return
        slots = rolled_paths(self.base, self.keep)
        # drop the oldest generation's files, then shift each slot up
        for f in self._own_files(slots[-1]):
            try:
                os.remove(f)
            except OSError:  # pragma: no cover - racing cleanup
                pass
        for n in range(self.keep - 2, -1, -1):
            src, dst = slots[n], slots[n + 1]
            for f in self._own_files(src):
                suffix = f[len(src):]  # "" or ".rank{k}"
                try:
                    os.replace(f, dst + suffix)
                except OSError:  # pragma: no cover - racing cleanup
                    pass

    def save(self, state) -> str:
        self._rotate()
        save_search_state(self.base, state)
        return self.base

    def candidates(self) -> List[str]:
        """Newest-first checkpoint slots that exist on disk."""
        return [
            p for p in rolled_paths(self.base, self.keep) if _files_for(p)
        ]


def load_newest_valid(paths: List[str], options,
                      corrupt_log: Optional[List[Tuple[str, str]]] = None,
                      ) -> Tuple[object, str]:
    """Load the first checkpoint in ``paths`` (newest-first) that
    survives digest verification and unpickling; corrupt candidates are
    skipped with a warning and — when ``corrupt_log`` is passed —
    recorded as ``(path, error)`` entries (the search loop turns those
    into ``checkpoint_corrupt`` fault events; nothing else that happens
    to warn during unpickling gets misreported). Raises the last
    :class:`CheckpointCorruptError` when every candidate is bad, and
    FileNotFoundError when the list is empty/absent."""
    last_error: Optional[Exception] = None
    tried = 0
    for p in paths:
        if not _files_for(p):
            continue
        tried += 1
        try:
            return load_search_state(p, options), p
        except CheckpointCorruptError as e:
            last_error = e
            if corrupt_log is not None:
                corrupt_log.append((p, str(e)))
            warnings.warn(
                f"checkpoint {p} is corrupt ({e}); falling back to the "
                "previous rolling checkpoint",
                stacklevel=2,
            )
    if tried == 0:
        raise FileNotFoundError(
            f"no checkpoint found among candidates: {paths}"
        )
    raise CheckpointCorruptError(
        f"all {tried} checkpoint candidate(s) are corrupt; last error: "
        f"{last_error}"
    )


def discover_resume_path(base_dir: str, keep: int = 8
                         ) -> Optional[List[str]]:
    """``resume="auto"`` discovery: newest-first checkpoint candidates
    under ``base_dir``.

    ``base_dir`` may be a run directory itself (contains
    ``search_state.pkl`` / rank shards), or an output base whose run
    subdirectories are scanned newest-mtime-first. Returns the candidate
    path list for :func:`load_newest_valid`, or None when nothing
    checkpoint-like exists."""
    if not os.path.isdir(base_dir):
        if _files_for(base_dir):  # a checkpoint file path directly
            return rolled_paths(base_dir, keep)
        return None

    def run_candidates(d: str) -> List[str]:
        base = os.path.join(d, CHECKPOINT_BASENAME)
        return [p for p in rolled_paths(base, keep) if _files_for(p)]

    direct = run_candidates(base_dir)
    if direct:
        return direct
    runs = []
    try:
        entries = os.listdir(base_dir)
    except OSError:
        return None
    for name in entries:
        d = os.path.join(base_dir, name)
        if not os.path.isdir(d):
            continue
        cands = run_candidates(d)
        if cands:
            runs.append((os.path.getmtime(cands[0]), cands))
    if not runs:
        return None
    runs.sort(key=lambda t: -t[0])
    return runs[0][1]
