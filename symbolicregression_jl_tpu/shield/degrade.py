"""Graceful degradation: transient-failure retries + eval-shape step-down.

Transient device failures — ``RESOURCE_EXHAUSTED`` under memory
pressure, compile-cache deserialization glitches, a preempted collective
— should cost a bounded retry, not the whole search. The
:class:`ShieldRunner` wraps each ``Engine.run_iteration`` call:

1. transient failures retry with exponential backoff (base
   ``Options(retry_backoff)``, doubling, capped) up to
   ``Options(max_retries)`` times;
2. when retries exhaust on an OOM-shaped failure, the eval tile rows
   step down (``Engine.degrade_eval_tile_rows`` halves
   ``cfg.eval_tile_rows`` and drops the compiled programs so the next
   call re-lowers at the smaller launch geometry), the retry budget
   resets, and the iteration re-runs;
3. anything non-transient — or a run out of degradation headroom —
   re-raises.

Every retry/degrade emits a ``fault`` record into the graftscope stream
so the recovery is auditable. Failure classification is by message
substring: jaxlib's ``XlaRuntimeError`` carries the gRPC-style status
name in its text, and the fault-injection harness raises exceptions with
the same markers, so tests and production take the same path.

Caveat (documented, not hidden): the single-launch iteration donates the
input state buffers, so a failure that occurs *after* the runtime
consumed them can poison the retry. In that case the retry itself fails
with a buffer-deleted error, which is non-transient and surfaces
immediately — recovery is then ``resume="auto"`` from the last rolling
checkpoint, which is exactly what the shield keeps fresh.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

__all__ = [
    "ShieldRunner",
    "OverloadLadder",
    "is_transient_failure",
    "TRANSIENT_MARKERS",
]

# Substrings (case-sensitive, matching XLA/gRPC status spellings) that
# mark a failure as worth retrying. Buffer-deleted / donation errors are
# deliberately NOT here: retrying them can only fail again.
#
# OOM spellings vary by allocator layer: the gRPC status name
# ("RESOURCE_EXHAUSTED") appears in distributed-runtime errors, but
# jaxlib's XlaRuntimeError from a local BFC-allocator failure reads
# "Resource exhausted: Out of memory while trying to allocate N bytes",
# and the TPU runtime emits "Failed to allocate request for ...". All
# of them must classify as transient AND as OOM-shaped, or the degrade
# ladder never gets a chance (ShieldRunner re-raises non-transient
# failures immediately) — every _OOM_MARKERS entry therefore also
# appears here.
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Resource exhausted",
    "Out of memory",
    "out of memory",
    "Failed to allocate",
    "UNAVAILABLE",
    "ABORTED",
    "DEADLINE_EXCEEDED",
    "Failed to deserialize",   # persistent compile-cache glitch
    "compilation cache",
)

_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Resource exhausted",
    "Out of memory",
    "out of memory",
    "Failed to allocate",
)


def is_transient_failure(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in TRANSIENT_MARKERS)


def _is_oom(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


class OverloadLadder:
    """Load-shedding ladder for the multi-tenant serve layer
    (docs/SERVING.md): degrade admitted work before refusing it.

    Given the queue utilization at admission time (``depth/capacity``),
    the ladder returns one of four levels and the concrete shed to
    apply — the same degrade-don't-die philosophy as the eval-tile
    step-down above, applied at the request level:

    - ``normal``      (< shed_sample_at): admit untouched;
    - ``shed_sample`` (>= shed_sample_at): admit, but row-sample the
      request's dataset down to ``sample_fraction`` (never below
      ``min_sample_rows``) — smaller evals, faster drain. The shed is
      recorded on the accepted request (journaled), so a replay after a
      crash re-runs the identical degraded search;
    - ``shed_priority`` (>= shed_priority_at): additionally demote the
      request's queue priority so interactive work admitted earlier
      drains first;
    - ``reject`` (>= reject_at): refuse with a structured backpressure
      error (serve/admission.py) carrying a retry-after hint.

    Every non-normal decision emits a ``fault`` audit record
    (``overload_shed`` / ``overload_reject``) when a telemetry hub is
    attached.
    """

    LEVELS = ("normal", "shed_sample", "shed_priority", "reject")

    def __init__(
        self,
        *,
        shed_sample_at: float = 0.5,
        shed_priority_at: float = 0.75,
        reject_at: float = 1.0,
        sample_fraction: float = 0.5,
        min_sample_rows: int = 64,
        telemetry=None,
    ) -> None:
        if not (0.0 < shed_sample_at <= shed_priority_at <= reject_at):
            raise ValueError(
                "ladder thresholds must satisfy "
                "0 < shed_sample_at <= shed_priority_at <= reject_at"
            )
        if not (0.0 < sample_fraction <= 1.0):
            raise ValueError("sample_fraction must be in (0, 1]")
        self.shed_sample_at = float(shed_sample_at)
        self.shed_priority_at = float(shed_priority_at)
        self.reject_at = float(reject_at)
        self.sample_fraction = float(sample_fraction)
        self.min_sample_rows = int(min_sample_rows)
        self.telemetry = telemetry
        self.sheds_total = 0
        self.rejects_total = 0

    def level(self, utilization: float) -> str:
        u = float(utilization)
        if u >= self.reject_at:
            return "reject"
        if u >= self.shed_priority_at:
            return "shed_priority"
        if u >= self.shed_sample_at:
            return "shed_sample"
        return "normal"

    def apply(self, utilization: float, *, n_rows: int, priority: int,
              request_id: str = "") -> dict:
        """Admission-time decision for one request: returns
        ``{"level", "admit", "sample_rows", "priority"}`` where
        ``sample_rows`` is None (no shed) or the reduced row count."""
        lvl = self.level(utilization)
        out = {"level": lvl, "admit": lvl != "reject",
               "sample_rows": None, "priority": int(priority)}
        if lvl == "reject":
            self.rejects_total += 1
            self._fault("overload_reject", request_id,
                        utilization=utilization)
            return out
        if lvl in ("shed_sample", "shed_priority"):
            shed = max(int(n_rows * self.sample_fraction),
                       min(self.min_sample_rows, int(n_rows)))
            if shed < int(n_rows):
                out["sample_rows"] = shed
            if lvl == "shed_priority":
                out["priority"] = int(priority) + 1
            # audit only a shed that actually changed the request — a
            # tiny dataset already at min_sample_rows is admitted
            # untouched and must not inflate the degradation counters
            if (out["sample_rows"] is not None
                    or out["priority"] != int(priority)):
                self.sheds_total += 1
                self._fault(
                    "overload_shed", request_id, level=lvl,
                    utilization=utilization,
                    sample_rows=out["sample_rows"],
                    priority=out["priority"],
                )
        return out

    def _fault(self, kind: str, request_id: str, **detail) -> None:
        if self.telemetry is not None:
            try:
                self.telemetry.fault(
                    kind, iteration=0, request_id=request_id or None,
                    **detail)
            except Exception:  # pragma: no cover - audit is best-effort
                pass


class ShieldRunner:
    """Retry/backoff + degradation supervisor for device dispatches."""

    def __init__(
        self,
        *,
        max_retries: int = 3,
        backoff: float = 0.5,
        backoff_cap: float = 30.0,
        telemetry=None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.max_retries = max(int(max_retries), 0)
        self.backoff = max(float(backoff), 0.0)
        self.backoff_cap = float(backoff_cap)
        self.telemetry = telemetry
        self._sleep = sleep
        self.retries_total = 0
        self.degrades_total = 0

    def _fault(self, kind: str, iteration: int, **detail) -> None:
        if self.telemetry is not None:
            self.telemetry.fault(kind, iteration=iteration, **detail)

    def run(self, fn: Callable[[], object], *, iteration: int = 0,
            engine=None, output: int = 1):
        """Run ``fn`` (one full device iteration, including the blocking
        sync) under the retry/degrade policy."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                if not is_transient_failure(e):
                    raise
                attempt += 1
                if attempt <= self.max_retries:
                    delay = min(
                        self.backoff * (2.0 ** (attempt - 1)),
                        self.backoff_cap,
                    )
                    self.retries_total += 1
                    self._fault(
                        "retry", iteration, output=output,
                        attempt=attempt, max_retries=self.max_retries,
                        delay_s=delay, error=str(e)[:500],
                    )
                    if delay > 0:
                        self._sleep(delay)
                    continue
                # Retries exhausted: try stepping the eval launch down.
                new_rows = None
                if engine is not None and _is_oom(e):
                    new_rows = engine.degrade_eval_tile_rows()
                if new_rows is None:
                    raise
                attempt = 0
                self.degrades_total += 1
                self._fault(
                    "degrade", iteration, output=output,
                    eval_tile_rows=new_rows, error=str(e)[:500],
                )
