"""Graceful degradation: transient-failure retries + eval-shape step-down.

Transient device failures — ``RESOURCE_EXHAUSTED`` under memory
pressure, compile-cache deserialization glitches, a preempted collective
— should cost a bounded retry, not the whole search. The
:class:`ShieldRunner` wraps each ``Engine.run_iteration`` call:

1. transient failures retry with exponential backoff (base
   ``Options(retry_backoff)``, doubling, capped) up to
   ``Options(max_retries)`` times;
2. when retries exhaust on an OOM-shaped failure, the eval tile rows
   step down (``Engine.degrade_eval_tile_rows`` halves
   ``cfg.eval_tile_rows`` and drops the compiled programs so the next
   call re-lowers at the smaller launch geometry), the retry budget
   resets, and the iteration re-runs;
3. anything non-transient — or a run out of degradation headroom —
   re-raises.

Every retry/degrade emits a ``fault`` record into the graftscope stream
so the recovery is auditable. Failure classification is by message
substring: jaxlib's ``XlaRuntimeError`` carries the gRPC-style status
name in its text, and the fault-injection harness raises exceptions with
the same markers, so tests and production take the same path.

Caveat (documented, not hidden): the single-launch iteration donates the
input state buffers, so a failure that occurs *after* the runtime
consumed them can poison the retry. In that case the retry itself fails
with a buffer-deleted error, which is non-transient and surfaces
immediately — recovery is then ``resume="auto"`` from the last rolling
checkpoint, which is exactly what the shield keeps fresh.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

__all__ = ["ShieldRunner", "is_transient_failure", "TRANSIENT_MARKERS"]

# Substrings (case-sensitive, matching XLA/gRPC status spellings) that
# mark a failure as worth retrying. Buffer-deleted / donation errors are
# deliberately NOT here: retrying them can only fail again.
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "ABORTED",
    "DEADLINE_EXCEEDED",
    "Failed to deserialize",   # persistent compile-cache glitch
    "compilation cache",
)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED",)


def is_transient_failure(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in TRANSIENT_MARKERS)


def _is_oom(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


class ShieldRunner:
    """Retry/backoff + degradation supervisor for device dispatches."""

    def __init__(
        self,
        *,
        max_retries: int = 3,
        backoff: float = 0.5,
        backoff_cap: float = 30.0,
        telemetry=None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.max_retries = max(int(max_retries), 0)
        self.backoff = max(float(backoff), 0.0)
        self.backoff_cap = float(backoff_cap)
        self.telemetry = telemetry
        self._sleep = sleep
        self.retries_total = 0
        self.degrades_total = 0

    def _fault(self, kind: str, iteration: int, **detail) -> None:
        if self.telemetry is not None:
            self.telemetry.fault(kind, iteration=iteration, **detail)

    def run(self, fn: Callable[[], object], *, iteration: int = 0,
            engine=None, output: int = 1):
        """Run ``fn`` (one full device iteration, including the blocking
        sync) under the retry/degrade policy."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                if not is_transient_failure(e):
                    raise
                attempt += 1
                if attempt <= self.max_retries:
                    delay = min(
                        self.backoff * (2.0 ** (attempt - 1)),
                        self.backoff_cap,
                    )
                    self.retries_total += 1
                    self._fault(
                        "retry", iteration, output=output,
                        attempt=attempt, max_retries=self.max_retries,
                        delay_s=delay, error=str(e)[:500],
                    )
                    if delay > 0:
                        self._sleep(delay)
                    continue
                # Retries exhausted: try stepping the eval launch down.
                new_rows = None
                if engine is not None and _is_oom(e):
                    new_rows = engine.degrade_eval_tile_rows()
                if new_rows is None:
                    raise
                attempt = 0
                self.degrades_total += 1
                self._fault(
                    "degrade", iteration, output=output,
                    eval_tile_rows=new_rows, error=str(e)[:500],
                )
