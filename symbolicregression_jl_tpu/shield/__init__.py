"""graftshield — the fault-tolerant search runtime (docs/ROBUSTNESS.md).

A supervision layer wrapped around the ``equation_search`` host loop and
``Engine.run_iteration``, with four pillars:

1. **Preemption-safe checkpointing** (:mod:`.signals`,
   :mod:`.checkpoints`): SIGTERM/SIGINT set a flag that forces an
   emergency checkpoint at the next iteration boundary; checkpoints roll
   (last-K, digest-verified on write) and ``equation_search(resume="auto")``
   discovers and falls back to the newest *valid* one.
2. **Watchdog deadlines** (:mod:`.watchdog`): a host-side thread detects
   a hung device dispatch or runaway compile against per-phase budgets
   (``Options(iteration_deadline, compile_budget)``) and aborts with a
   diagnostic dump instead of hanging until an external ``timeout``
   kills the job (the rc=124 failure mode of MULTICHIP_r05).
3. **Graceful degradation** (:mod:`.degrade`, :mod:`.quarantine`):
   transient ``RESOURCE_EXHAUSTED``/compile-cache failures retry with
   bounded exponential backoff, then step the eval tile rows down
   instead of crashing; a NaN-storm-collapsed island is quarantined —
   reseeded from hall-of-fame entries in-graph — and the search keeps
   going.
4. **Deterministic fault injection** (:mod:`.faults`): raise-on-Nth-
   dispatch, NaN-poison-island-i, SIGTERM-at-iteration-k, checkpoint
   corruption, simulated OOM — the test suite and the CI
   ``fault-injection-smoke`` job pin every recovery path with it.

Every fault, retry, degradation, and quarantine event flows into the
graftscope JSONL stream as a ``fault`` record (telemetry/schema.py), so
recoveries are auditable per-run.
"""

from .checkpoints import (
    RollingCheckpointer,
    discover_resume_path,
    load_newest_valid,
)
from .degrade import ShieldRunner, is_transient_failure
from .faults import FaultInjector, FaultPlan, InjectedFault, active_injector
from .signals import PreemptionGuard
from .watchdog import Watchdog, WatchdogTimeout

__all__ = [
    "RollingCheckpointer",
    "discover_resume_path",
    "load_newest_valid",
    "ShieldRunner",
    "is_transient_failure",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "active_injector",
    "PreemptionGuard",
    "Watchdog",
    "WatchdogTimeout",
]
