"""Preemption signal capture: turn SIGTERM/SIGINT into a graceful stop.

TPU preemption and maintenance events deliver SIGTERM with a short grace
window; a bare SIGTERM kills the process mid-iteration and loses
everything since the last periodic checkpoint. The guard converts the
signal into a flag the search loop polls at iteration boundaries
(``_budget_stop``), which then stops with ``stop_reason="preempted"``
and writes the emergency checkpoint through the normal end-of-loop path
— the state written is exactly the state an uninterrupted run would
have had at that boundary, which is what makes ``resume="auto"``
bit-identical (tests/test_shield.py).

Signal-handler discipline (enforced by graftlint rule GL007): the
handler bodies below only record which signal arrived and set a
``threading.Event`` — no jax calls, no device syncs, no file IO, no
allocation-heavy work. Everything else (the checkpoint itself, fault
telemetry) happens later, on the main thread, at the iteration boundary.

A second SIGINT (the user leaning on ctrl-C because the current device
dispatch is long) re-raises ``KeyboardInterrupt`` so the process can
still be torn down the classic way.

Multi-tenant discipline: handler installation is REFCOUNTED and the
delivered-signal flag is process-shared. N concurrent (or nested)
searches in one process — the graftserve worker threads, a search
calling another search — each attach a guard; the first attach from the
main thread installs the real handlers, the last detach restores the
previous ones, and a single SIGTERM is observed by every attached guard
at once (the whole process was told to die, so every in-flight search
must checkpoint). A guard attached from a worker thread cannot install
handlers (a Python limitation) but still *observes* the shared flag set
by a main-thread installation — which is exactly how a search running
inside a serve worker learns about the server's SIGTERM.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

__all__ = ["PreemptionGuard"]


class _SharedSignalState:
    """Process-wide signal bookkeeping shared by every attached guard."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.event = threading.Event()
        self.signum: Optional[int] = None
        self.int_count = 0
        self.attached = 0           # live guards (any thread)
        self.handlers_installed = False
        self.prev: dict = {}


_STATE = _SharedSignalState()


# -- handlers (GL007: flag-set only; see module docstring) --------------
def _chain_unattended(signum) -> bool:
    """A signal arriving while NO guard is attached — possible when the
    last detach ran on a worker thread and handler restoration was
    deferred (see _restore_handlers) — must not be swallowed by the
    flag-only handler: nobody is polling the flag, so the process would
    become silently immune to SIGTERM/SIGINT. Handlers execute on the
    main thread, so restoring the original disposition here is legal;
    re-delivering the signal then gives it pre-guard behavior. Reads
    _STATE without the lock on purpose: a worker holding it would
    deadlock the main thread inside a signal handler, and a racing
    attach at worst sees one chained (i.e. default-behavior) signal."""
    if _STATE.attached > 0:
        return False
    _restore_handlers()
    os.kill(os.getpid(), signum)
    return True


def _on_sigterm(signum, frame) -> None:
    if _chain_unattended(signum):
        return
    _STATE.signum = signum
    _STATE.event.set()


def _on_sigint(signum, frame) -> None:
    if _chain_unattended(signum):
        return
    _STATE.int_count += 1
    _STATE.signum = signum
    _STATE.event.set()
    if _STATE.int_count >= 2:
        raise KeyboardInterrupt


class PreemptionGuard:
    """Attaches to the shared SIGTERM/SIGINT capture for one search.

    ``install``/``uninstall`` are refcounted across all guards in the
    process (see module docstring): handlers are installed once by the
    first main-thread attach and restored by the last detach, so
    concurrent or nested searches never clobber each other's handlers.
    From a non-main thread the attach is passive — no handlers are
    touched, but ``requested`` still reflects signals captured by a
    main-thread installation elsewhere in the process (e.g. the serve
    layer's own guard).
    """

    def __init__(self) -> None:
        self._attached = False

    # -------------------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        with _STATE.lock:
            if self._attached:
                return self
            self._attached = True
            if _STATE.attached == 0:
                # fresh attach cycle: a flag left over from a previous,
                # fully-detached cycle (including one whose handler
                # restore was deferred — see _restore_handlers) must
                # not preempt this search. Clear BEFORE incrementing
                # the refcount: a signal landing in between still sees
                # attached == 0 and chains to the original disposition
                # instead of being recorded and immediately wiped.
                _STATE.event.clear()
                _STATE.signum = None
                _STATE.int_count = 0
            _STATE.attached += 1
            if (
                not _STATE.handlers_installed
                and threading.current_thread() is threading.main_thread()
            ):
                try:
                    _STATE.prev[signal.SIGTERM] = signal.signal(
                        signal.SIGTERM, _on_sigterm)
                    _STATE.prev[signal.SIGINT] = signal.signal(
                        signal.SIGINT, _on_sigint)
                    _STATE.handlers_installed = True
                except (ValueError, OSError):  # non-main interpreters
                    _restore_handlers()
        return self

    def uninstall(self) -> None:
        with _STATE.lock:
            if not self._attached:
                return
            self._attached = False
            _STATE.attached = max(_STATE.attached - 1, 0)
            if _STATE.attached == 0:
                _restore_handlers()
                _STATE.event.clear()
                _STATE.signum = None
                _STATE.int_count = 0

    @property
    def installed(self) -> bool:
        """True when real handlers are live for this attach (installed
        by this guard or by another attached guard in the process)."""
        return self._attached and _STATE.handlers_installed

    # -------------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return _STATE.event.is_set()

    @property
    def signal_name(self) -> Optional[str]:
        if _STATE.signum is None:
            return None
        try:
            return signal.Signals(_STATE.signum).name
        except ValueError:  # pragma: no cover - exotic signum
            return str(_STATE.signum)

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def _restore_handlers() -> None:
    # Only the main thread may call signal.signal. When the LAST detach
    # happens on a worker thread (e.g. a serve worker's search outlives
    # the server's own guard), restoration is DEFERRED: the saved
    # original handlers stay in _STATE.prev and handlers_installed stays
    # True, so a later attach cycle reuses the installed handlers
    # without re-saving ours as "previous", and the next main-thread
    # last-detach performs the real restore. Clearing prev here would
    # leak our handlers permanently and lose the originals.
    if threading.current_thread() is not threading.main_thread():
        return
    for signum, prev in list(_STATE.prev.items()):
        try:
            signal.signal(signum, prev)
            del _STATE.prev[signum]
        except (ValueError, OSError):  # pragma: no cover
            pass
    if not _STATE.prev:
        _STATE.handlers_installed = False
