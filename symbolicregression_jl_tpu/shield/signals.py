"""Preemption signal capture: turn SIGTERM/SIGINT into a graceful stop.

TPU preemption and maintenance events deliver SIGTERM with a short grace
window; a bare SIGTERM kills the process mid-iteration and loses
everything since the last periodic checkpoint. The guard converts the
signal into a flag the search loop polls at iteration boundaries
(``_budget_stop``), which then stops with ``stop_reason="preempted"``
and writes the emergency checkpoint through the normal end-of-loop path
— the state written is exactly the state an uninterrupted run would
have had at that boundary, which is what makes ``resume="auto"``
bit-identical (tests/test_shield.py).

Signal-handler discipline (enforced by graftlint rule GL007): the
handler bodies below only record which signal arrived and set a
``threading.Event`` — no jax calls, no device syncs, no file IO, no
allocation-heavy work. Everything else (the checkpoint itself, fault
telemetry) happens later, on the main thread, at the iteration boundary.

A second SIGINT (the user leaning on ctrl-C because the current device
dispatch is long) re-raises ``KeyboardInterrupt`` so the process can
still be torn down the classic way.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers for the duration of a search.

    Only installable from the main thread (a Python limitation);
    elsewhere — e.g. a search running inside a worker thread of a
    service — ``install`` is a recorded no-op and the surrounding
    service owns signal policy.
    """

    def __init__(self) -> None:
        self._event = threading.Event()
        self._signum: Optional[int] = None
        self._int_count = 0
        self._prev: dict = {}
        self.installed = False

    # -- handlers (GL007: flag-set only; see module docstring) ----------
    def _on_sigterm(self, signum, frame) -> None:
        self._signum = signum
        self._event.set()

    def _on_sigint(self, signum, frame) -> None:
        self._int_count += 1
        self._signum = signum
        self._event.set()
        if self._int_count >= 2:
            raise KeyboardInterrupt

    # -------------------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            self._prev[signal.SIGTERM] = signal.signal(
                signal.SIGTERM, self._on_sigterm)
            self._prev[signal.SIGINT] = signal.signal(
                signal.SIGINT, self._on_sigint)
            self.installed = True
        except (ValueError, OSError):  # non-main interpreter contexts
            self.uninstall()
        return self

    def uninstall(self) -> None:
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()
        self.installed = False

    # -------------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def signal_name(self) -> Optional[str]:
        if self._signum is None:
            return None
        try:
            return signal.Signals(self._signum).name
        except ValueError:  # pragma: no cover - exotic signum
            return str(self._signum)

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
