"""Host-side watchdog: deadlines for device dispatches and compiles.

The rc=124 failure mode (MULTICHIP_r05.json): a hung device dispatch or
a runaway XLA compile blocks the host in ``block_until_ready`` forever,
and the only diagnostic is an external ``timeout`` killing the job with
nothing to show. The watchdog is a daemon thread armed around each
supervised phase; when a phase exceeds its budget it assembles a
diagnostic dump (phase, elapsed vs budget, every thread's Python stack
— the main thread's stack shows exactly which dispatch is stuck) and
invokes the abort action.

The default action writes the dump to stderr (and ``dump_path`` when
set), emits a ``fault`` telemetry event, and hard-exits with code 124 —
the same code external ``timeout`` would have produced, except minutes
earlier and with a stack attribution. A Python-level exception cannot
interrupt a thread blocked inside the XLA runtime, so a hard exit is
the honest abort; tests inject a recording action instead.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from ..utils.monitor import thread_dump

__all__ = ["Watchdog", "WatchdogTimeout"]


class WatchdogTimeout(RuntimeError):
    """Raised by the *test-friendly* `raise_in_caller` follow-up: after
    the watchdog fires, the next `phase()` entry/exit on the supervised
    thread raises this (the blocked dispatch itself cannot be
    interrupted, but a phase that eventually returns is failed)."""


def _default_abort(dump: str, exit_code: int = 124) -> None:
    sys.stderr.write(dump)
    sys.stderr.flush()
    os._exit(exit_code)


class Watchdog:
    """Arms a deadline around supervised phases of the search loop.

    Usage::

        wd = Watchdog(dump_path=..., on_timeout=None)  # None = abort
        with wd.phase("iteration", budget=options.iteration_deadline):
            state = engine.run_iteration(...)
            jax.block_until_ready(...)
        wd.stop()

    ``budget=None`` phases are unsupervised (no arming, no thread work).
    The monitor thread is started lazily on the first armed phase and
    polls at ``poll_interval``; firing is once-per-phase.
    """

    def __init__(
        self,
        *,
        on_timeout: Optional[Callable[[str], None]] = None,
        dump_path: Optional[str] = None,
        telemetry=None,
        poll_interval: float = 0.25,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._on_timeout = on_timeout
        self.dump_path = dump_path
        self.telemetry = telemetry
        self.poll_interval = float(poll_interval)
        self._clock = clock
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._phase: Optional[str] = None
        self._budget: Optional[float] = None
        self._started: Optional[float] = None
        self._iteration: int = 0
        self._fired_phase: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False
        self.last_dump: Optional[str] = None

    # ------------------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="graftshield-watchdog", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                deadline = self._deadline
                phase = self._phase
                budget = self._budget
                started = self._started
                iteration = self._iteration
            if deadline is None or phase is None:
                continue
            now = self._clock()
            if now < deadline:
                continue
            with self._lock:
                if self._deadline is None:  # disarmed while we looked
                    continue
                self._deadline = None  # fire once per phase
                self._fired_phase = phase
            self._fire(phase, budget, now - (started or now), iteration)

    def _fire(self, phase: str, budget: Optional[float], elapsed: float,
              iteration: int) -> None:
        self.fired = True
        dump = self.build_dump(phase, budget, elapsed, iteration)
        self.last_dump = dump
        if self.dump_path is not None:
            try:
                with open(self.dump_path, "w") as f:
                    f.write(dump)
            except OSError:  # the dump must not mask the timeout itself
                pass
        if self.telemetry is not None:
            try:
                self.telemetry.fault(
                    "watchdog_timeout", iteration=iteration,
                    phase=phase, budget_s=budget, elapsed_s=elapsed,
                    dump_path=self.dump_path,
                )
            except Exception:  # pragma: no cover - telemetry best-effort
                pass
        action = self._on_timeout or _default_abort
        action(dump)

    @staticmethod
    def build_dump(phase: str, budget: Optional[float], elapsed: float,
                   iteration: int) -> str:
        head = (
            "=== graftshield watchdog: phase deadline exceeded ===\n"
            f"phase      : {phase}\n"
            f"iteration  : {iteration}\n"
            f"elapsed    : {elapsed:.1f}s (budget "
            f"{'-' if budget is None else f'{budget:.1f}s'})\n"
            "A device dispatch or compile is not completing. Thread\n"
            "stacks below; the main thread shows the blocked call.\n"
        )
        return head + thread_dump() + "\n"

    # ------------------------------------------------------------------
    @contextmanager
    def phase(self, name: str, budget: Optional[float],
              iteration: int = 0):
        """Supervise one phase. No-op when ``budget`` is None."""
        if budget is None:
            yield
            return
        self._ensure_thread()
        with self._lock:
            if self._fired_phase is not None:
                fired, self._fired_phase = self._fired_phase, None
                raise WatchdogTimeout(
                    f"watchdog fired during phase {fired!r}"
                )
            self._phase = name
            self._budget = float(budget)
            self._started = self._clock()
            self._deadline = self._started + float(budget)
            self._iteration = int(iteration)
        try:
            yield
        finally:
            with self._lock:
                self._deadline = None
                self._phase = None
                if self._fired_phase is not None:
                    fired, self._fired_phase = self._fired_phase, None
                    raise WatchdogTimeout(
                        f"watchdog fired during phase {fired!r}"
                    )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
