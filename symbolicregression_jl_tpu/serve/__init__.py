"""graftserve — crash-safe, multi-tenant persistent search service.

Public surface::

    from symbolicregression_jl_tpu.serve import SearchServer, ServerSaturated

    server = SearchServer("/var/sr/root", capacity=8).start()
    rid = server.submit(X, y, options={"maxsize": 12}, niterations=8,
                        seed=7)
    status = server.poll(rid)        # queued/running/done/... + result
    server.cancel(rid)               # honored at iteration boundary
    server.stop(drain=True)

Kill the process at any point; a new ``SearchServer`` over the same
root replays the journal and finishes every accepted request with
results bit-identical to an unkilled run. Full design note:
docs/SERVING.md.
"""

from .admission import (
    AdmissionController,
    AdmissionDecision,
    ServerSaturated,
    shape_bucket,
)
from .cache import ExecutableCache
from .journal import JournalCorruptError, RequestJournal
from .server import SearchRequest, SearchServer, result_fingerprint
from .telemetry import ServeLog

__all__ = [
    "SearchServer",
    "SearchRequest",
    "ServerSaturated",
    "AdmissionController",
    "AdmissionDecision",
    "shape_bucket",
    "ExecutableCache",
    "RequestJournal",
    "JournalCorruptError",
    "ServeLog",
    "result_fingerprint",
]
