"""Compiled-executable cache: share engines (and their XLA programs)
across requests.

A cold ``equation_search`` pays the full trace+compile cost of the
evolve/epilogue programs (up to ~160 s at the device-scale config even
after the round-5 work). The per-engine jit caches live on the
``Engine`` instance's jitted callables, so a fresh Engine per request —
what ``equation_search`` builds by default — re-traces everything even
when jax's persistent compilation cache (api/search.py
``_enable_default_compile_cache``) absorbs the backend compile.

:class:`ExecutableCache` closes that gap for the serve layer: requests
whose **canonical Options fingerprint** (api/checkpoint.py
``options_fingerprint`` — every field that can affect the device
programs or numerics, host-only IO/supervision fields excluded) and
structural geometry (features, shards, mesh, dtype, params) match reuse
one Engine instance, and with it every compiled executable. Shape
buckets (serve/admission.py) label the hit/miss counters graftscope
reports; within one shared engine, each distinct row count still
compiles once and is then warm for every later request at that shape.

Uncacheable configs — template expressions (host callables inside the
engine), custom C callables the fingerprint cannot canonicalize —
return None and the caller builds a fresh Engine; correctness is never
traded for a cache hit.

Concurrency notes: jax jit dispatch/compilation is thread-safe, so two
worker threads sharing an engine at worst duplicate one compile.
``Engine.degrade_eval_tile_rows`` (the OOM step-down) mutates the
shared engine — a degrade triggered by one tenant lowers the launch
geometry for all of them, which is the intended whole-device behavior
under memory pressure (docs/SERVING.md).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..api.checkpoint import options_fingerprint

__all__ = ["ExecutableCache"]


class ExecutableCache:
    """Process-wide Engine cache keyed by canonical config + geometry."""

    def __init__(
        self,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        max_entries: int = 16,
    ) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, Any] = {}
        self._on_event = on_event
        self.max_entries = int(max_entries)
        self.hits = 0
        self.misses = 0
        self.uncacheable = 0
        self.by_bucket: Dict[Tuple[int, int, int],
                             Dict[str, int]] = {}

    # ------------------------------------------------------------------
    def _note(self, kind: str, bucket, detail: Dict[str, Any]) -> None:
        if bucket is not None:
            with self._lock:
                d = self.by_bucket.setdefault(
                    tuple(bucket), {"hits": 0, "misses": 0})
                if kind == "cache_hit":
                    d["hits"] += 1
                elif kind == "cache_miss":
                    d["misses"] += 1
        if self._on_event is not None:
            try:
                self._on_event(kind, detail)
            except Exception:  # pragma: no cover - audit is best-effort
                pass

    @staticmethod
    def _footprint_bytes(fp: Optional[str]) -> Optional[int]:
        """Known footprint for a config fingerprint from the graftgauge
        ledger (largest geometry recorded), stamped onto cache hit/miss
        telemetry. The cache itself never compiles eagerly (the jit
        caches on the engine are lazy), so this is read-only bookkeeping
        — None until some compile site has recorded the config."""
        try:
            from ..gauge import global_ledger

            entry = global_ledger().lookup(fp)
            if entry is None:
                return None
            total = (entry.get("summary") or {}).get("total_bytes")
            return int(total) if total else None
        except Exception:  # noqa: BLE001 - audit detail is best-effort
            return None

    @staticmethod
    def _mesh_key(mesh) -> Tuple:
        try:
            return (
                tuple(d.id for d in np.asarray(mesh.devices).flat),
                tuple(mesh.axis_names),
                tuple(np.asarray(mesh.devices).shape),
            )
        except Exception:
            return (repr(mesh),)

    # ------------------------------------------------------------------
    def get_engine(
        self,
        options,
        *,
        nfeatures: int,
        dtype,
        n_params: int,
        n_classes: int,
        template,
        n_data_shards: int,
        n_island_shards: int,
        mesh,
        rows: int,
        bucket: Optional[Tuple[int, int, int]] = None,
    ):
        """An Engine for this config — shared when possible, else fresh
        (and cached), else None (uncacheable; caller builds its own)."""
        if bucket is None:
            from .admission import shape_bucket

            bucket = shape_bucket(rows, nfeatures)
        if template is not None:
            # template structures hold host callables whose identity the
            # fingerprint cannot guarantee across requests
            self.uncacheable += 1
            self._note("cache_uncacheable", None,
                       {"reason": "template", "bucket": list(bucket)})
            return None
        fp = options_fingerprint(options)
        if fp is None:
            self.uncacheable += 1
            self._note("cache_uncacheable", None,
                       {"reason": "unfingerprintable",
                        "bucket": list(bucket)})
            return None
        key = (
            fp, int(nfeatures), str(np.dtype(dtype)), int(n_params),
            int(n_classes), int(n_data_shards), int(n_island_shards),
            self._mesh_key(mesh),
        )
        with self._lock:
            engine = self._entries.get(key)
            if engine is not None:
                # LRU refresh: re-insert at the end of the (insertion-
                # ordered) dict so the hottest engine is never the
                # first evicted when the cache fills
                self._entries.pop(key)
                self._entries[key] = engine
                self.hits += 1
        if engine is not None:
            self._note("cache_hit", bucket,
                       {"bucket": list(bucket), "rows": int(rows),
                        "footprint_bytes": self._footprint_bytes(fp)})
            return engine
        from ..evolve.engine import Engine

        # build OUTSIDE the lock: a slow construction for one config
        # must not serialize other workers' lookups. Losing the insert
        # race costs at most one duplicated build.
        engine = Engine(
            options, nfeatures, dtype=dtype, n_params=n_params,
            n_classes=n_classes, template=template,
            n_data_shards=n_data_shards,
            n_island_shards=n_island_shards, mesh=mesh,
        )
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                engine = existing  # another worker won the race
            else:
                if len(self._entries) >= self.max_entries:
                    # drop the least-recently-used entry (hits
                    # re-insert at the end) — a bounded cache must not
                    # pin every config's programs forever
                    oldest = next(iter(self._entries))
                    self._entries.pop(oldest, None)
                self._entries[key] = engine
            self.misses += 1
        self._note("cache_miss", bucket,
                   {"bucket": list(bucket), "rows": int(rows),
                    "footprint_bytes": self._footprint_bytes(fp)})
        return engine

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "misses": self.misses,
            "uncacheable": self.uncacheable,
            "hit_rate": (self.hits / total) if total else None,
            "by_bucket": {
                str(list(b)): dict(d) for b, d in self.by_bucket.items()
            },
        }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
