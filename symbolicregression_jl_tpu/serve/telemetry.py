"""Server-side graftscope emitter.

Each request's *search* writes its own graftscope.v1 stream (the
Telemetry hub, telemetry/hub.py) under the request's run directory; the
server itself writes one long-lived stream of ``serve`` and ``fault``
events — the fleet-level audit trail: admissions, rejections, journal
replay, cache hits, overload shedding, shutdowns. Both streams are the
same schema (telemetry/schema.py), so ``telemetry report`` and
``telemetry validate`` work on either, and the report's per-request
view groups serve events by request_id (docs/SERVING.md).

Unlike the per-search hub, this file is opened in append mode and
persists across server restarts — a restarted server's ``replay``
events land in the same stream as the original acceptances, which is
what makes the recovery auditable end to end.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..ledger.context import TraceContext, mint_run_trace
from ..telemetry.schema import SCHEMA_VERSION

__all__ = ["ServeLog"]

# server-scope events (shutdown, journal faults, cache events that
# matched no live request) still carry a trace — the constant
# server-lifecycle tree. Root-independent by construction: no path in
# the mint, so cross-root A/B comparisons see identical ids here too.
_SERVER_TRACE = mint_run_trace("graftserve")


class ServeLog:
    """Append-only graftscope.v2 emitter for serve/fault events."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {}
        # request_id -> TraceContext, populated by the server on
        # accept/replay: emitters that know only the request id (cache
        # callbacks, fault harness hooks) still stamp the right trace.
        # Bounded by the server's own request records.
        self.trace_of: Dict[str, TraceContext] = {}
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _emit(self, obj: Dict[str, Any],
              trace: Optional[TraceContext] = None) -> None:
        obj = {"schema": SCHEMA_VERSION, "t": time.time(), **obj}
        obj["trace"] = (trace or _SERVER_TRACE).to_dict()
        if self.path is None:
            return
        try:
            # _lock is this log's own line-serialization lock (held for
            # one buffered write, nothing else nests inside it); the
            # server-wide lock is never held around log calls
            with self._lock, open(self.path, "a") as f:  # graftlint: disable=GL009
                f.write(json.dumps(obj) + "\n")
        except OSError:  # auditing must never break serving
            pass

    # ------------------------------------------------------------------
    def serve(self, kind: str, request_id: str,
              trace: Optional[TraceContext] = None, **detail) -> None:
        """One request-lifecycle event (schema event type ``serve``).

        ``trace`` is the request's journaled graftledger root span —
        the same trace_id the request's search hub stamps on its own
        stream, which is what makes the serve lifecycle and the engine
        iterations one causal tree across files. Callers without the
        context in hand fall back to the ``trace_of`` registry, then to
        the server-lifecycle trace."""
        self.counts[kind] = self.counts.get(kind, 0) + 1
        if trace is None:
            trace = self.trace_of.get(str(request_id))
        self._emit({
            "event": "serve",
            "kind": str(kind),
            "request_id": str(request_id),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }, trace=trace)

    def fault(self, kind: str, *, iteration: int = 0,
              trace: Optional[TraceContext] = None, **detail) -> None:
        """A shield-style fault/recovery audit record — same shape the
        search hub emits, so OverloadLadder and the fault injectors can
        target either sink."""
        self.counts[kind] = self.counts.get(kind, 0) + 1
        self._emit({
            "event": "fault",
            "kind": str(kind),
            "iteration": int(iteration),
            "detail": {k: v for k, v in detail.items() if v is not None},
        }, trace=trace)
