"""Server-side graftscope emitter.

Each request's *search* writes its own graftscope.v1 stream (the
Telemetry hub, telemetry/hub.py) under the request's run directory; the
server itself writes one long-lived stream of ``serve`` and ``fault``
events — the fleet-level audit trail: admissions, rejections, journal
replay, cache hits, overload shedding, shutdowns. Both streams are the
same schema (telemetry/schema.py), so ``telemetry report`` and
``telemetry validate`` work on either, and the report's per-request
view groups serve events by request_id (docs/SERVING.md).

Unlike the per-search hub, this file is opened in append mode and
persists across server restarts — a restarted server's ``replay``
events land in the same stream as the original acceptances, which is
what makes the recovery auditable end to end.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..telemetry.schema import SCHEMA_VERSION

__all__ = ["ServeLog"]


class ServeLog:
    """Append-only graftscope.v1 emitter for serve/fault events."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {}
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _emit(self, obj: Dict[str, Any]) -> None:
        obj = {"schema": SCHEMA_VERSION, "t": time.time(), **obj}
        if self.path is None:
            return
        try:
            with self._lock, open(self.path, "a") as f:
                f.write(json.dumps(obj) + "\n")
        except OSError:  # auditing must never break serving
            pass

    # ------------------------------------------------------------------
    def serve(self, kind: str, request_id: str, **detail) -> None:
        """One request-lifecycle event (schema event type ``serve``)."""
        self.counts[kind] = self.counts.get(kind, 0) + 1
        self._emit({
            "event": "serve",
            "kind": str(kind),
            "request_id": str(request_id),
            "detail": {k: v for k, v in detail.items() if v is not None},
        })

    def fault(self, kind: str, *, iteration: int = 0, **detail) -> None:
        """A shield-style fault/recovery audit record — same shape the
        search hub emits, so OverloadLadder and the fault injectors can
        target either sink."""
        self.counts[kind] = self.counts.get(kind, 0) + 1
        self._emit({
            "event": "fault",
            "kind": str(kind),
            "iteration": int(iteration),
            "detail": {k: v for k, v in detail.items() if v is not None},
        })
