"""The live metrics surface: a Prometheus-text HTTP endpoint.

graftscope's JSONL stream answers "what happened"; this answers "what
is happening": a tiny stdlib HTTP server exposing ``GET /metrics``
(Prometheus text format v0.0.4, rendered fresh per scrape from
``SearchServer.metrics_text``) and ``GET /healthz``. No third-party
client library, no background sampling thread — the server's own
counters (admission, executable cache, request records) ARE the state,
and a scrape just reads them.

Binds 127.0.0.1 by default (the serve API itself is in-process;
exposing metrics beyond the host is a deployment decision, not a
default). ``port=0`` picks an ephemeral port — tests and multi-server
hosts read it back from ``MetricsServer.port``.

docs/OBSERVABILITY.md carries the full metric-name table.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

__all__ = ["MetricsServer", "CONTENT_TYPE", "render_ledger_metrics",
           "render_gauge_metrics"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def render_ledger_metrics(p, rollup: Optional[dict]) -> None:
    """Append the graftledger per-tenant cost section to a ``PromText``
    builder from a ``graftledger.rollup.v1`` document (ledger/rollup.py;
    None — no rollup written yet — appends nothing).

    One label set per request the root has ever completed: attribution
    is the point, and a serve root's request count is bounded by its
    lifetime, not its concurrency — operators with long-lived roots
    should scrape the rollup file instead of relying on these families
    staying small."""
    if not rollup:
        return
    for rid, acct in sorted(rollup.get("requests", {}).items()):
        labels = {"request": rid}
        p.counter("request_device_seconds_total", acct.get("device_s", 0.0),
                  "Ledger-attributed device seconds per request", labels)
        p.counter("request_host_seconds_total", acct.get("host_s", 0.0),
                  "Ledger-attributed host bookkeeping seconds", labels)
        p.counter("request_compile_seconds_total",
                  acct.get("compile_s", 0.0),
                  "Ledger-attributed trace+compile seconds", labels)
        p.counter("request_ledger_evals_total", acct.get("num_evals", 0.0),
                  "Final cumulative expression evaluations", labels)
        p.counter("request_checkpoint_bytes_total",
                  acct.get("checkpoint_bytes", 0),
                  "Bytes of full-state checkpoints written", labels)
        hist = acct.get("iteration_latency") or {}
        le = hist.get("le") or []
        counts = hist.get("counts") or []
        if le and len(counts) == len(le) + 1:
            p.histogram(
                "request_iteration_latency_seconds", le, counts,
                acct.get("device_s", 0.0) + acct.get("host_s", 0.0),
                "Per-iteration device+host latency (log-bucketed)",
                labels)
    totals = rollup.get("totals", {})
    p.counter("ledger_device_seconds_total", totals.get("device_s", 0.0),
              "Ledger-attributed device seconds, all requests")
    p.counter("ledger_evals_total", totals.get("num_evals", 0.0),
              "Cumulative expression evaluations, all requests")


def render_gauge_metrics(p) -> None:
    """Append the graftgauge capacity section to a ``PromText`` builder:
    the process-wide dispatch-latency histogram, the peak live-array
    bytes any search in this process reached, and one ``footprint_bytes``
    gauge per footprint-ledger entry (fingerprint truncated to 12 hex
    chars — a label, not a join key; the full value is in the gauge
    events and the ledger API). All reads of process-global state;
    never raises into a scrape."""
    try:
        from ..gauge import global_latency, global_ledger, process_peak_bytes

        global_latency().render(p)
        p.gauge(
            "process_peak_live_bytes", process_peak_bytes(),
            "Peak live jax-array bytes observed by any search "
            "in this process",
        )
        for e in global_ledger().entries():
            total = (e.get("summary") or {}).get("total_bytes")
            if not total:
                continue
            fp = e.get("fingerprint") or ""
            p.gauge(
                "footprint_bytes", int(total),
                "Compiled-program footprint (temp+args+output+aliases"
                "+code) from XLA memory analysis",
                {
                    "fingerprint": fp[:12] or "none",
                    "geometry": e.get("geometry", ""),
                    "source": e.get("source", ""),
                },
            )
    except Exception:  # noqa: BLE001 - a scrape must not 500 on gauge
        pass


class MetricsServer:
    """Serve ``render()`` at /metrics until ``stop()``."""

    def __init__(self, render: Callable[[], str], *, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.render = render
        self._requested_port = int(port)
        self.host = host
        # guards the _httpd/_thread lifecycle handoff only — never held
        # across bind/shutdown/join (those block on the network stack)
        self._state_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port (after ``start()``; resolves port=0)."""
        httpd = self._httpd
        return httpd.server_address[1] if httpd else None

    @property
    def running(self) -> bool:
        return self._httpd is not None

    def start(self) -> "MetricsServer":
        with self._state_lock:
            if self._httpd is not None:
                # a second bind would leak a ThreadingHTTPServer on a
                # second port behind the caller's back — refuse loudly;
                # callers that may race a live endpoint check .running
                raise RuntimeError(
                    f"MetricsServer already serving on port {self.port}")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        body = outer.render().encode()
                    except Exception as e:  # render must not kill a scrape
                        self.send_error(500, explain=str(e)[:200])
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", "3")
                    self.end_headers()
                    self.wfile.write(b"ok\n")
                else:
                    self.send_error(404)

            def log_message(self, *args) -> None:
                pass  # scrapes every few seconds; stderr stays quiet

        # bind OUTSIDE the state lock (it can block in the network
        # stack); publish under it, losing a concurrent start() cleanly
        httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        httpd.daemon_threads = True
        with self._state_lock:
            if self._httpd is not None:
                httpd.server_close()
                raise RuntimeError(
                    f"MetricsServer already serving on port {self.port}")
            self._httpd = httpd
            self._thread = threading.Thread(
                target=httpd.serve_forever,
                name="graftserve-metrics", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Shut the endpoint down and join the serving thread (bounded).
        Idempotent — concurrent/repeat stops take the refs under the
        state lock, so exactly one caller does the shutdown."""
        with self._state_lock:
            httpd, thread = self._httpd, self._thread
            self._httpd = None
            self._thread = None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
