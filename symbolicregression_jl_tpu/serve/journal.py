"""Durable request journal — the crash-recovery spine of graftserve.

Schema-versioned JSONL (``graftserve.v1``), one record per request
lifecycle transition, each record individually sha256-verified like the
checkpoint v2 envelope (api/checkpoint.py)::

    {"schema": "graftserve.v1", "seq": 7, "t": ..., "event": "submit",
     "request_id": "req00003", "detail": {...}, "sha256": "<hex>"}

The digest is computed over the canonical (sort_keys) JSON of the record
*without* the ``sha256`` field, so any bit flip or truncation inside a
record is detected on replay. Appends are flushed + fsync'd before
``append`` returns: once ``submit`` has returned to the client, the
acceptance survives a kill -9.

Replay (:meth:`RequestJournal.replay`) is corruption-tolerant in the
same spirit as the rolling-checkpoint fallback: a torn final record
(the expected artifact of a crash mid-append) is dropped silently-but-
audited, a corrupt record in the middle is skipped and reported, and
everything verifiable is returned in order. The server turns the
corruption notes into ``fault`` telemetry events so every recovery is
auditable (docs/SERVING.md).

Dataset arrays ride inside ``submit`` records as base64-encoded raw
bytes (:func:`encode_array`) — bit-exact round-trip, which the
killed-vs-unkilled bit-identity guarantee needs. The journal is the
replay source, so it holds the request's *effective* configuration:
post-admission shed sample size, demoted priority, seed, options
kwargs. This bounds journal use to small/medium requests (the workload
PAPER.md §2.10 describes); multi-GB datasets want a content-addressed
store, not a journal line.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "JOURNAL_SCHEMA",
    "JournalCorruptError",
    "RequestJournal",
    "encode_array",
    "decode_array",
]

JOURNAL_SCHEMA = "graftserve.v1"

# Lifecycle record kinds. `submit` carries the full effective request;
# the others reference it by request_id.
RECORD_EVENTS = ("submit", "start", "done", "cancel", "failed")


class JournalCorruptError(ValueError):
    """The journal file as a whole cannot be trusted (e.g. a schema
    marker from a future incompatible version). Per-record corruption
    does NOT raise — it is skipped and reported by ``replay``."""


def encode_array(a) -> Dict[str, Any]:
    """numpy array -> JSON-safe dict with bit-exact payload."""
    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def decode_array(d: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["data"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


def _record_digest(rec: Dict[str, Any]) -> str:
    body = {k: v for k, v in rec.items() if k != "sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


class RequestJournal:
    """Append-only, digest-per-record JSONL journal for one server."""

    def __init__(self, path: str, injector=None) -> None:
        import threading

        self.path = path
        self.injector = injector  # ServeFaultInjector (corrupt-record hook)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # appends come from both the server (submit/cancel) and its
        # worker threads (start/done) — seq assignment and the write
        # must be atomic
        self._lock = threading.Lock()
        self._seq = 0
        self._records_written = 0
        # counter recovery from an existing file is deferred to the
        # first replay() or append(): the server replays once at
        # startup anyway, and submit records embed whole datasets — a
        # second parse+digest pass over the journal would double the
        # recovery cost for nothing
        self._recovered = not os.path.exists(path)

    # ------------------------------------------------------------------
    def append(self, event: str, request_id: str,
               detail: Optional[Dict[str, Any]] = None) -> int:
        """Durably append one record; returns its seq number."""
        if event not in RECORD_EVENTS:
            raise ValueError(
                f"journal event {event!r} not one of {RECORD_EVENTS}")
        if not self._recovered:
            self.replay()  # one-time counter recovery (reopened file)
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = {
                "schema": JOURNAL_SCHEMA,
                "seq": seq,
                "t": time.time(),
                "event": event,
                "request_id": str(request_id),
                "detail": detail or {},
            }
            rec["sha256"] = _record_digest(rec)
            line = (json.dumps(rec, sort_keys=True) + "\n").encode()
            # binary append: byte-exact offsets for the corruption-
            # injection hook, no text-mode tell() cookie ambiguity
            # a+b (not ab): append semantics with READ access, needed
            # for the torn-tail probe below
            #
            # _lock exists precisely to serialize seq assignment with
            # this file append+fsync (a record's durability is its
            # acknowledgement); callers never hold any other lock here
            with open(self.path, "a+b") as f:  # graftlint: disable=GL009
                f.seek(0, os.SEEK_END)
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        # torn tail from a crash mid-append: seal the
                        # partial line so this record is not glued onto
                        # the corrupt bytes — otherwise the first
                        # post-restart append (already fsync'd and
                        # acknowledged to its client) would itself be
                        # unreadable after a second crash. replay still
                        # skips + audits the sealed torn line.
                        f.write(b"\n")
                offset = f.tell()
                f.write(line)
                f.flush()
                os.fsync(f.fileno())  # graftlint: disable=GL009
            self._records_written += 1
            if self.injector is not None:
                self.injector.on_journal_append(
                    self.path, self._records_written, offset,
                    len(line) - 1)
        return seq

    # ------------------------------------------------------------------
    def replay(self) -> Tuple[List[Dict[str, Any]],
                              List[Dict[str, Any]]]:
        """Read back every verifiable record, in order.

        Returns ``(records, corrupt)`` where ``corrupt`` is one note per
        unusable line: ``{"line": n, "reason": ..., "torn_tail": bool}``.
        A non-JSON or digest-failing FINAL line is classified as a torn
        tail (the normal crash artifact); anywhere else it is skipped
        corruption. Both are audited by the server as ``fault`` events.
        """
        records: List[Dict[str, Any]] = []
        corrupt: List[Dict[str, Any]] = []
        if not os.path.exists(self.path):
            return records, corrupt
        # binary read: a bit-flipped record may not even be valid UTF-8,
        # and one garbled line must not make the whole file unreadable
        with open(self.path, "rb") as f:
            lines = f.read().splitlines()
        last = len(lines)
        for lineno, raw in enumerate(lines, start=1):
            raw = raw.strip()
            if not raw:
                continue
            reason = None
            rec = None
            try:
                rec = json.loads(raw.decode())
            except UnicodeDecodeError as e:
                reason = f"invalid UTF-8: {e}"
            except json.JSONDecodeError as e:
                reason = f"invalid JSON: {e}"
            if rec is not None:
                if not isinstance(rec, dict):
                    reason = "record is not an object"
                elif rec.get("sha256") != _record_digest(rec):
                    # digest FIRST: a bit flip inside the schema string
                    # must be per-record corruption (skip + audit), not
                    # a file-level version error that bricks recovery
                    reason = "sha256 digest mismatch"
                elif rec.get("schema") != JOURNAL_SCHEMA:
                    # digest-valid but different schema: genuinely a
                    # file from an incompatible journal version
                    raise JournalCorruptError(
                        f"{self.path}:{lineno}: schema "
                        f"{rec.get('schema')!r}, expected "
                        f"{JOURNAL_SCHEMA!r}"
                    )
                elif rec.get("event") not in RECORD_EVENTS:
                    reason = f"unknown event {rec.get('event')!r}"
            if reason is not None:
                corrupt.append({
                    "line": lineno,
                    "reason": reason,
                    "torn_tail": lineno == last,
                })
                continue
            records.append(rec)
        with self._lock:
            if not self._recovered:
                self._recovered = True
                if records:
                    self._seq = max(self._seq,
                                    max(r["seq"] for r in records))
                # floor at the line count too: when the NEWEST record
                # is the corrupt one, its seq must not be reused by the
                # next append (every record's seq <= its line number,
                # so this over-approximation keeps seqs unique)
                self._seq = max(self._seq, last)
                self._records_written = max(self._records_written, last)
        return records, corrupt
