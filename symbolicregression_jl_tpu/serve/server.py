"""graftserve — a crash-safe, multi-tenant persistent search service.

One long-lived :class:`SearchServer` owns a device, a compiled-engine
cache, a bounded admission queue, and a durable request journal; clients
interact through a thin **submit / poll / cancel** API in front of
``api/search.py`` (ROADMAP item 2; docs/SERVING.md is the full design
note). Robustness-first contracts:

- **Durability**: once ``submit`` returns, the request is journaled
  (serve/journal.py, fsync'd, sha256 per record). A SIGTERM'd, killed,
  or crashed server process, restarted over the same root directory,
  replays the journal and finishes every accepted request — in-flight
  searches resume from their graftshield rolling checkpoints
  (``resume="auto"``), and each completed result is **bit-identical**
  to what an unkilled server would have produced (the per-request
  searches are deterministic given seed+options, and boundary-only
  stops keep checkpoints on the uninterrupted trajectory).
- **Admission control**: bounded, shape-bucketed queue with an overload
  ladder (shield/degrade.py) — shed row-sample size, then queue
  priority, then reject with a structured retry-after error
  (serve/admission.py). Saturation never hangs and never OOMs the
  device with unbounded queued work.
- **Cancellation + deadlines**: per-request cancel and deadline are
  wired through ``RuntimeOptions.stop_hook`` (honored at iteration
  boundaries, preserving resume bit-identity) with a
  shield/watchdog.py backstop for genuinely hung dispatches.
- **Audit**: every lifecycle transition, recovery, rejection, shed, and
  cache hit/miss is a graftscope.v1 ``serve``/``fault`` event
  (serve/telemetry.py); ``telemetry report`` renders the per-request
  view and the executable-cache hit rate.

Requests are specified as JSON-able payloads (numpy data + an Options
**kwargs dict**) precisely so the journal can replay them; Options
objects with live callables don't survive a process boundary.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..ledger.context import TraceContext, mint_trace
from ..ledger.rollup import load_rollup, write_rollup
from ..pack import (PackedCohort, PackPolicy, pack_group_key, packable,
                    pad_to_bucket, slot_cap)
from ..shield.faults import active_serve_injector
from ..shield.watchdog import Watchdog, WatchdogTimeout
from .admission import AdmissionController, ServerSaturated, shape_bucket
from .cache import ExecutableCache
from .journal import RequestJournal, decode_array, encode_array
from .telemetry import ServeLog

__all__ = ["SearchServer", "SearchRequest", "ServerSaturated"]

# Options keys the server owns; client-supplied values are ignored so a
# request can neither disable its own durability, write outside its run
# directory, nor arm the shield watchdog's process-abort (os._exit 124)
# — one tenant's deadline must never kill the whole server (and, via
# journal replay of the poison request, crash-loop every restart).
# Per-request deadlines go through submit(deadline_s=...), which
# cancels at iteration boundaries instead of aborting the process.
# timeout_in_seconds is owned for a different reason: a wall-clock stop
# is machine-load dependent, so it would journal a NONDETERMINISTIC
# partial result as "done" and break the kill-restart bit-identity
# contract. (max_evals/early_stop_condition stay client-usable: they
# stop on deterministic search state.)
_SERVER_OWNED_OPTIONS = (
    "output_directory", "save_to_file", "telemetry", "telemetry_file",
    "interactive_quit", "seed", "shield", "use_recorder",
    "iteration_deadline", "compile_budget", "timeout_in_seconds",
)

_TERMINAL = ("done", "failed", "cancelled")


@dataclasses.dataclass
class SearchRequest:
    """The journaled (effective, post-admission) form of one request."""

    request_id: str
    X: np.ndarray
    y: np.ndarray
    niterations: int
    seed: int
    options_kwargs: Dict[str, Any]
    priority: int = 0
    deadline_s: Optional[float] = None
    sample_rows: Optional[int] = None
    bucket: Tuple[int, int, int] = (0, 0, 0)
    index: int = 0  # k-th accepted request of this root, 1-based
    # graftpack padded-bucket provenance (docs/SERVING.md "Packed
    # tenancy"): the pow2 row count this request's dataset is padded to
    # (0 = unpacked path) and how many zero-weight replica rows that
    # adds AFTER any overload-shed sampling. Journaled effective
    # configuration, like sample_rows: replay reads these back instead
    # of re-deriving from the server's current pack setting, so a
    # killed-and-restarted request pads identically even if the
    # restarted server's pack policy changed.
    bucket_rows: int = 0
    pad_rows: int = 0
    # graftpulse: arm a profiler-capture window for this request's
    # search (RuntimeOptions.pulse_trace_on); journaled so a replayed
    # request still honors it
    pulse_trace: bool = False
    # graftledger: the request's root TraceContext, minted at submit()
    # from request content (ledger/context.py) and journaled — a
    # replayed request reads the ORIGINAL ids back verbatim, so
    # kill-restart-replay reconstructs the identical causal tree.
    trace: Optional[TraceContext] = None

    def to_detail(self) -> Dict[str, Any]:
        return {
            "X": encode_array(self.X),
            "y": encode_array(self.y),
            "niterations": int(self.niterations),
            "seed": int(self.seed),
            "options_kwargs": self.options_kwargs,
            "priority": int(self.priority),
            "deadline_s": self.deadline_s,
            "sample_rows": self.sample_rows,
            "bucket": list(self.bucket),
            "index": int(self.index),
            "bucket_rows": int(self.bucket_rows),
            "pad_rows": int(self.pad_rows),
            "pulse_trace": bool(self.pulse_trace),
            "trace": self.trace.to_dict() if self.trace else None,
        }

    @staticmethod
    def from_detail(request_id: str, d: Dict[str, Any]) -> "SearchRequest":
        return SearchRequest(
            request_id=request_id,
            X=decode_array(d["X"]),
            y=decode_array(d["y"]),
            niterations=int(d["niterations"]),
            seed=int(d["seed"]),
            options_kwargs=dict(d.get("options_kwargs") or {}),
            priority=int(d.get("priority", 0)),
            deadline_s=d.get("deadline_s"),
            sample_rows=d.get("sample_rows"),
            bucket=tuple(d.get("bucket") or (0, 0, 0)),
            index=int(d.get("index", 0)),
            bucket_rows=int(d.get("bucket_rows", 0)),
            pad_rows=int(d.get("pad_rows", 0)),
            pulse_trace=bool(d.get("pulse_trace", False)),
            # pre-graftledger journals carry no trace: re-mint from the
            # same content the original submit would have hashed, so
            # old roots replay with stable (and still deterministic) ids
            trace=(TraceContext.from_dict(d.get("trace"))
                   or mint_trace(request_id,
                                 seed=int(d["seed"]),
                                 niterations=int(d["niterations"]))),
        )


class _RequestRecord:
    """In-memory runtime state of one accepted request."""

    def __init__(self, request: SearchRequest) -> None:
        self.request = request
        # queued|running|done|failed|cancelled — a preempted request
        # goes back to "queued" (the `interrupted` serve EVENT audits
        # the transition; it is not a state)
        self.state = "queued"
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.cancel_event = threading.Event()
        self.cancel_reason: Optional[str] = None
        self.submitted_t = time.time()
        self.started_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.resumed = False
        # False while submit() is still journaling the record (outside
        # the server lock): a cancel in that window defers its journal
        # write to submit's publish step, so the journal can never hold
        # a `cancel` record ahead of its `submit` (replay would drop it)
        self.journaled = False
        # wall-clock of the FIRST start attempt, surviving preemptions
        # and restarts (recovered from the journal's start records):
        # the request's deadline_s budget is anchored here, not at each
        # resume, so a preempted request cannot restart its clock
        self.first_started_wall: Optional[float] = None
        # live per-iteration progress (graftpulse /metrics gauges):
        # written by the worker's logger probe, read by metrics_text
        self.progress: Optional[Dict[str, Any]] = None

    def cancel(self, reason: str = "cancelled") -> None:
        # a terminal cancel (client/deadline) OVERRIDES a pending
        # preemption — preempt means "pause and resume later", cancel
        # means "never finish"; the terminal reason must win or the
        # requeue path would resurrect a cancelled request. The first
        # terminal reason sticks.
        if self.cancel_reason in (None, "preempted"):
            self.cancel_reason = reason
        self.cancel_event.set()

    def snapshot(self) -> Dict[str, Any]:
        return {
            "request_id": self.request.request_id,
            "state": self.state,
            "priority": self.request.priority,
            "bucket": list(self.request.bucket),
            "sample_rows": self.request.sample_rows,
            "bucket_rows": self.request.bucket_rows,
            "pad_rows": self.request.pad_rows,
            "result": self.result,
            "error": self.error,
            "cancel_reason": self.cancel_reason,
            "resumed": self.resumed,
        }


class _InjectorProbe:
    """RuntimeOptions.logger shim: a per-iteration hook inside a
    running request's search without any api/search.py surface. Serves
    three consumers: the serve fault injector (cancel-mid-iteration
    scenario), the /metrics per-request progress gauges (iteration,
    evals, evals/s of every RUNNING request, live), and — when the
    request runs inside a graftpack cohort — the lockstep barrier
    (pack/cohort.py), which keys the tenants' iteration boundaries
    together. The barrier call comes LAST: a cancel decided this
    iteration must not wait a full round to be observed."""

    def __init__(self, server: "SearchServer", rec: _RequestRecord,
                 cohort: Optional[PackedCohort] = None,
                 slot: Optional[int] = None) -> None:
        self.server = server
        self.rec = rec
        self.cohort = cohort
        self.slot = slot

    def log_iteration(self, *, iteration, num_evals=0.0, elapsed=0.0,
                      **_kw) -> None:
        it = int(iteration)
        self.rec.progress = {
            "iteration": it,
            "num_evals": float(num_evals),
            "elapsed_s": float(elapsed),
            "evals_per_sec": float(num_evals) / max(float(elapsed), 1e-9),
        }
        inj = self.server._injector
        if inj is not None and inj.should_cancel(
                self.rec.request.index, it,
                self.rec.request.request_id):
            self.rec.cancel("cancelled")
        if self.cohort is not None and self.slot is not None:
            self.cohort.arrive(self.slot)


class _RequestCacheView:
    """RuntimeOptions.engine_cache adapter pinning the request's
    ADMISSION bucket onto the cache's hit/miss accounting. Without it
    the cache recomputes the bucket from the effective row count, so an
    overload-shed request (1000 rows sampled to 500) would be
    admission-counted in bucket 1024 but cache-counted in bucket 512 —
    and `telemetry report`'s per-bucket views would disagree. Pure
    accounting: the engine cache key itself is row-agnostic."""

    def __init__(self, cache: ExecutableCache, bucket) -> None:
        self._cache = cache
        self._bucket = tuple(bucket) if any(bucket) else None

    def get_engine(self, options, **kw):
        if self._bucket is not None:
            kw.setdefault("bucket", self._bucket)
        return self._cache.get_engine(options, **kw)


def result_fingerprint(state) -> str:
    """sha256 over the device hall-of-fame tensors of a finished
    SearchState — the bit-identity comparison surface for the
    killed-vs-unkilled acceptance check (tools/serve_smoke.py)."""
    h = hashlib.sha256()
    for ds in state.device_states:
        for f in ("arity", "op", "feat", "const", "length"):
            h.update(np.ascontiguousarray(
                np.asarray(getattr(ds.hof.trees, f))).tobytes())
        h.update(np.ascontiguousarray(np.asarray(ds.hof.cost)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(ds.hof.loss)).tobytes())
    return h.hexdigest()


class SearchServer:
    """The persistent engine process (see module docstring).

    ``SearchServer(root)`` over an existing root replays the journal and
    re-queues every accepted-but-unfinished request; call ``start()`` to
    begin (or resume) draining. ``workers=0`` with ``start()`` never
    called is valid — submissions queue (or reject) without running,
    which the admission tests use.
    """

    def __init__(
        self,
        root: str,
        *,
        capacity: int = 8,
        bucket_capacity: Optional[int] = None,
        workers: int = 1,
        ladder=None,
        cache: Optional[ExecutableCache] = None,
        hang_grace_s: float = 60.0,
        telemetry: bool = True,
        metrics_port: Optional[int] = None,
        debug_checks: bool = False,
        pack=None,
    ) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.log = ServeLog(
            os.path.join(self.root, "serve_telemetry.jsonl")
            if telemetry else None
        )
        self._injector = active_serve_injector(telemetry=self.log)
        self.journal = RequestJournal(
            os.path.join(self.root, "requests.jsonl"),
            injector=self._injector,
        )
        from ..gauge import HeadroomModel
        from ..shield.degrade import OverloadLadder

        self.admission = AdmissionController(
            capacity, bucket_capacity=bucket_capacity,
            ladder=ladder or OverloadLadder(telemetry=self.log),
            # graftgauge memory advisory: predicted footprint vs device
            # budget, attached to every accept record (advisory only —
            # see AdmissionController; docs/SERVING.md)
            headroom=HeadroomModel(),
        )
        self.cache = cache or ExecutableCache(
            on_event=self._on_cache_event)
        # graftpack multi-tenant packing (docs/SERVING.md "Packed
        # tenancy"): OFF by default. ``pack=True`` enables the default
        # PackPolicy; a PackPolicy instance sets the knobs. When on,
        # packable requests are padded to their admission bucket at
        # submit (journaled provenance) and same-group queued requests
        # launch together as one lockstep cohort sharing a compiled
        # engine, instead of timesharing the worker.
        if pack is True:
            self.pack: Optional[PackPolicy] = PackPolicy()
        elif pack:
            self.pack = pack
        else:
            self.pack = None
        # pack counters for /metrics; mutated under self._lock
        self._pack_stats = {
            "launches": 0, "multi_tenant_launches": 0, "tenants": 0,
            "peak_tenants": 0, "occupancy_sum": 0.0, "occupancy_n": 0,
        }
        # pack groups whose shared programs have been traced at least
        # once (a tenant completed an iteration): cold groups stage
        # their first launch so ONE tenant pays the trace/compile
        # instead of every tenant re-tracing concurrently (the engine
        # cache dedupes Engine objects, not jit traces in flight)
        self._pack_warm: set = set()
        self.workers = int(workers)
        self.hang_grace_s = float(hang_grace_s)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._records: Dict[str, _RequestRecord] = {}
        self._queue: List[Tuple[int, int, str]] = []  # (priority, seq, id)
        self._qseq = 0
        self._rid_seq = 0  # auto request-id counter (collision-skipping)
        self._accepted = 0  # k-th accepted counter (faults target it)
        self._threads: List[threading.Thread] = []
        self._stopping = False
        self._preempting = False
        self._guard = None
        # per-WORKER-thread request attribution for cache events: a
        # shared attribute would be clobbered across workers
        self._cache_tls = threading.local()
        # graftpulse live metrics endpoint (serve/metrics.py): None
        # disables; 0 binds an ephemeral port (read server.metrics.port
        # back after start()). Scrapes render metrics_text() fresh.
        self.metrics = None
        if metrics_port is not None:
            from .metrics import MetricsServer

            self.metrics = MetricsServer(self.metrics_text,
                                         port=metrics_port)
        # graftwarden runtime auditor (lint/racecheck.py): wraps every
        # serve/shield lock, asserts actual acquisition order against
        # the blessed lint/lock_order.py manifest, and honors the
        # SR_RACE_PLAN deterministic context-switch windows. Opt-in —
        # ctor flag or SR_RACECHECK=1 — so production pays nothing.
        self.debug_checks = bool(debug_checks) or bool(
            os.environ.get("SR_RACECHECK"))
        if self.debug_checks:
            from ..lint.racecheck import instrument_server

            self._race_recorder = instrument_server(self)
        self._recover()

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------
    def _recover(self) -> None:
        records, corrupt = self.journal.replay()
        for note in corrupt:
            # A torn tail is the expected crash artifact; mid-file
            # corruption means a journaled acceptance may be LOST — both
            # are audited, the latter loudly.
            self.log.fault(
                "journal_corrupt", line=note["line"],
                reason=note["reason"], torn_tail=note["torn_tail"],
            )
        started: Dict[str, bool] = {}
        pending: List[Tuple[int, int, str]] = []
        for rec in records:
            rid = rec["request_id"]
            ev = rec["event"]
            if ev == "submit":
                try:
                    req = SearchRequest.from_detail(rid, rec["detail"])
                    self.log.trace_of[rid] = req.trace
                except Exception as e:  # noqa: BLE001 - poison record
                    # a digest-valid record whose payload cannot be
                    # reconstructed must not brick recovery of every
                    # OTHER request in the root: skip it, loudly
                    self.log.fault(
                        "journal_replay_failed", request_id=rid,
                        error=f"{type(e).__name__}: {e}"[:200],
                    )
                    continue
                r = _RequestRecord(req)
                r.journaled = True
                self._records[rid] = r
                self._accepted = max(self._accepted, req.index)
                pending.append((req.priority, req.index, rid))
            elif rid not in self._records:
                continue  # lifecycle record whose submit was corrupted
            elif ev == "start":
                started[rid] = True
                r = self._records[rid]
                t = rec.get("t")
                if isinstance(t, (int, float)) and (
                        r.first_started_wall is None
                        or t < r.first_started_wall):
                    r.first_started_wall = t
            elif ev == "done":
                r = self._records[rid]
                r.state = "done"
                r.result = rec["detail"].get("result")
            elif ev == "cancel":
                r = self._records[rid]
                r.state = "cancelled"
                r.cancel_reason = rec["detail"].get("reason", "cancelled")
            elif ev == "failed":
                r = self._records[rid]
                r.state = "failed"
                r.error = rec["detail"].get("error")
        for priority, index, rid in sorted(pending, key=lambda t: t[:2]):
            r = self._records[rid]
            if r.state in _TERMINAL:
                continue
            r.resumed = started.get(rid, False)
            self.admission.readmit(r.request.bucket)
            # construction-time: _recover runs from __init__ before any
            # worker thread exists, so the queue counter is unshared
            self._qseq += 1  # graftlint: disable=GL011
            heapq.heappush(self._queue, (priority, self._qseq, rid))
            self.log.serve(
                "replay", rid, trace=r.request.trace, resumed=r.resumed,
                bucket=list(r.request.bucket),
            )

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(
        self,
        X,
        y,
        *,
        options: Optional[Dict[str, Any]] = None,
        niterations: int = 4,
        seed: int = 0,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        request_id: Optional[str] = None,
        pulse_trace: bool = False,
    ) -> str:
        """Admit one search request; returns its request_id.

        Raises :class:`ServerSaturated` (with a retry-after hint) when
        the queue or the request's shape class is full, and ValueError
        for malformed payloads. On return the request is durably
        journaled and will complete even across server crashes.
        """
        # copy, not asarray: the accepted request must be a SNAPSHOT of
        # the submit-time bytes. A caller reusing its buffer after
        # submit would otherwise mutate the queued in-memory request
        # while the journal holds the original — and the in-process
        # result would differ from a crash-replay's (bit-identity).
        X = np.array(X, copy=True)
        y = np.array(y, copy=True)
        if X.ndim != 2 or y.ndim != 1 or X.shape[0] != y.shape[0]:
            raise ValueError(
                f"expected X [n, f] and y [n]; got {X.shape} / {y.shape}")
        if X.dtype.kind not in "biuf" or y.dtype.kind not in "biuf":
            # an object-dtype array would journal cleanly (tobytes()
            # succeeds) but decode_array cannot reconstruct it — the
            # poison record would brick every future replay of the root
            raise ValueError(
                f"X/y must be numeric arrays; got {X.dtype} / {y.dtype}")
        opts = dict(options or {})
        for k in _SERVER_OWNED_OPTIONS:
            opts.pop(k, None)
        try:
            json.dumps(opts)
        except TypeError as e:
            raise ValueError(
                "serve options must be a JSON-able kwargs dict (the "
                f"journal replays it across restarts): {e}"
            ) from e
        if self._stopping:
            raise ServerSaturated(
                "server is shutting down",
                retry_after_s=self.admission.default_retry_after_s,
                queue_depth=self.admission.depth,
                capacity=self.admission.capacity,
                bucket=shape_bucket(X.shape[0], X.shape[1]),
                level="shutdown",
            )
        # admission (internally locked) runs OUTSIDE the server lock:
        # under overload its ladder writes shed/reject audit records to
        # the serve telemetry file, and file I/O must not stall
        # poll/cancel or the workers' queue transitions
        try:
            decision = self.admission.admit(
                n_rows=X.shape[0], nfeatures=X.shape[1],
                priority=priority, request_id=request_id or "",
            )
        except ServerSaturated as e:
            self.log.serve("reject", request_id or "", **e.to_dict())
            raise
        try:
            with self._lock:
                if self._stopping:
                    raise ServerSaturated(
                        "server is shutting down",
                        retry_after_s=(
                            self.admission.default_retry_after_s),
                        queue_depth=self.admission.depth,
                        capacity=self.admission.capacity,
                        bucket=decision.bucket, level="shutdown",
                    )
                if request_id is not None:
                    rid = request_id
                    if rid in self._records:
                        raise ValueError(
                            f"request_id {rid!r} already exists")
                else:
                    # server-owned counter, skipping past any id a
                    # client chose explicitly — an auto id must never
                    # collide
                    while True:
                        self._rid_seq += 1
                        rid = f"req{self._rid_seq:05d}"
                        if rid not in self._records:
                            break
                self._accepted += 1
                # graftpack padding provenance, decided AT ADMISSION and
                # journaled: effective rows (post-shed) padded up to the
                # bucket's pow2 row count. Computed here, not at run
                # time, so replay pads identically regardless of the
                # replaying server's pack setting.
                bucket_rows = pad_rows = 0
                if self.pack is not None and packable(opts):
                    eff_rows = (
                        decision.sample_rows
                        if decision.sample_rows is not None
                        and decision.sample_rows < X.shape[0]
                        else X.shape[0])
                    bucket_rows = int(decision.bucket[0])
                    pad_rows = max(bucket_rows - int(eff_rows), 0)
                req = SearchRequest(
                    request_id=rid, X=X, y=y,
                    niterations=int(niterations), seed=int(seed),
                    options_kwargs=opts, priority=decision.priority,
                    deadline_s=deadline_s,
                    sample_rows=decision.sample_rows,
                    bucket=decision.bucket, index=self._accepted,
                    bucket_rows=bucket_rows, pad_rows=pad_rows,
                    pulse_trace=bool(pulse_trace),
                    # graftledger root span: minted from request content
                    # (never the root path), journaled with the submit
                    # record — replay and cross-root A/B runs agree on
                    # every id
                    trace=mint_trace(rid, seed=int(seed),
                                     niterations=int(niterations)),
                )
                # reserve the id (collision checks see it) but do NOT
                # enqueue yet: no worker may journal a dependent
                # "start" before the submit record is durable
                rec = _RequestRecord(req)
                self._records[rid] = rec
        except BaseException:
            self.admission.release(decision.bucket)
            raise
        # the heavy part — base64-encoding the dataset + an fsync'd
        # append — runs OUTSIDE the server lock (the journal has its
        # own), so one client's submit I/O cannot stall poll/cancel or
        # the workers' queue transitions
        try:
            self.journal.append("submit", rid, req.to_detail())
        except OSError:
            with self._lock:
                self._records.pop(rid, None)
                self.admission.release(decision.bucket)
                # _accepted is NOT rolled back: a concurrent submit may
                # already hold the next index — a gap in the accepted
                # numbering is harmless, a duplicate is not
            raise
        # audit "accept" BEFORE the publish step: once the request is
        # on the heap a worker may log "start" immediately, and the
        # per-request view's lifecycle ordering (accept -> start) must
        # hold in the stream. Still outside the server lock.
        self.log.trace_of[rid] = req.trace
        self.log.serve(
            "accept", rid, trace=req.trace, bucket=list(decision.bucket),
            priority=decision.priority,
            sample_rows=decision.sample_rows,
            level=decision.level, queue_depth=self.admission.depth,
            memory=decision.memory,
            # graftpack padding provenance: bucket_rows=0 means the
            # unpacked path; `report summarize_requests` audits these
            bucket_rows=req.bucket_rows, pad_rows=req.pad_rows,
        )
        with self._lock:
            rec.journaled = True
            cancelled = rec.cancel_event.is_set()
            if cancelled:
                # a cancel arrived while the record was being journaled
                # (deferred by cancel() so the journal stays in order):
                # finalize it here instead of enqueueing
                rec.state = "cancelled"
                rec.finished_t = time.time()
                self.admission.release(decision.bucket)
            else:
                self._qseq += 1
                heapq.heappush(self._queue,
                               (req.priority, self._qseq, rid))
                self._cond.notify_all()
        if cancelled:
            self._journal_cancel(rec, where="queued")
        return rid

    def poll(self, request_id: str) -> Dict[str, Any]:
        """Status snapshot of one request (state, result when done)."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                raise KeyError(f"unknown request_id {request_id!r}")
            return rec.snapshot()

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Cancel a queued or running request. Returns False when the
        request already reached a terminal state."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                raise KeyError(f"unknown request_id {request_id!r}")
            if rec.state in _TERMINAL:
                return False
            rec.cancel(reason)
            # finalize a queued cancel only once its submit record is
            # durable — a cancel racing submit's unlocked journal write
            # would otherwise land its record FIRST, and replay drops
            # lifecycle records that precede their submit (the request
            # would resurrect). Pre-journal cancels are completed by
            # submit's publish step.
            finalize = rec.state == "queued" and rec.journaled
            if finalize:
                # remove from the heap lazily (worker skips cancelled)
                rec.state = "cancelled"
                rec.finished_t = time.time()
                self.admission.release(rec.request.bucket)
        if finalize:
            self._journal_cancel(rec, where="queued")
        return True

    def requests(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.snapshot() for r in self._records.values()]

    def metrics_text(self) -> str:
        """The /metrics exposition body (Prometheus text format);
        docs/OBSERVABILITY.md has the metric-name table. Renders fresh
        from the server's own counters — no sampling thread."""
        from ..pulse import PromText

        p = PromText("graftserve")
        p.gauge("queue_depth", self.admission.depth,
                "Requests queued or running")
        p.gauge("queue_capacity", self.admission.capacity,
                "Admission queue capacity")
        p.gauge("queue_utilization", self.admission.utilization(),
                "queue_depth / queue_capacity")
        for bucket, n in sorted(self.admission.in_flight_by_bucket().items()):
            p.gauge("bucket_in_flight", n,
                    "Queued+running requests per admission shape bucket",
                    labels={"bucket": "x".join(str(b) for b in bucket)})
        stats = self.cache.stats()
        p.gauge("cache_entries", stats["entries"], "Cached engines")
        p.counter("cache_hits_total", stats["hits"],
                  "Executable-cache hits")
        p.counter("cache_misses_total", stats["misses"],
                  "Executable-cache misses")
        p.gauge("cache_hit_rate", stats["hit_rate"] or 0.0,
                "hits / (hits + misses); 0 before any lookup")
        if self.pack is not None:
            with self._lock:
                ps = dict(self._pack_stats)
            p.counter("pack_launches_total", ps["launches"],
                      "Packed cohort launches")
            p.counter("pack_multi_tenant_launches_total",
                      ps["multi_tenant_launches"],
                      "Cohort launches holding more than one tenant")
            p.counter("pack_tenants_total", ps["tenants"],
                      "Tenant searches run inside packed cohorts")
            p.gauge("pack_peak_tenants", ps["peak_tenants"],
                    "Largest tenant count of any single launch")
            p.gauge("pack_mean_occupancy",
                    (ps["occupancy_sum"] / ps["occupancy_n"]
                     if ps["occupancy_n"] else 0.0),
                    "Mean per-round tenant occupancy across launches")
        with self._lock:
            by_state: Dict[str, int] = {}
            running = []
            for r in self._records.values():
                by_state[r.state] = by_state.get(r.state, 0) + 1
                if r.state == "running" and r.progress is not None:
                    running.append((r.request, dict(r.progress)))
        for state in ("queued", "running", "done", "failed", "cancelled"):
            p.gauge("requests", by_state.get(state, 0),
                    "Requests by lifecycle state",
                    labels={"state": state})
        # per-RUNNING-request progress only: terminal requests would
        # grow the label cardinality without bound over a server's life
        for req, prog in running:
            labels = {"request": req.request_id}
            p.gauge("request_iteration", prog["iteration"],
                    "Completed iterations of a running request", labels)
            p.gauge("request_iterations_total", req.niterations,
                    "Iteration target of a running request", labels)
            p.gauge("request_evals", prog["num_evals"],
                    "Cumulative expression evaluations", labels)
            p.gauge("request_evals_per_sec", prog["evals_per_sec"],
                    "Cumulative evaluation rate", labels)
        # graftledger per-tenant cost attribution: device/host/compile
        # seconds, evals, checkpoint bytes, and the log-bucketed
        # iteration-latency histogram per request, from the rollup the
        # completion path maintains (ledger/rollup.py)
        from .metrics import render_gauge_metrics, render_ledger_metrics

        render_ledger_metrics(p, load_rollup(self.root))
        # graftgauge capacity section: dispatch-latency histogram, peak
        # live bytes, per-entry compiled-program footprints
        render_gauge_metrics(p)
        return p.render()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "SearchServer":
        """Start the worker pool (and the process-global preemption
        guard when called from the main thread — a SIGTERM then drains
        gracefully: in-flight searches stop at the next iteration
        boundary with their emergency checkpoints, and the journal
        carries everything else)."""
        from ..shield.signals import PreemptionGuard

        with self._lock:
            # a prior stop() that timed out may have left finished (or
            # still-draining) workers tracked; only fully-dead threads
            # are pruned — live stragglers block a restart rather than
            # letting worker count exceed the configured pool
            self._threads = [t for t in self._threads if t.is_alive()]
            if self._threads:
                return self
            self._stopping = False
            self._preempting = False
            if self._guard is not None:
                # a SIGTERM-drained pool dies without stop() running:
                # detach the stale guard so the attach below opens a
                # fresh cycle (refcount back to 0 clears the shared
                # preempt flag — otherwise new workers would observe
                # the old signal and exit immediately)
                self._guard.uninstall()
                self._guard = None
            self._guard = PreemptionGuard().install()
            for i in range(max(self.workers, 1)):
                t = threading.Thread(
                    target=self._worker_loop,
                    name=f"graftserve-worker-{i}", daemon=True,
                )
                t.start()
                self._threads.append(t)
        if self.metrics is not None and not self.metrics.running:
            # .running guard: a stop() that timed out keeps the endpoint
            # up, and MetricsServer.start() now refuses a double bind
            self.metrics.start()
        return self

    def stop(self, drain: bool = False, timeout: Optional[float] = None
             ) -> None:
        """Stop the server. ``drain=True`` finishes everything queued
        first; ``drain=False`` preempts in-flight searches at their next
        iteration boundary (their checkpoints + the journal let a later
        server finish them)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if drain:
            self.wait_idle(timeout=timeout)
        with self._lock:
            self._stopping = True
            if not drain:
                self._preempting = True
                for rec in self._records.values():
                    if rec.state == "running":
                        rec.cancel("preempted")
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=(
                None if deadline is None
                else max(deadline - time.monotonic(), 0.1)))
        alive = [t for t in self._threads if t.is_alive()]
        if alive:
            # the stop timeout elapsed mid-dispatch: the workers WILL
            # exit at their next iteration boundary (stop flags are
            # set). Keep them tracked (start() must not over-spawn),
            # keep _preempting and the guard live (their searches still
            # need the stop signal), and audit the leak.
            self._threads = alive
            self.log.fault("stop_timeout", workers=len(alive))
            return
        self._threads = []
        self._preempting = False
        if self._guard is not None:
            self._guard.uninstall()
            self._guard = None
        if self.metrics is not None:
            # only on a FULL stop: a stop_timeout return above keeps the
            # endpoint up — the server is still effectively running
            self.metrics.stop()
        self.log.serve("shutdown", "", drained=drain)

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until no request is queued or running."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                # record states, NOT the heap: a queued cancel is
                # removed lazily (the tuple stays on the heap until a
                # worker pops and skips it), and a stale entry must not
                # make an idle server look busy — stop(drain=True)
                # would hang forever with workers=0
                busy = any(
                    r.state in ("queued", "running")
                    for r in self._records.values())
                if not busy:
                    return True
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=(
                    0.5 if remaining is None else min(remaining, 0.5)))

    def wait(self, request_id: str, timeout: Optional[float] = None
             ) -> Dict[str, Any]:
        """Block until one request reaches a terminal state."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            snap = self.poll(request_id)
            if snap["state"] in _TERMINAL:
                return snap
            if deadline is not None and time.monotonic() > deadline:
                return snap
            time.sleep(0.05)

    def __enter__(self) -> "SearchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=exc == (None, None, None))

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------
    def _preempt_requested(self) -> bool:
        return self._preempting or (
            self._guard is not None and self._guard.requested)

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping and (
                        not self._preempt_requested()):
                    self._cond.wait(timeout=0.2)
                if self._stopping or self._preempt_requested():
                    self._cond.notify_all()
                    return
                _, _, rid = heapq.heappop(self._queue)
                rec = self._records.get(rid)
                if rec is None or rec.state != "queued":
                    continue  # lazily-removed cancellation
                rec.state = "running"
                rec.started_t = time.time()
            try:
                if self.pack is not None and rec.request.bucket_rows > 0:
                    # packed path: this worker becomes the cohort
                    # manager — it claims co-queued same-group requests
                    # and launches them together (one shared compiled
                    # program, lockstep iterations)
                    self._run_packed_cohort(rec)
                else:
                    self._run_request(rec)
            except Exception as e:  # noqa: BLE001 - fail the request
                self._finish(rec, "failed",
                             error=f"{type(e).__name__}: {e}")
            with self._cond:
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # graftpack: packed-cohort manager (docs/SERVING.md "Packed tenancy")
    # ------------------------------------------------------------------
    def _claim_pack_peers(self, gkey: str,
                          budget: int) -> List[_RequestRecord]:
        """Claim up to ``budget`` queued requests of the same pack group
        (same bucket + same options kwargs). Claimed records flip to
        "running" under the lock; their heap tuples are removed lazily,
        exactly like a queued cancel (the worker pop skips non-queued
        states)."""
        claimed: List[_RequestRecord] = []
        with self._lock:
            for _, _, rid in sorted(self._queue):
                if len(claimed) >= budget:
                    break
                r = self._records.get(rid)
                if r is None or r.state != "queued":
                    continue
                rq = r.request
                if rq.bucket_rows <= 0:
                    continue
                if pack_group_key(rq.bucket, rq.options_kwargs) != gkey:
                    continue
                r.state = "running"
                r.started_t = time.time()
                claimed.append(r)
        return claimed

    def _run_pack_tenant(self, rec: _RequestRecord,
                         cohort: PackedCohort, slot: int) -> None:
        """One tenant of a packed launch: the unchanged per-request run
        (journal start/done, checkpoints, ledger, telemetry all intact),
        plus cohort membership for the iteration barrier. Always peels
        the slot off, whatever the outcome — a leaked slot would stall
        the peers' barrier until its timeout."""
        try:
            try:
                self._run_request(rec, cohort=cohort, slot=slot)
            except Exception as e:  # noqa: BLE001 - fail the request
                self._finish(rec, "failed",
                             error=f"{type(e).__name__}: {e}")
        finally:
            cohort.leave(slot)
            self.log.serve(
                "pack_peel", rec.request.request_id,
                trace=rec.request.trace, state=rec.state,
                iterations=(rec.progress or {}).get("iteration"),
            )
            with self._cond:
                self._cond.notify_all()

    def _run_packed_cohort(self, lead: _RequestRecord) -> None:
        """Cohort manager, run on the worker thread that popped the
        lead request: coalesce the burst, launch every tenant on its
        own thread, then admit late joiners at iteration boundaries
        until the group drains. Tenant threads are owned by this
        manager (the worker does not return until they exit), so
        stop()/preemption semantics are unchanged — each tenant's
        stop_hook fires exactly as on the unpacked path."""
        req = lead.request
        gkey = pack_group_key(req.bucket, req.options_kwargs)
        # graftgauge bin capacity: the per-bucket byte prediction from
        # the headroom model bounds how many tenants one launch holds.
        # Advisory contract carries over: no data -> policy cap, and
        # the floor is always the lead tenant.
        advice = None
        if self.admission.headroom is not None:
            try:
                advice = self.admission.headroom.advise(
                    bucket=req.bucket,
                    limit_bytes=self.admission.memory_limit_bytes)
            except Exception:  # noqa: BLE001 - advisory is best-effort
                advice = None
        cap = slot_cap(self.pack, advice)
        cohort = PackedCohort(
            gkey, slot_cap=cap,
            barrier_timeout_s=self.pack.barrier_timeout_s)
        # coalesce window (no locks held): let the rest of a burst land
        # before the first launch so it starts at high occupancy
        if self.pack.coalesce_window_s > 0 and not self._stopping:
            time.sleep(self.pack.coalesce_window_s)
        members = [(lead, cohort.join(req.request_id))]
        for r in self._claim_pack_peers(gkey, cap - 1):
            slot = cohort.join(r.request.request_id)
            if slot is None:  # cannot happen while only we add; belt
                self._requeue_claimed(r)
                continue
            members.append((r, slot))
        launch_t = time.time()
        self.log.serve(
            "pack_launch", req.request_id, trace=req.trace,
            bucket=list(req.bucket), slot_cap=cap,
            tenants=[r.request.request_id for r, _ in members],
            coalesce_wait_s={
                r.request.request_id: round(launch_t - r.submitted_t, 6)
                for r, _ in members},
            memory=advice,
        )
        with self._lock:
            st = self._pack_stats
            st["launches"] += 1
            st["tenants"] += len(members)
            if len(members) > 1:
                st["multi_tenant_launches"] += 1
            st["peak_tenants"] = max(st["peak_tenants"], len(members))
        threads: List[threading.Thread] = []

        def spawn(r: _RequestRecord, slot: int) -> None:
            t = threading.Thread(
                target=self._run_pack_tenant, args=(r, cohort, slot),
                name=f"graftpack-{r.request.request_id}", daemon=True)
            t.start()
            threads.append(t)

        with self._lock:
            warm = gkey in self._pack_warm
        spawn(*members[0])
        if not warm and len(members) > 1:
            # cold group: the lead's FIRST iteration traces+compiles
            # the shared device programs; peers spawned now would each
            # re-trace the same programs concurrently (jit dedupes
            # executables, not traces in flight) and the pack's
            # one-compile win would become N compiles. Hold the peers
            # until the lead's first iteration boundary — the probe
            # sets rec.progress BEFORE arriving at the barrier, and
            # the lead then simply waits at that barrier until the
            # warmed peers catch up (scheduling-only, always safe).
            lead_t = threads[0]
            while (lead_t.is_alive() and lead.progress is None
                   and not self._stopping
                   and not self._preempt_requested()):
                lead_t.join(timeout=self.pack.join_poll_s)
            if lead.progress is not None:
                with self._lock:
                    self._pack_warm.add(gkey)
        else:
            with self._lock:
                self._pack_warm.add(gkey)
        for r, slot in members[1:]:
            spawn(r, slot)
        # late-join loop: free slots (initial headroom or peeled
        # tenants) admit queued same-group requests at iteration
        # boundaries while the cohort is still running
        while any(t.is_alive() for t in threads):
            if not self._stopping and not self._preempt_requested():
                budget = cap - cohort.size()
                if budget > 0:
                    for r in self._claim_pack_peers(gkey, budget):
                        slot = cohort.join(r.request.request_id)
                        if slot is None:
                            self._requeue_claimed(r)
                            continue
                        self.log.serve(
                            "pack_join", r.request.request_id,
                            trace=r.request.trace,
                            bucket=list(r.request.bucket),
                            coalesce_wait_s=round(
                                time.time() - r.submitted_t, 6),
                        )
                        with self._lock:
                            self._pack_stats["tenants"] += 1
                        spawn(r, slot)
            for t in threads:
                if t.is_alive():
                    t.join(timeout=self.pack.join_poll_s)
                    break
        occ = cohort.occupancy()
        self.log.serve("pack_done", req.request_id, trace=req.trace,
                       bucket=list(req.bucket), **occ)
        with self._lock:
            if occ["occupancy"] is not None:
                self._pack_stats["occupancy_sum"] += occ["occupancy"]
                self._pack_stats["occupancy_n"] += 1

    def _requeue_claimed(self, rec: _RequestRecord) -> None:
        """Put a claimed-but-not-launched record back on the queue."""
        with self._cond:
            if rec.state == "running":
                rec.state = "queued"
                self._qseq += 1
                heapq.heappush(
                    self._queue,
                    (rec.request.priority, self._qseq,
                     rec.request.request_id))
                self._cond.notify_all()

    # ------------------------------------------------------------------
    def _on_cache_event(self, kind: str, detail: Dict[str, Any]) -> None:
        rid = getattr(self._cache_tls, "request_id", "") or ""
        rec = self._records.get(rid)
        self.log.serve(kind, rid,
                       trace=rec.request.trace if rec else None, **detail)

    def _request_dir(self, rid: str) -> str:
        return os.path.join(self.root, "requests", rid)

    def _journal_cancel(self, rec: _RequestRecord, *, where: str) -> None:
        """Durably record + audit a queued-cancel. Caller must NOT hold
        the server lock — the append fsyncs."""
        rid = rec.request.request_id
        try:
            self.journal.append(
                "cancel", rid, {"reason": rec.cancel_reason or "cancelled"})
        except OSError as e:
            self.log.fault("journal_write_failed", request_id=rid,
                           event="cancel", error=str(e)[:200])
        self.log.serve("cancel", rid, trace=rec.request.trace,
                       reason=rec.cancel_reason, where=where)

    def _finish(self, rec: _RequestRecord, state: str, *, result=None,
                error=None, journal_event: Optional[str] = None) -> None:
        with self._lock:
            rec.state = state
            rec.result = result
            rec.error = error
            rec.finished_t = time.time()
            self.admission.release(rec.request.bucket)
            if rec.started_t is not None:
                self.admission.observe_service_time(
                    rec.finished_t - rec.started_t)
        # journal + audit OUTSIDE the server lock (the journal has its
        # own): the fsync'd terminal record on a contended disk must
        # not stall poll/submit/cancel or the other workers
        try:
            if journal_event:
                detail: Dict[str, Any] = {}
                if result is not None:
                    detail["result"] = result
                if error is not None:
                    detail["error"] = str(error)[:500]
                if state == "cancelled":
                    detail["reason"] = rec.cancel_reason or "cancelled"
                self.journal.append(
                    journal_event, rec.request.request_id, detail)
            elif state == "failed":
                self.journal.append(
                    "failed", rec.request.request_id,
                    {"error": str(error)[:500]})
        except OSError as e:
            # a full/failing disk must not leak the admission slot or
            # kill the worker thread: the in-memory state is final
            # either way, and a restart simply re-runs the request
            # (its terminal record is missing) — the deterministic
            # search makes that safe, just wasteful
            self.log.fault(
                "journal_write_failed",
                request_id=rec.request.request_id,
                event=journal_event or state, error=str(e)[:200],
            )
        self.log.serve(
            {"cancelled": "cancel"}.get(state, state),
            rec.request.request_id, trace=rec.request.trace,
            error=error, reason=rec.cancel_reason,
        )
        # graftledger rollup: rebuild the per-tenant view from the
        # per-request ledger files on every completion. A full rewrite,
        # so a crash between completions loses nothing — the files are
        # the source of truth. /metrics and `bench load` read it.
        write_rollup(self.root)

    def _run_request(self, rec: _RequestRecord,
                     cohort: Optional[PackedCohort] = None,
                     slot: Optional[int] = None) -> None:
        from ..api.search import RuntimeOptions, equation_search
        from ..core.options import Options

        req = rec.request
        rid = req.request_id
        try:
            self.journal.append("start", rid, {"resumed": rec.resumed})
        except OSError as e:
            # same policy as _finish: a transient disk failure must not
            # terminally fail a durably-accepted request. Cost of a
            # missing start record: a restart loses the deadline anchor
            # and the resumed flag — the search itself still resumes
            # from its checkpoints.
            self.log.fault("journal_write_failed", request_id=rid,
                           event="start", error=str(e)[:200])
        self.log.serve("start", rid, trace=req.trace, resumed=rec.resumed)
        if self._injector is not None:
            self._injector.on_request_start(req.index, rid)

        options = Options(
            output_directory=self._request_dir(rid),
            save_to_file=True, telemetry=True, interactive_quit=False,
            shield=True, seed=req.seed, **req.options_kwargs,
        )
        X, y = req.X, req.y
        if req.sample_rows is not None and req.sample_rows < X.shape[0]:
            # overload shed, journaled at admission. Deterministic
            # STRIDED sample, not a head slice: row-ordered datasets
            # (time series, swept parameters) keep full domain
            # coverage, and the selection depends only on
            # (n, sample_rows) so a crash-replay re-runs the identical
            # degraded search
            sel = (np.arange(req.sample_rows) * X.shape[0]
                   ) // req.sample_rows
            X, y = X[sel], y[sel]
        # graftpack shape-bucket padding, driven by the JOURNALED
        # provenance alone (never by cohort membership or the server's
        # current pack setting): zero-weight cyclic-replica rows up to
        # the bucket's pow2 row count, provably inert (pack/padding.py)
        # — so near-miss shapes share one trace/compile, and a replayed
        # request pads bit-identically
        weights = None
        if req.pad_rows > 0 and req.bucket_rows > X.shape[0]:
            X, y, weights = pad_to_bucket(X, y, rows=req.bucket_rows)

        # deadline budget anchored at the FIRST start attempt — wall
        # clock, because it must survive preemption and process
        # restarts (recovered from the journal's start records): a
        # resumed request spends its REMAINING budget, not a fresh one
        if rec.first_started_wall is None:
            rec.first_started_wall = time.time()
        elapsed0 = time.time() - rec.first_started_wall
        started_m = time.monotonic()

        def stop_hook() -> Optional[str]:
            if rec.cancel_event.is_set():
                return rec.cancel_reason or "cancelled"
            if self._preempt_requested():
                rec.cancel("preempted")
                return "preempted"
            if req.deadline_s is not None and (
                    elapsed0 + (time.monotonic() - started_m)
                    > req.deadline_s):
                rec.cancel("deadline")
                return "deadline"
            return None

        # run_id = request id: deterministic across restarts (the same
        # run directory resumes) AND attributable — every event in the
        # request's graftscope stream carries it, so concatenated
        # multi-tenant streams group correctly in `telemetry report`.
        ropt = RuntimeOptions(
            niterations=req.niterations, run_id=rid, seed=req.seed,
            verbosity=0, checkpoint_every_n=1, return_state=True,
            engine_cache=_RequestCacheView(self.cache, req.bucket),
            stop_hook=stop_hook,
            logger=_InjectorProbe(self, rec, cohort, slot), log_every_n=1,
            pulse_trace_on=bool(req.pulse_trace),
            # graftledger: the search runs under a child span of the
            # journaled request root — its hub stamps the same trace_id
            # on every event of the request's own graftscope stream
            trace=req.trace,
        )
        # Hang backstop: the soft deadline above stops at an iteration
        # boundary; a dispatch that never reaches one trips the
        # watchdog, which cancels the request and audits the hang (it
        # cannot interrupt the blocked XLA call — docs/ROBUSTNESS.md).
        watchdog = None
        if req.deadline_s is not None:
            def on_hang(dump: str) -> None:
                rec.cancel("deadline")
                self.log.fault("request_hang", request_id=rid,
                               dump_head=dump[:500])
            watchdog = Watchdog(on_timeout=on_hang)
        try:
            self._cache_tls.request_id = rid
            import contextlib

            phase = (
                watchdog.phase(
                    "serve_request",
                    max(req.deadline_s - elapsed0, 0.0)
                    + self.hang_grace_s)
                if watchdog is not None else contextlib.nullcontext()
            )
            with phase:
                # resume="auto": first run finds nothing and starts
                # fresh; a journal-replayed run finds the request's
                # rolling checkpoints and continues bit-identically.
                state, hof = equation_search(
                    X, y, weights=weights, options=options,
                    resume="auto", runtime_options=ropt,
                )
        except WatchdogTimeout:
            self._finish(rec, "cancelled", journal_event="cancel")
            return
        finally:
            self._cache_tls.request_id = None
            if watchdog is not None:
                watchdog.stop()

        iters = int(state.iterations_done)
        # a client cancel (or deadline) landing in the same window as a
        # preemption is STILL a terminal cancellation: the non-preempt
        # reason wins, else the requeue path below would resurrect the
        # request and a cancelled search would later complete as "done"
        user_stop = (rec.cancel_event.is_set()
                     and rec.cancel_reason not in (None, "preempted"))
        preempted = not user_stop and (
            rec.cancel_reason == "preempted" or self._preempt_requested())
        if (rec.cancel_event.is_set() and not preempted
                and iters < req.niterations):
            # any non-preempt cancel reason (including custom reasons
            # passed to cancel()) is a terminal cancellation — a
            # partial result must never be journaled as "done"
            self._finish(rec, "cancelled", journal_event="cancel")
            return
        if iters < req.niterations and preempted:
            # interrupted mid-flight: journal deliberately left at
            # "start". Re-queue IN PROCESS (keeping the admission slot
            # — the request never left the system) so a start() on this
            # same instance resumes it; a fresh server over the root
            # replays the journal instead.
            with self._cond:
                if rec.cancel_reason not in (None, "preempted"):
                    # a terminal cancel raced the requeue decision
                    # (e.g. client cancel during the preemption window)
                    # — it must not be wiped by the state reset below
                    terminal = True
                else:
                    terminal = False
                    rec.cancel_event.clear()
                    rec.cancel_reason = None
                    rec.resumed = True
                    rec.state = "queued"
                    self._qseq += 1
                    heapq.heappush(
                        self._queue, (req.priority, self._qseq, rid))
            if terminal:
                self._finish(rec, "cancelled", journal_event="cancel")
            else:
                self.log.serve("interrupted", rid, trace=req.trace,
                               iterations=iters)
            return
        hofs = hof if isinstance(hof, list) else [hof]
        result = {
            "fingerprint": result_fingerprint(state),
            "iterations": iters,
            "num_evals": float(state.num_evals),
            "equations": [
                {
                    "equation": e.equation_string(),
                    "loss": float(e.loss),
                    "complexity": int(e.complexity),
                }
                for h in hofs for e in h.pareto_frontier()
            ],
        }
        self._finish(rec, "done", result=result, journal_event="done")
