"""Admission control: bounded, shape-bucketed request classes with
structured backpressure.

Every request is classified into a **shape bucket** — dataset rows
rounded up to a power of two (floored at ``MIN_ROW_BUCKET``) plus the
exact feature/output counts. Buckets serve two purposes:

1. **admission classes**: the queue is bounded both in total and per
   bucket, so a storm of one shape cannot starve every other class of
   its share of the queue;
2. **executable-cache accounting**: requests in one bucket are the ones
   that can share a compiled engine (serve/cache.py), and the
   hit/miss counters graftscope reports are grouped by bucket.

Saturation never blocks and never hangs: ``decide`` either admits
(possibly degraded by the :class:`~..shield.degrade.OverloadLadder`) or
raises :class:`ServerSaturated`, a structured error carrying the queue
depth, the bucket, and a retry-after hint derived from observed request
service times — the reject-with-retry-after contract in docs/SERVING.md.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple

from ..shield.degrade import OverloadLadder

__all__ = [
    "MIN_ROW_BUCKET",
    "ServerSaturated",
    "AdmissionDecision",
    "AdmissionController",
    "shape_bucket",
]

MIN_ROW_BUCKET = 256


def shape_bucket(n_rows: int, nfeatures: int, nout: int = 1
                 ) -> Tuple[int, int, int]:
    """(row-bucket, nfeatures, nout): rows rounded up to a power of two,
    never below ``MIN_ROW_BUCKET`` — the granularity at which compiled
    executables are shareable across requests."""
    b = MIN_ROW_BUCKET
    while b < int(n_rows):
        b *= 2
    return (b, int(nfeatures), int(nout))


class ServerSaturated(RuntimeError):
    """Structured backpressure: the queue (total or this request's shape
    class) is full. Clients should back off for ``retry_after_s`` and
    resubmit; nothing was journaled or enqueued."""

    def __init__(self, message: str, *, retry_after_s: float,
                 queue_depth: int, capacity: int,
                 bucket: Tuple[int, int, int],
                 level: str = "reject") -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.queue_depth = int(queue_depth)
        self.capacity = int(capacity)
        self.bucket = tuple(bucket)
        self.level = level

    def to_dict(self) -> dict:
        return {
            "error": "server_saturated",
            "message": str(self),
            "retry_after_s": self.retry_after_s,
            "queue_depth": self.queue_depth,
            "capacity": self.capacity,
            "bucket": list(self.bucket),
            "level": self.level,
        }


@dataclasses.dataclass
class AdmissionDecision:
    admitted: bool
    bucket: Tuple[int, int, int]
    priority: int
    sample_rows: Optional[int]
    level: str
    utilization: float
    # graftgauge memory advisory (capacity.HeadroomModel.advise):
    # predicted program bytes vs the device byte budget for this shape
    # bucket, or None when no headroom model is attached / the ledger
    # has no history for the shape. ADVISORY ONLY — admission never
    # rejects on it (a floor estimate's false "no" would be an outage);
    # it is recorded on the accept audit event for operators to alert
    # on.
    memory: Optional[dict] = None


class AdmissionController:
    """Bounded admission with shape-bucketed classes + overload ladder.

    ``capacity`` bounds queued-plus-running requests in total;
    ``bucket_capacity`` (default: the full capacity, i.e. no per-class
    penalty) optionally bounds any single shape class so one shape's
    storm cannot monopolize the queue. Thread-safe; the server calls
    ``admit``/``release`` around a request's queued+running lifetime.
    """

    def __init__(
        self,
        capacity: int = 8,
        *,
        bucket_capacity: Optional[int] = None,
        ladder: Optional[OverloadLadder] = None,
        default_retry_after_s: float = 5.0,
        headroom=None,
        memory_limit_bytes: Optional[int] = None,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.bucket_capacity = int(
            bucket_capacity if bucket_capacity is not None else capacity
        )
        self.ladder = ladder or OverloadLadder()
        self.default_retry_after_s = float(default_retry_after_s)
        # graftgauge memory-aware admission (docs/SERVING.md): a
        # capacity.HeadroomModel whose advisory is attached to every
        # admitted decision; memory_limit_bytes overrides the backend
        # allocator limit (the only source on CPU)
        self.headroom = headroom
        self.memory_limit_bytes = memory_limit_bytes
        self._lock = threading.Lock()
        self._in_flight: Dict[Tuple[int, int, int], int] = {}
        self._total = 0
        # EWMA of request service time → retry-after hint
        self._avg_service_s: Optional[float] = None

    # ------------------------------------------------------------------
    @property
    def depth(self) -> int:
        return self._total

    def utilization(self) -> float:
        return self._total / self.capacity

    def in_flight_by_bucket(self) -> Dict[Tuple[int, int, int], int]:
        """Snapshot of per-bucket queued+running counts (the /metrics
        per-bucket admission gauges read this)."""
        with self._lock:
            return dict(self._in_flight)

    def observe_service_time(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        self._avg_service_s = (
            s if self._avg_service_s is None
            else 0.8 * self._avg_service_s + 0.2 * s
        )

    def retry_after_s(self, queue_depth: int) -> float:
        """Drain-time estimate: how long until a queue slot frees."""
        per = self._avg_service_s
        if per is None:
            return self.default_retry_after_s
        return max(per * max(queue_depth, 1) / max(self.capacity, 1), per)

    # ------------------------------------------------------------------
    def admit(self, *, n_rows: int, nfeatures: int, nout: int = 1,
              priority: int = 0, request_id: str = ""
              ) -> AdmissionDecision:
        """Admit (and count) one request, or raise ServerSaturated."""
        bucket = shape_bucket(n_rows, nfeatures, nout)
        # memory advisory BEFORE taking the admission lock: advise()
        # reads the footprint ledger (its own lock) and the backend
        # allocator — neither may nest inside self._lock
        memory = None
        if self.headroom is not None:
            try:
                memory = self.headroom.advise(
                    bucket=bucket, limit_bytes=self.memory_limit_bytes)
            except Exception:  # noqa: BLE001 - advisory is best-effort
                memory = None
        with self._lock:
            util = self._total / self.capacity
            bucket_depth = self._in_flight.get(bucket, 0)
            if self._total >= self.capacity or (
                    bucket_depth >= self.bucket_capacity):
                scope = ("queue" if self._total >= self.capacity
                         else f"shape class {bucket}")
                self.ladder.rejects_total += 1
                raise ServerSaturated(
                    f"server saturated: {scope} is full "
                    f"({self._total}/{self.capacity} total, "
                    f"{bucket_depth}/{self.bucket_capacity} in bucket)",
                    retry_after_s=self.retry_after_s(self._total),
                    queue_depth=self._total, capacity=self.capacity,
                    bucket=bucket,
                )
            shed = self.ladder.apply(
                util, n_rows=n_rows, priority=priority,
                request_id=request_id)
            if not shed["admit"]:
                raise ServerSaturated(
                    f"server overloaded (utilization {util:.0%} >= "
                    f"reject threshold)",
                    retry_after_s=self.retry_after_s(self._total),
                    queue_depth=self._total, capacity=self.capacity,
                    bucket=bucket, level=shed["level"],
                )
            self._in_flight[bucket] = bucket_depth + 1
            self._total += 1
            return AdmissionDecision(
                admitted=True, bucket=bucket,
                priority=shed["priority"],
                sample_rows=shed["sample_rows"],
                level=shed["level"], utilization=util,
                memory=memory,
            )

    def readmit(self, bucket: Tuple[int, int, int]) -> None:
        """Count a journal-replayed request WITHOUT bounds or ladder:
        an accepted request survives a restart unconditionally — the
        admission decision was already made (and journaled) by the
        process that accepted it. Recovery may transiently exceed
        capacity; new submissions then see a saturated queue until the
        backlog drains, which is the correct backpressure."""
        bucket = tuple(bucket)
        with self._lock:
            self._in_flight[bucket] = self._in_flight.get(bucket, 0) + 1
            self._total += 1

    def release(self, bucket: Tuple[int, int, int]) -> None:
        """A request left the system (done/failed/cancelled)."""
        bucket = tuple(bucket)
        with self._lock:
            self._total = max(self._total - 1, 0)
            n = self._in_flight.get(bucket, 0)
            if n <= 1:
                self._in_flight.pop(bucket, None)
            else:
                self._in_flight[bucket] = n - 1
