"""Device-mesh placement of the search state: islands × data sharding.

The reference's distributed runtime is a master/worker RPC island model
over Distributed.jl (/root/reference/src/SearchUtils.jl:289-308,
/root/reference/src/Configure.jl). The TPU-native equivalent is a
single-program SPMD design (SURVEY.md §5.8): the island axis of every
population array is sharded over the mesh's ``island`` axis, and the
dataset's row axis is sharded over the ``data`` axis. Cross-island
operations inside the jitted iteration (migration pool all-gather, global
hall-of-fame merge) lower to XLA collectives over ICI; the per-row loss
reduction lowers to a psum over the ``data`` axis. Multi-host scaling uses
the same program via ``jax.distributed.initialize`` — no user-function
shipping is needed because closures compile into the program.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "shard_search_state",
    "shard_device_data",
    "replicated",
]

ISLAND_AXIS = "island"
DATA_AXIS = "data"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    n_island_shards: Optional[int] = None,
    n_data_shards: int = 1,
) -> Mesh:
    """Build an ``(island, data)`` mesh over the given (or all) devices.

    By default all devices go to the island axis — the natural layout for
    evolutionary search, where islands are embarrassingly parallel between
    migrations. Use ``n_data_shards > 1`` for huge datasets where row
    parallelism pays for its collectives.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_island_shards is None:
        n_island_shards = n // n_data_shards
    if n_island_shards * n_data_shards != n:
        raise ValueError(
            f"mesh shape {n_island_shards}x{n_data_shards} != {n} devices"
        )
    dev_array = np.array(devices).reshape(n_island_shards, n_data_shards)
    return Mesh(dev_array, (ISLAND_AXIS, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _plan_for(mesh: Mesh):
    """Wrap an existing mesh in the canonical placement plan
    (mesh/plan.MeshPlan) — one source of truth for per-leaf
    PartitionSpecs, shared with the graftmesh runtime. Imported lazily:
    mesh.plan imports ``make_mesh`` from this module."""
    from ..mesh.plan import MeshPlan

    return MeshPlan(
        mesh=mesh,
        n_island_shards=mesh.shape[ISLAND_AXIS],
        n_data_shards=mesh.shape[DATA_AXIS],
    )


def shard_search_state(state, mesh: Mesh):
    """Place a SearchDeviceState on the mesh: island-major arrays sharded
    on the island axis, global state (HoF, stats, key) replicated.

    The per-island pytrees (pops, birth, ref) all carry the island axis
    as their leading dimension. Delegates to ``mesh.plan.MeshPlan`` —
    the legacy helper and the graftmesh runtime share one placement
    definition.
    """
    return _plan_for(mesh).place_state(state)


def shard_device_data(data, mesh: Mesh):
    """Shard dataset rows over the ``data`` mesh axis (replicate when the
    data axis has a single shard). Delegates to ``mesh.plan.MeshPlan``."""
    return _plan_for(mesh).place_data(data)
