"""Device-mesh placement of the search state: islands × data sharding.

The reference's distributed runtime is a master/worker RPC island model
over Distributed.jl (/root/reference/src/SearchUtils.jl:289-308,
/root/reference/src/Configure.jl). The TPU-native equivalent is a
single-program SPMD design (SURVEY.md §5.8): the island axis of every
population array is sharded over the mesh's ``island`` axis, and the
dataset's row axis is sharded over the ``data`` axis. Cross-island
operations inside the jitted iteration (migration pool all-gather, global
hall-of-fame merge) lower to XLA collectives over ICI; the per-row loss
reduction lowers to a psum over the ``data`` axis. Multi-host scaling uses
the same program via ``jax.distributed.initialize`` — no user-function
shipping is needed because closures compile into the program.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "shard_search_state",
    "shard_device_data",
    "replicated",
]

ISLAND_AXIS = "island"
DATA_AXIS = "data"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    n_island_shards: Optional[int] = None,
    n_data_shards: int = 1,
) -> Mesh:
    """Build an ``(island, data)`` mesh over the given (or all) devices.

    By default all devices go to the island axis — the natural layout for
    evolutionary search, where islands are embarrassingly parallel between
    migrations. Use ``n_data_shards > 1`` for huge datasets where row
    parallelism pays for its collectives.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_island_shards is None:
        n_island_shards = n // n_data_shards
    if n_island_shards * n_data_shards != n:
        raise ValueError(
            f"mesh shape {n_island_shards}x{n_data_shards} != {n} devices"
        )
    dev_array = np.array(devices).reshape(n_island_shards, n_data_shards)
    return Mesh(dev_array, (ISLAND_AXIS, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _shard_leading(mesh: Mesh, x: jax.Array, axis_name: str) -> jax.Array:
    spec = P(axis_name, *([None] * (x.ndim - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def shard_search_state(state, mesh: Mesh):
    """Place a SearchDeviceState on the mesh: island-major arrays sharded
    on the island axis, global state (HoF, stats, key) replicated.

    The per-island pytrees (pops, birth, ref) all carry the island axis as
    their leading dimension.
    """
    island_sharded = jax.tree.map(
        lambda x: _shard_leading(mesh, x, ISLAND_AXIS), (state.pops, state.birth, state.ref)
    )
    pops, birth, ref = island_sharded
    rep = replicated(mesh)
    hof, stats = jax.tree.map(lambda x: jax.device_put(x, rep), (state.hof, state.stats))
    import dataclasses

    return dataclasses.replace(
        state,
        pops=pops,
        birth=birth,
        ref=ref,
        hof=hof,
        stats=stats,
        num_evals=jax.device_put(state.num_evals, rep),
        key=jax.device_put(state.key, rep),
    )


def shard_device_data(data, mesh: Mesh):
    """Shard dataset rows over the ``data`` mesh axis (replicate when the
    data axis has a single shard)."""
    n_data = mesh.shape[DATA_AXIS]

    def place(x, row_axis):
        if x is None:
            return None
        if n_data == 1 or x.ndim == 0:
            return jax.device_put(x, replicated(mesh))
        spec = [None] * x.ndim
        spec[row_axis] = DATA_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    import dataclasses

    return dataclasses.replace(
        data,
        Xt=place(data.Xt, 1),
        y=place(data.y, 0),
        weights=place(data.weights, 0),
        class_idx=place(data.class_idx, 0),
        baseline_loss=jax.device_put(data.baseline_loss, replicated(mesh)),
        use_baseline=jax.device_put(data.use_baseline, replicated(mesh)),
        x_dims=(
            None if data.x_dims is None
            else jax.device_put(data.x_dims, replicated(mesh))
        ),
        y_dims=(
            None if data.y_dims is None
            else jax.device_put(data.y_dims, replicated(mesh))
        ),
    )
