"""Multi-host (DCN) execution: the Distributed.jl-cluster analogue.

The reference scales past one machine with Distributed.jl workers over
TCP — addprocs/Slurm integration, module import on workers, and
user-function shipping (/root/reference/src/Configure.jl:253-360,
docs/src/slurm.md). The TPU-native design needs none of that machinery:
the search is one SPMD program, so multi-host is the *same* jitted
iteration compiled over a larger mesh — islands sharded across all
hosts' devices, migration/HoF collectives riding ICI within a slice and
DCN across slices (SURVEY.md §5.8). Closures compile into the program,
so "shipping user functions" (custom operators, template combiners,
losses) is automatic.

Usage, one call per host before building the search::

    from symbolicregression_jl_tpu.parallel import initialize_multihost
    initialize_multihost()          # TPU pods: auto-detected
    # or explicitly, e.g. on GPU/CPU clusters:
    initialize_multihost(coordinator_address="10.0.0.1:1234",
                         num_processes=4, process_id=rank)

    hof = equation_search(X, y, options=options)   # unchanged

Every host must run the same program with the same data (the dataset is
replicated — or row-sharded over the mesh's data axis with
``RuntimeOptions(n_data_shards=...)``). `jax.devices()` then reports the
global device set and the island mesh spans all of them.
"""

from __future__ import annotations

from typing import Optional

import jax

__all__ = ["initialize_multihost", "is_multihost", "process_index"]


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> None:
    """Join this process to the multi-host run (jax.distributed wrapper).

    Must be the FIRST JAX interaction in the process — any call that
    touches devices (even ``jax.devices()``) initializes the local XLA
    backend and makes joining impossible. On TPU pods all arguments are
    auto-detected from the environment; elsewhere pass the coordinator's
    ``host:port``, the total process count, and this process's rank.
    Idempotent when already initialized; a quiet no-op on a single host
    with no cluster arguments/environment.
    """
    # jax.distributed.is_initialized only exists on newer jax; on older
    # versions the probe is the distributed client handle.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:  # pragma: no cover - newer jax only
        if is_init():
            return
    else:
        from jax._src.distributed import global_state

        if getattr(global_state, "client", None) is not None:
            return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except (ValueError, RuntimeError) as e:
        msg = str(e)
        no_args = coordinator_address is None and num_processes is None
        if no_args and not _cluster_env_present():
            # No cluster arguments and no cluster environment: plain
            # single-host run — nothing to join, whatever the error.
            return
        if "before any JAX" in msg or "backend" in msg.lower():
            # The backend is already up: joining can never succeed now —
            # never swallow this on a real cluster, or a pod run silently
            # degrades into N disconnected single-host searches racing on
            # the same outputs.
            raise RuntimeError(
                "initialize_multihost must run before any other JAX call "
                "in this process (the XLA backend is already initialized). "
                "Call it at the very top of your program."
            ) from e
        raise RuntimeError(
            f"Multi-host initialization failed: {e}. Every host must call "
            "initialize_multihost with the same coordinator_address and "
            "num_processes, and a distinct process_id."
        ) from e


def _cluster_env_present() -> bool:
    """Heuristic for auto-detectable MULTI-host environments (TPU pod /
    Slurm / Open MPI) — the ones jax.distributed.initialize() can join
    without explicit arguments. Single-worker values (e.g.
    ``TPU_WORKER_HOSTNAMES=localhost`` on a lone chip) don't count."""
    import os

    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    ntasks = os.environ.get("SLURM_NTASKS") or os.environ.get(
        "OMPI_COMM_WORLD_SIZE"
    )
    if ntasks and int(ntasks) > 1:
        return True
    return "MEGASCALE_COORDINATOR_ADDRESS" in os.environ


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    """This host's rank (0 = the host that should write outputs/CSVs)."""
    return jax.process_index()
