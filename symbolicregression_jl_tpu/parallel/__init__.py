"""Device-mesh placement and multi-host execution (the reference's
Distributed.jl runtime re-imagined as single-program SPMD, SURVEY.md
§2.4/§5.8)."""

from .mesh import make_mesh, replicated, shard_device_data, shard_search_state
from .multihost import initialize_multihost, is_multihost, process_index

__all__ = [
    "make_mesh",
    "replicated",
    "shard_device_data",
    "shard_search_state",
    "initialize_multihost",
    "is_multihost",
    "process_index",
]
