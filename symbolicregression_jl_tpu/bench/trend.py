"""Trajectory report: fold the committed benchmark history into one view.

The driver archives ``python bench.py``'s JSON line as
``BENCH_r0N.json`` and the multi-chip dryrun as ``MULTICHIP_r0N.json``
every round; gate runs add ``graftbench.result.v1`` files (CI artifact
+ optional ``benchmarks/history/``). ``bench trend`` folds all three
into one trajectory so the perf story is read off one report instead of
hand-diffed artifacts.

A NON-GREEN artifact (nonzero rc, ok=false) is a RED row carrying its
rc — never silently dropped: MULTICHIP_r05's rc=124 is the motivating
example (a red dryrun that round 5's narrative only caught because a
reviewer went digging).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

__all__ = ["build_trend", "format_trend"]

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_of(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _last_json_line(text: str) -> Optional[Dict[str, Any]]:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _bench_row(path: str) -> Dict[str, Any]:
    with open(path) as f:
        art = json.load(f)
    rc = art.get("rc")
    row: Dict[str, Any] = {
        "round": art.get("n", _round_of(path)),
        "file": os.path.basename(path),
        "rc": rc,
        "red": rc not in (0, None),
    }
    parsed = art.get("parsed") or _last_json_line(art.get("tail", ""))
    if parsed and "value" in parsed:
        row["evals_per_sec"] = parsed.get("value")
        row["vs_baseline"] = parsed.get("vs_baseline")
        row["n_devices"] = parsed.get("n_devices")
        row["projected_v5e8"] = parsed.get("projected_v5e8")
    elif not row["red"]:
        # a green rc with an unparseable tail is itself a red flag:
        # the headline number for that round is unrecoverable
        row["red"] = True
        row["note"] = "no parseable bench JSON line in artifact"
    return row


def _multichip_row(path: str) -> Dict[str, Any]:
    with open(path) as f:
        art = json.load(f)
    rc = art.get("rc")
    ok = bool(art.get("ok"))
    row = {
        "round": art.get("n", _round_of(path)),
        "file": os.path.basename(path),
        "rc": rc,
        "ok": ok,
        "skipped": bool(art.get("skipped")),
        # red = the dryrun RAN and failed; a skip is reported but not
        # red (no device to run on is not a regression signal)
        "red": (not ok and not art.get("skipped")),
        "n_devices": art.get("n_devices"),
    }
    if row["red"]:
        row["note"] = f"dryrun failed rc={rc}"
    return row


def _gate_row(path: str) -> Dict[str, Any]:
    from .gate import RESULT_SCHEMA

    with open(path) as f:
        rec = json.load(f)
    row: Dict[str, Any] = {"file": os.path.basename(path)}
    if rec.get("schema") != RESULT_SCHEMA:
        row.update(red=True,
                   note=f"unexpected schema {rec.get('schema')!r}")
        return row
    cells = rec.get("cells", {})
    failures = rec.get("failures", {})
    gate = rec.get("gate") or {}
    gate_failed = bool(gate.get("failed"))
    eps = [c["metrics"].get("evals_per_sec") for c in cells.values()]
    eps = [v for v in eps if isinstance(v, (int, float))]
    # graftpulse: anomaly-detector events ride the gate artifacts via
    # metrics_view's "anomalies" key (older artifacts predate it — 0)
    anomalies = sum(
        int(c["metrics"].get("anomalies") or 0) for c in cells.values())
    # graftgauge: peak live-array bytes per cell rides metrics_view's
    # "peak_live_bytes" (None in pre-gauge artifacts); the trend shows
    # the worst cell — a memory-footprint creep across rounds is a
    # regression signal even while throughput holds
    peaks = [c["metrics"].get("peak_live_bytes") for c in cells.values()]
    peaks = [int(v) for v in peaks if isinstance(v, (int, float))]
    row.update(
        matrix=rec.get("matrix"),
        platform=rec.get("platform"),
        cells=len(cells),
        failed_cells=sorted(failures),
        anomalies=anomalies,
        peak_live_bytes=(max(peaks) if peaks else None),
        # red = cells crashed OR the embedded gate verdict failed OR an
        # otherwise-green run carried anomaly events — "fast but the
        # detector fired" is a regression signal, not a green row
        red=bool(failures) or gate_failed or anomalies > 0,
        mean_evals_per_sec=(
            round(sum(eps) / len(eps), 1) if eps else None),
    )
    notes = []
    if failures:
        notes.append(f"{len(failures)} matrix cell(s) failed")
    if gate_failed:
        n_reg = sum(1 for f in gate.get("findings", [])
                    if f.get("status") in ("regression", "missing_cell",
                                           "schema"))
        notes.append(f"gate FAILED ({n_reg} finding(s))")
    if anomalies and not failures and not gate_failed:
        notes.append(f"{anomalies} anomaly event(s) in a green run")
    if notes:
        row["note"] = "; ".join(notes)
    return row


def _mesh_scaling_row(path: str) -> Dict[str, Any]:
    """One row per committed graftmesh scaling artifact
    (profiling/mesh_scaling.py, schema graftmesh.scaling.v1): the
    MEASURED shards-vs-evals/s curve that replaces the closed-form ICI
    projection in the multi-chip story (docs/SCALING.md)."""
    row: Dict[str, Any] = {"file": os.path.basename(path)}
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        row.update(red=True, note=f"unreadable scaling artifact: {e}")
        return row
    if rec.get("schema") != "graftmesh.scaling.v1":
        row.update(red=True,
                   note=f"unexpected schema {rec.get('schema')!r}")
        return row
    points = rec.get("points") or []
    errs = [p for p in points if "error" in p]
    row.update(
        matrix=rec.get("matrix"),
        virtual_cpu_mesh=bool(rec.get("virtual_cpu_mesh")),
        points=[
            {k: p.get(k) for k in ("shards", "evals_per_sec",
                                   "evals_per_sec_per_shard")}
            for p in points if "error" not in p
        ],
        red=bool(errs) or not points,
    )
    if errs:
        row["note"] = (f"{len(errs)} scaling point(s) failed: "
                       + ", ".join(f"shards={p.get('shards')}"
                                   for p in errs))
    elif not points:
        row["note"] = "no measured points in artifact"
    return row


def build_trend(
    root: str = ".",
    gate_paths: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Machine-readable trajectory: every BENCH/MULTICHIP round row
    (red ones flagged with their rc) + any gate result files found in
    ``<root>/benchmarks/history/`` or passed explicitly + the measured
    graftmesh scaling curve(s) under ``<root>/profiling/``."""
    bench = sorted(
        (_bench_row(p) for p in glob.glob(os.path.join(
            root, "BENCH_r*.json"))),
        key=lambda r: (r.get("round") or 0))
    multichip = sorted(
        (_multichip_row(p) for p in glob.glob(os.path.join(
            root, "MULTICHIP_r*.json"))),
        key=lambda r: (r.get("round") or 0))
    paths = list(gate_paths or [])
    paths += sorted(glob.glob(os.path.join(
        root, "benchmarks", "history", "*.json")))
    gates = [_gate_row(p) for p in paths]
    mesh_scaling = [
        _mesh_scaling_row(p) for p in sorted(glob.glob(os.path.join(
            root, "profiling", "MESH_SCALING*.json")))
    ]

    reds = ([r for r in bench if r["red"]]
            + [r for r in multichip if r["red"]]
            + [r for r in gates if r.get("red")]
            + [r for r in mesh_scaling if r.get("red")])
    greens = [r for r in bench
              if not r["red"] and r.get("evals_per_sec") is not None]
    flat_note = None
    if len(greens) >= 2:
        prev, last = greens[-2], greens[-1]
        if prev["evals_per_sec"]:
            delta = (last["evals_per_sec"] - prev["evals_per_sec"]
                     ) / prev["evals_per_sec"]
            if abs(delta) < 0.05:
                flat_note = (
                    f"headline flat r{prev['round']:02d}->"
                    f"r{last['round']:02d} ({delta:+.1%})")
    return {
        "schema": "graftbench.trend.v1",
        "bench": bench,
        "multichip": multichip,
        "gates": gates,
        "mesh_scaling": mesh_scaling,
        "red_count": len(reds),
        "flat_note": flat_note,
    }


def _fmt(v, spec: str = ",.0f") -> str:
    return "-" if v is None else format(v, spec)


def format_trend(trend: Dict[str, Any]) -> str:
    lines = ["headline bench (python bench.py, per round):"]
    for r in trend["bench"]:
        mark = f"RED rc={r['rc']}" if r["red"] else "ok"
        lines.append(
            f"  r{(r.get('round') or 0):02d}  "
            f"{_fmt(r.get('evals_per_sec')):>12} evals/s  "
            f"vs_baseline {_fmt(r.get('vs_baseline'), '.2f'):>6}  "
            f"proj_v5e8 {_fmt(r.get('projected_v5e8')):>12}  [{mark}]"
            + (f"  {r['note']}" if r.get("note") else ""))
    lines.append("multi-chip dryrun (MULTICHIP_r0N.json):")
    for r in trend["multichip"]:
        if r.get("skipped"):
            mark = "skipped"
        elif r["red"]:
            mark = f"RED rc={r['rc']}"
        else:
            mark = "green"
        lines.append(
            f"  r{(r.get('round') or 0):02d}  "
            f"{r.get('n_devices') or '-':>2} device(s)  [{mark}]"
            + (f"  {r['note']}" if r.get("note") else ""))
    if trend["gates"]:
        lines.append("gate matrix results:")
        for r in trend["gates"]:
            mark = (f"RED ({r.get('note')})" if r.get("red")
                    else "green")
            peak = r.get("peak_live_bytes")
            lines.append(
                f"  {r['file']:<28} {r.get('matrix') or '?'}/"
                f"{r.get('platform') or '?'}  "
                f"cells={r.get('cells', '-')}  "
                f"mean evals/s {_fmt(r.get('mean_evals_per_sec'))}  "
                f"anomalies={r.get('anomalies', '-')}  "
                + (f"peak live {peak:,} B  " if peak else "")
                + f"[{mark}]")
    if trend.get("mesh_scaling"):
        lines.append("measured mesh scaling (profiling/mesh_scaling.py):")
        for r in trend["mesh_scaling"]:
            if r.get("red"):
                lines.append(
                    f"  {r['file']:<28} [RED]  {r.get('note', '')}")
                continue
            curve = "  ".join(
                f"{p['shards']}sh={_fmt(p.get('evals_per_sec'))}"
                for p in r.get("points", []))
            caveat = (" (virtual CPU mesh: one core timeshared — "
                      "validity+overhead, not speedup)"
                      if r.get("virtual_cpu_mesh") else "")
            lines.append(f"  {r['file']:<28} {curve}{caveat}")
    if trend.get("flat_note"):
        lines.append(f"note: {trend['flat_note']}")
    lines.append(
        f"{trend['red_count']} red artifact(s) in the trajectory"
        if trend["red_count"] else "trajectory is green")
    return "\n".join(lines)
