"""Multi-chip projection plumbing: the one sanctioned bridge to
``profiling/ici_model.py``.

The headline benchmark (bench/headline.py, wrapped by the repo-root
``bench.py``) projects a v5e-8 number from the measured single-chip
rate and the closed-form ICI byte model. The model lives in
``profiling/`` — outside the package — so it is loaded here by file
path, replacing the ``sys.path.insert`` + ``import ici_model`` hack
that used to live inline in bench.py (and leaking ``profiling/`` onto
``sys.path`` for every later import with it).

Pure host arithmetic: no jax, no device.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Dict, Tuple

__all__ = ["load_ici_model", "v5e8_comm_efficiency", "profiling_dir"]

_ICI_CACHE = None


def profiling_dir() -> str:
    """``<repo root>/profiling`` for a repo checkout of this package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "profiling")


def load_ici_model():
    """Load profiling/ici_model.py as a module (cached), without
    mutating ``sys.path``. Raises FileNotFoundError outside a repo
    checkout — callers treat the projection as unavailable."""
    global _ICI_CACHE
    if _ICI_CACHE is not None:
        return _ICI_CACHE
    path = os.path.join(profiling_dir(), "ici_model.py")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"profiling/ici_model.py not found at {path}; the v5e-8 "
            "projection needs a repo checkout")
    spec = importlib.util.spec_from_file_location("_graftbench_ici", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _ICI_CACHE = mod
    return mod


def v5e8_comm_efficiency(
    iter_seconds: float,
    *,
    islands: int = 512 * 8,
    population_size: int = 256,
    maxsize: int = 30,
    topn: int = 12,
    n_devices: int = 8,
    ici_gbps: float = 400.0,
) -> Tuple[float, Dict[str, Any]]:
    """Communication-bound weak-scaling efficiency for a v5e-8 from the
    closed-form ICI byte model (profiling/ici_model.py).

    Islands are data-independent — the per-chip program at 512 local
    islands is EXACTLY the measured single-chip program; the only
    cross-chip traffic is the migration-pool all-gather + HoF merge +
    stats psum. A virtual CPU mesh cannot measure this (its 'devices'
    share the host cores, so per-device throughput mechanically drops
    ~1/n); profiling/weak_scaling.py exists to (a) produce the real
    number the day multi-chip hardware is attached and (b) validate
    that the sharded program executes at 1..8 shards, which the
    driver's dryrun_multichip also pins every round.

    ``iter_seconds`` is the measured per-iteration wall time of THIS
    run; the defaults are the worst-case partitioner bound at the bench
    config with a conservative 400 Gbit/s effective ICI (v5e raw
    per-chip is ~4x that).
    """
    m = load_ici_model().model(
        I=islands, P=population_size, L=maxsize, topn=topn,
        maxsize=maxsize, n_devices=n_devices,
        iter_seconds=iter_seconds, ici_gbps=ici_gbps,
    )
    return m["weak_scaling_comm_efficiency_lower_bound"], {
        "model": "profiling/ici_model.py worst-case partitioner bound",
        "total_MB_per_iter_upper": m["total_MB_per_iter_upper"],
        "measured_iter_seconds": round(iter_seconds, 2),
        "ici_gbps_assumed": ici_gbps,
    }
