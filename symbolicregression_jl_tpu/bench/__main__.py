"""graftbench CLI: ``python -m symbolicregression_jl_tpu.bench <cmd>``.

Commands (docs/BENCHMARKING.md):

- ``run``   — execute the benchmark matrix, write the result JSON, and
  optionally pin it as a new baseline (``--baseline-out``, with noise
  bands calibrated from ``--repeats``).
- ``gate``  — run a fresh matrix and diff it against the committed
  baseline; exits nonzero on regression beyond band (the CI job).
- ``load``  — the serve-level submit/poll storm benchmark.
- ``trend`` — fold BENCH_r0*/MULTICHIP_r0* history + gate results into
  one trajectory report (red artifacts flagged, never dropped).
- ``_cell`` — internal: one matrix cell in a clean subprocess.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _write_json(path: Optional[str], payload: dict, log=print) -> None:
    if not path:
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    log(f"wrote {path}")


def _add_matrix_args(p: argparse.ArgumentParser) -> None:
    from .cell import VARIANTS

    p.add_argument("--full", action="store_true",
                   help="chip-sized shapes (default: CPU mini matrix)")
    p.add_argument("--variants", nargs="+", default=list(VARIANTS),
                   choices=list(VARIANTS), metavar="VARIANT")
    p.add_argument("--seeds", nargs="+", type=int, default=[0, 1])
    p.add_argument("--workdir", default=None,
                   help="scratch dir for cell runs/telemetry "
                        "(default $TMPDIR/graftbench)")


def cmd_run(args) -> int:
    from .gate import calibrate_bands, make_baseline
    from .matrix import run_matrix

    matrix = "full" if args.full else "mini"
    results = []
    for rep in range(max(args.repeats, 1)):
        print(f"matrix run {rep + 1}/{args.repeats} ({matrix}):")
        results.append(run_matrix(
            matrix=matrix, variants=args.variants, seeds=args.seeds,
            workdir=args.workdir))
    result = results[-1]
    _write_json(args.out, result)
    # failures from ANY repeat fail the run: a cell that crashed in an
    # earlier repeat would otherwise silently degrade the calibration
    # (fewer samples per cell) behind a green exit code
    failed_cells = sorted(
        {cid for r in results for cid in r["failures"]})
    if args.baseline_out:
        if failed_cells:
            print(f"refusing to pin a baseline: cell(s) failed in at "
                  f"least one repeat: {', '.join(failed_cells)}",
                  file=sys.stderr)
        else:
            try:
                baseline = make_baseline(
                    results, calibrate_bands(results))
            except ValueError as e:  # non-finite gated metric
                print(str(e), file=sys.stderr)
                return 1
            _write_json(args.baseline_out, baseline)
    if failed_cells:
        print(f"{len(failed_cells)} cell(s) failed across "
              f"{len(results)} repeat(s)", file=sys.stderr)
        return 1
    return 0


def cmd_gate(args) -> int:
    from .gate import (diff_result, format_findings, gate_failed,
                       load_baseline)
    from .matrix import run_matrix

    try:
        baseline = load_baseline(args.baseline)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"gate: cannot load baseline: {e}", file=sys.stderr)
        return 2
    from .cell import VARIANTS
    from .matrix import DEFAULT_SEEDS, matrix_cells

    cells_filter = None
    if (tuple(args.variants) != VARIANTS
            or tuple(args.seeds) != DEFAULT_SEEDS):
        # a deliberately sliced gate (fresh run OR --result of a
        # sliced run) diffs only what was asked for — the cells it
        # was ASKED to skip are not "missing". The slice must
        # actually intersect the baseline (checked BEFORE spending
        # minutes running it): an empty intersection would "PASS"
        # having compared nothing.
        requested = [cid for cid, _, _ in matrix_cells(
            args.variants, args.seeds)]
        cells_filter = [cid for cid in requested
                        if cid in baseline.get("cells", {})]
        if not cells_filter:
            print(f"gate: requested slice {requested} matches no "
                  f"baseline cell — nothing to gate", file=sys.stderr)
            return 2
        print(f"gate: PARTIAL — diffing {len(cells_filter)} of "
              f"{len(baseline.get('cells', {}))} baseline cells")
    if args.result:
        with open(args.result) as f:
            result = json.load(f)
    else:
        matrix = "full" if args.full else "mini"
        if matrix != baseline.get("matrix"):
            print(f"gate: baseline is a {baseline.get('matrix')!r} "
                  f"matrix; pass the matching flags", file=sys.stderr)
            return 2
        print(f"gate: running fresh {matrix} matrix "
              f"against {args.baseline}")
        result = run_matrix(
            matrix=matrix, variants=args.variants, seeds=args.seeds,
            workdir=args.workdir)
    findings = diff_result(result, baseline, cells_filter=cells_filter)
    payload = dict(result)
    payload["gate"] = {
        "baseline": args.baseline,
        "findings": [f.to_dict() for f in findings],
        "failed": gate_failed(findings),
    }
    _write_json(args.out, payload)
    print(format_findings(findings, verbose=args.verbose))
    return 1 if gate_failed(findings) else 0


def cmd_load(args) -> int:
    from .load import run_compare, run_load

    kw = dict(
        requests=args.requests, workers=args.workers,
        capacity=args.capacity, rows=args.rows,
        niterations=args.niterations, timeout_s=args.timeout,
    )
    if args.compare:
        report = run_compare(args.root, row_step=args.row_step, **kw)
        _write_json(args.out, report)
        return 0 if report["ok"] else 1
    report = run_load(args.root, packed=args.packed,
                      row_step=args.row_step, **kw)
    _write_json(args.out, report)
    if not report["ok"]:
        print(f"load: {report['failed']} failed / "
              f"{report['unfinished']} unfinished / "
              f"{args.requests - report['submitted']} never-admitted "
              f"request(s)", file=sys.stderr)
        return 1
    return 0


def cmd_trend(args) -> int:
    from .trend import build_trend, format_trend

    trend = build_trend(args.root, gate_paths=args.gate or None)
    if args.json:
        print(json.dumps(trend))
    else:
        print(format_trend(trend))
    return 1 if (args.strict and trend["red_count"]) else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m symbolicregression_jl_tpu.bench",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run the benchmark matrix")
    _add_matrix_args(p)
    p.add_argument("--repeats", type=int, default=1,
                   help="repeat the matrix N times (band calibration)")
    p.add_argument("--out", default=None, help="result JSON path")
    p.add_argument("--baseline-out", default=None,
                   help="pin the run(s) as a new baseline at this path")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("gate", help="diff a fresh matrix vs baseline")
    _add_matrix_args(p)
    p.add_argument("--baseline", default="benchmarks/baseline.json")
    p.add_argument("--result", default=None,
                   help="gate a precomputed result file instead of "
                        "running the matrix")
    p.add_argument("--out", default=None,
                   help="write result+findings JSON here (CI artifact)")
    p.add_argument("--verbose", action="store_true",
                   help="also print in-band (ok) comparisons")
    p.set_defaults(fn=cmd_gate)

    p = sub.add_parser("load", help="serve submit/poll storm benchmark")
    p.add_argument("--root", default=os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "graftbench_load"))
    p.add_argument("--requests", type=int, default=10)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--capacity", type=int, default=4)
    p.add_argument("--rows", type=int, default=160)
    p.add_argument("--niterations", type=int, default=1)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--packed", action="store_true",
                   help="graftpack multi-tenant packing: pad requests "
                        "to their shape bucket and launch same-bucket "
                        "cohorts together (adds occupancy/coalesce "
                        "metrics to the report)")
    p.add_argument("--row-step", type=int, default=0,
                   help="near-miss row mix: request i gets rows + "
                        "(i %% 4) * row_step rows (same shape bucket)")
    p.add_argument("--compare", action="store_true",
                   help="run the storm timeshared AND packed at a "
                        "near-miss row mix; report the wall ratio")
    p.add_argument("--out", default=None, help="report JSON path")
    p.set_defaults(fn=cmd_load)

    p = sub.add_parser("trend", help="benchmark trajectory report")
    p.add_argument("--root", default=".",
                   help="repo root holding BENCH_r0*/MULTICHIP_r0*")
    p.add_argument("--gate", nargs="*", default=None,
                   help="extra gate result JSON files to fold in")
    p.add_argument("--json", action="store_true")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero when any red artifact exists")
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("_cell")  # internal subprocess entry
    p.add_argument("spec")
    p.set_defaults(fn=None)

    args = ap.parse_args(argv)
    if args.cmd == "_cell":
        from .cell import cell_main

        return cell_main(args.spec)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
