"""graftbench — continuous benchmark matrix + perf/quality regression gate.

The repo's perf/quality safety net (ROADMAP item 5, docs/BENCHMARKING.md):

- ``python -m symbolicregression_jl_tpu.bench run`` executes a small
  fixed matrix (plain / template / parametric / island-sharded x seeds;
  CPU-sized shapes by default, chip-sized with ``--full``) with
  graftscope telemetry on, and extracts per-cell metrics — evals/s,
  best loss, host-fraction, recompile count, pareto volume — from the
  telemetry JSONL rather than ad-hoc timers (bench/extract.py over
  telemetry/report.py's machine-readable metrics view).
- ``... bench gate`` diffs a fresh matrix result against the committed
  schema-versioned baseline (benchmarks/baseline.json) using per-metric
  noise bands calibrated from repeated seed runs, and exits nonzero on
  regression beyond band: quality regressions gate hard, throughput
  regressions gate with a wider band on CPU (bench/gate.py).
- ``... bench load`` is the serve-level benchmark: a sustained
  submit/poll storm against a real :class:`~..serve.SearchServer`,
  reporting requests/s, p99 poll latency, executable-cache hit rate,
  and shed fraction (bench/load.py).
- ``... bench trend`` folds the committed BENCH_r0*.json /
  MULTICHIP_r0*.json history plus gate results into one trajectory
  report, flagging red artifacts (nonzero rc) explicitly instead of
  silently skipping them (bench/trend.py).

The repo-root ``bench.py`` headline benchmark is a thin wrapper over
:mod:`.headline` and keeps its one-line JSON contract.
"""

from __future__ import annotations

from .extract import extract_metrics
from .gate import (
    BASELINE_SCHEMA,
    GATED_METRICS,
    calibrate_bands,
    diff_result,
    load_baseline,
    make_baseline,
)
from .matrix import MATRIX_SHAPES, RESULT_SCHEMA, matrix_cells, run_matrix
from .projection import v5e8_comm_efficiency

__all__ = [
    "BASELINE_SCHEMA",
    "GATED_METRICS",
    "MATRIX_SHAPES",
    "RESULT_SCHEMA",
    "calibrate_bands",
    "diff_result",
    "extract_metrics",
    "load_baseline",
    "make_baseline",
    "matrix_cells",
    "run_matrix",
    "v5e8_comm_efficiency",
]
