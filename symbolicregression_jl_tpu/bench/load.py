"""Serve-level load benchmark: a sustained submit/poll storm against a
real :class:`~..serve.server.SearchServer`.

Reports the four numbers ROADMAP item 5 asks serve regressions to be
judged by — requests/s, p99 poll latency, executable-cache hit rate,
and shed fraction — measured from a live server (workers draining tiny
deterministic searches), with the cache hit rate read back from the
server's own graftscope serve stream rather than re-counted here.

The storm deliberately over-submits relative to ``capacity`` so the
overload ladder engages: sheds and structured rejects are part of the
measured behavior, not an error. Every submitted request must still
reach a terminal state (or a structured reject) — anything else fails
the benchmark.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

__all__ = ["LOAD_SCHEMA", "COMPARE_SCHEMA", "percentile", "run_load",
           "run_compare"]

LOAD_SCHEMA = "graftbench.load.v1"
COMPARE_SCHEMA = "graftbench.load_compare.v1"


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not samples:
        return None
    xs = sorted(samples)
    k = max(0, min(len(xs) - 1,
                   int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


def _storm_options() -> Dict[str, Any]:
    # tiny deterministic search: the load bench measures the SERVER
    # (queueing, journaling, cache, poll responsiveness), not the
    # search kernel — the matrix cells own that
    return {
        "binary_operators": ["+", "*"],
        "unary_operators": [],
        "maxsize": 8,
        "populations": 2,
        "population_size": 8,
        "ncycles_per_iteration": 2,
        "tournament_selection_n": 4,
        "optimizer_probability": 0.0,
    }


def run_load(
    root: str,
    *,
    requests: int = 10,
    workers: int = 2,
    capacity: int = 4,
    rows: int = 160,
    niterations: int = 1,
    poll_interval_s: float = 0.02,
    timeout_s: float = 600.0,
    packed: bool = False,
    row_step: int = 0,
    log=print,
) -> Dict[str, Any]:
    """Run the storm; returns the schema-versioned load report.

    All requests share one shape bucket (same ``rows``), so repeats
    after the first SHOULD hit the executable cache — the hit rate is
    the serve-scaling headline (docs/SERVING.md pins >=90% on repeats).

    ``packed=True`` turns on graftpack multi-tenant packing (default
    PackPolicy) and adds the ``pack`` metrics section: per-launch
    occupancy, coalesce wait p50/p99, and — via the graftledger rollup
    already reported — the per-tenant device-seconds fairness spread.
    ``row_step`` varies request row counts (rows + (i % 4) * row_step)
    WITHIN the same shape bucket: the near-miss mix that padding
    collapses onto one traced executable and that timesharing retraces
    per distinct shape — set it on both sides of a packed-vs-timeshared
    comparison (:func:`run_compare`).
    """
    import numpy as np

    from ..ledger.rollup import load_rollup
    from ..pack import PackPolicy
    from ..serve.admission import ServerSaturated, shape_bucket
    from ..serve.server import SearchServer
    from ..telemetry.report import summarize
    from ..telemetry.schema import load_events

    if os.path.isdir(root):
        shutil.rmtree(root)  # a stale journal would replay old requests
    row_counts = [rows + (i % 4) * max(int(row_step), 0)
                  for i in range(requests)]
    if len({shape_bucket(r, 2) for r in row_counts}) > 1:
        raise ValueError(
            f"row_step={row_step} pushes the near-miss mix across shape "
            f"buckets; the storm must stay same-bucket")
    rng = np.random.default_rng(0)
    Xfull = rng.uniform(-2.0, 2.0, (max(row_counts), 2)).astype(np.float32)
    yfull = (Xfull[:, 0] * 2.0 + Xfull[:, 1]).astype(np.float32)
    opts = _storm_options()

    server = SearchServer(
        root, capacity=capacity, workers=workers,
        pack=PackPolicy() if packed else None)
    submitted: List[str] = []
    rejects = 0
    poll_lat: List[float] = []
    t0 = time.perf_counter()
    try:
        server.start()
        # sustained storm: a rejected submit backs off (bounded by the
        # server's retry-after hint) and retries — structured rejects
        # are counted as backpressure events, not lost requests, so the
        # storm keeps the queue pinned at capacity for its whole span
        deadline0 = time.monotonic() + timeout_s
        for i in range(requests):
            n_i = row_counts[i]
            while True:
                try:
                    rid = server.submit(
                        Xfull[:n_i], yfull[:n_i], options=opts,
                        niterations=niterations, seed=i,
                    )
                    submitted.append(rid)
                    break
                except ServerSaturated as e:
                    rejects += 1
                    if time.monotonic() > deadline0:
                        break
                    time.sleep(min(e.retry_after_s or 0.1, 0.25))
        # sustained poll loop: every poll() call is timed — its latency
        # is the client-visible responsiveness of the server lock under
        # concurrent worker/journal traffic
        pending = set(submitted)
        deadline = time.monotonic() + timeout_s
        while pending and time.monotonic() < deadline:
            for rid in list(pending):
                tp = time.perf_counter()
                snap = server.poll(rid)
                poll_lat.append(time.perf_counter() - tp)
                if snap["state"] in ("done", "failed", "cancelled"):
                    pending.discard(rid)
            time.sleep(poll_interval_s)
        wall = time.perf_counter() - t0
    finally:
        server.stop(drain=False, timeout=30.0)

    snaps = {rid: server.poll(rid) for rid in submitted}
    done = [r for r, s in snaps.items() if s["state"] == "done"]
    failed = [r for r, s in snaps.items() if s["state"] == "failed"]
    unfinished = sorted(set(submitted) - set(done) - set(failed)
                        - {r for r, s in snaps.items()
                           if s["state"] == "cancelled"})
    shed = [r for r, s in snaps.items()
            if s.get("sample_rows") is not None]

    cache_hit_rate = None
    pack_metrics: Optional[Dict[str, Any]] = None
    serve_stream = os.path.join(root, "serve_telemetry.jsonl")
    if os.path.exists(serve_stream):
        events = load_events(serve_stream)
        summary = summarize(events)
        cache_hit_rate = (summary.get("serve", {})
                          .get("cache", {}).get("hit_rate"))
        if packed:
            # graftpack occupancy + coalesce waits from the serve
            # stream: pack_launch carries per-tenant coalesce waits,
            # pack_join the late joiners', pack_done the per-round
            # occupancy record (pack/cohort.py)
            waits: List[float] = []
            occs: List[float] = []
            launches = multi = tenants = 0
            for e in events:
                if e.get("event") != "serve":
                    continue
                det = e.get("detail") or {}
                if e.get("kind") == "pack_launch":
                    launches += 1
                    members = det.get("tenants") or []
                    tenants += len(members)
                    if len(members) > 1:
                        multi += 1
                    waits.extend(
                        float(w) for w in
                        (det.get("coalesce_wait_s") or {}).values())
                elif e.get("kind") == "pack_join":
                    tenants += 1
                    if det.get("coalesce_wait_s") is not None:
                        waits.append(float(det["coalesce_wait_s"]))
                elif e.get("kind") == "pack_done":
                    if isinstance(det.get("occupancy"), (int, float)):
                        occs.append(float(det["occupancy"]))
            pack_metrics = {
                "launches": launches,
                "multi_tenant_launches": multi,
                "tenants": tenants,
                "occupancy_mean": (round(sum(occs) / len(occs), 4)
                                   if occs else None),
                "coalesce_wait_s": {
                    "samples": len(waits),
                    "p50": percentile(waits, 50),
                    "p99": percentile(waits, 99),
                    "max": max(waits) if waits else None,
                },
            }

    # per-tenant cost attribution: the server's graftledger rollup
    # (written on every request completion) gives each request's
    # device-seconds; the max/min spread is the fairness headline — a
    # storm of IDENTICAL searches should cost every tenant about the
    # same, so a wide spread means scheduling skew, not workload skew
    ledger: Optional[Dict[str, Any]] = None
    rollup = load_rollup(root)
    if rollup and rollup.get("requests"):
        per_req = {
            rid: round(float(acct.get("device_s", 0.0)), 6)
            for rid, acct in sorted(rollup["requests"].items())
        }
        costs = [c for c in per_req.values() if c > 0.0]
        spread = (round(max(costs) / min(costs), 3)
                  if costs and min(costs) > 0.0 else None)
        totals = rollup.get("totals", {})
        ledger = {
            "requests": len(per_req),
            "device_seconds": per_req,
            "total_device_s": round(float(totals.get("device_s", 0.0)), 6),
            "total_evals": totals.get("num_evals"),
            "fairness_spread": spread,  # max/min per-request device_s
        }

    report = {
        "schema": LOAD_SCHEMA,
        "t": time.time(),
        "config": {
            "requests": requests, "workers": workers,
            "capacity": capacity, "rows": rows,
            "niterations": niterations,
            "packed": packed, "row_step": row_step,
        },
        "submitted": len(submitted),
        "rejected": rejects,
        "completed": len(done),
        "failed": len(failed),
        "unfinished": len(unfinished),
        "shed": len(shed),
        "shed_fraction": (len(shed) / len(submitted)
                          if submitted else None),
        "wall_s": round(wall, 3),
        "requests_per_sec": (round(len(done) / wall, 3)
                             if wall > 0 else None),
        "poll_latency_s": {
            "samples": len(poll_lat),
            "p50": percentile(poll_lat, 50),
            "p99": percentile(poll_lat, 99),
            "max": max(poll_lat) if poll_lat else None,
        },
        "cache_hit_rate": cache_hit_rate,
        "pack": pack_metrics,
        "ledger": ledger,
        "serve_telemetry": serve_stream,
    }
    p99 = report["poll_latency_s"]["p99"]
    log(f"load: {len(done)}/{len(submitted)} done "
        f"(+{rejects} rejected, {len(shed)} shed) in {wall:.1f}s — "
        f"{report['requests_per_sec']} req/s, "
        f"p99 poll {'-' if p99 is None else format(p99, '.4f')}s, "
        f"cache hit rate "
        f"{'-' if cache_hit_rate is None else format(cache_hit_rate, '.0%')}")
    if ledger is not None:
        log(f"load: ledger {ledger['requests']} request(s), "
            f"{ledger['total_device_s']:.3f} device-s total, "
            f"fairness spread (max/min device-s) "
            f"{'-' if ledger['fairness_spread'] is None else ledger['fairness_spread']}")
    if pack_metrics is not None:
        cw = pack_metrics["coalesce_wait_s"]
        log(f"load: pack {pack_metrics['launches']} launch(es) "
            f"({pack_metrics['multi_tenant_launches']} multi-tenant, "
            f"{pack_metrics['tenants']} tenants), "
            f"occupancy {pack_metrics['occupancy_mean']}, "
            f"coalesce wait p50 "
            f"{'-' if cw['p50'] is None else format(cw['p50'], '.3f')}s / "
            f"p99 {'-' if cw['p99'] is None else format(cw['p99'], '.3f')}s")
    # a storm where admission wedged and some requests were NEVER
    # accepted (the retry loop ran out its deadline) must fail too —
    # submitted==0 with zero failures is not a healthy server
    report["ok"] = (not failed and not unfinished
                    and len(submitted) == requests)
    return report


def run_compare(root: str, *, log=print, **kw) -> Dict[str, Any]:
    """Timeshared-vs-packed A/B at identical storm parameters.

    Runs the same near-miss same-bucket storm twice — once on the
    timeshared path (each distinct row count retraces the shared
    engine's jitted programs), once packed (every request padded to the
    bucket, one trace, cohorts of concurrent tenants) — and reports the
    wall-clock ratio. ISSUE-20 acceptance pins packed <= 0.6x
    timeshared on a 4x oversubscribed same-bucket CPU storm.
    """
    kw.setdefault("row_step", 8)
    ts = run_load(os.path.join(root, "timeshared"),
                  packed=False, log=log, **kw)
    pk = run_load(os.path.join(root, "packed"),
                  packed=True, log=log, **kw)
    speedup = (round(ts["wall_s"] / pk["wall_s"], 3)
               if pk["wall_s"] else None)
    log(f"compare: timeshared {ts['wall_s']}s vs packed {pk['wall_s']}s "
        f"-> packed/timeshared = "
        f"{'-' if not speedup else format(pk['wall_s'] / ts['wall_s'], '.2f')}x"
        f" (speedup {speedup}x)")
    return {
        "schema": COMPARE_SCHEMA,
        "t": time.time(),
        "timeshared": ts,
        "packed": pk,
        "wall_ratio_packed_over_timeshared": (
            round(pk["wall_s"] / ts["wall_s"], 3) if ts["wall_s"] else None),
        "speedup": speedup,
        "ok": bool(ts["ok"] and pk["ok"]),
    }
