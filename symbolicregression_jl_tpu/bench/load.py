"""Serve-level load benchmark: a sustained submit/poll storm against a
real :class:`~..serve.server.SearchServer`.

Reports the four numbers ROADMAP item 5 asks serve regressions to be
judged by — requests/s, p99 poll latency, executable-cache hit rate,
and shed fraction — measured from a live server (workers draining tiny
deterministic searches), with the cache hit rate read back from the
server's own graftscope serve stream rather than re-counted here.

The storm deliberately over-submits relative to ``capacity`` so the
overload ladder engages: sheds and structured rejects are part of the
measured behavior, not an error. Every submitted request must still
reach a terminal state (or a structured reject) — anything else fails
the benchmark.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

__all__ = ["LOAD_SCHEMA", "percentile", "run_load"]

LOAD_SCHEMA = "graftbench.load.v1"


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not samples:
        return None
    xs = sorted(samples)
    k = max(0, min(len(xs) - 1,
                   int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


def _storm_options() -> Dict[str, Any]:
    # tiny deterministic search: the load bench measures the SERVER
    # (queueing, journaling, cache, poll responsiveness), not the
    # search kernel — the matrix cells own that
    return {
        "binary_operators": ["+", "*"],
        "unary_operators": [],
        "maxsize": 8,
        "populations": 2,
        "population_size": 8,
        "ncycles_per_iteration": 2,
        "tournament_selection_n": 4,
        "optimizer_probability": 0.0,
    }


def run_load(
    root: str,
    *,
    requests: int = 10,
    workers: int = 2,
    capacity: int = 4,
    rows: int = 160,
    niterations: int = 1,
    poll_interval_s: float = 0.02,
    timeout_s: float = 600.0,
    log=print,
) -> Dict[str, Any]:
    """Run the storm; returns the schema-versioned load report.

    All requests share one shape bucket (same ``rows``), so repeats
    after the first SHOULD hit the executable cache — the hit rate is
    the serve-scaling headline (docs/SERVING.md pins >=90% on repeats).
    """
    import numpy as np

    from ..ledger.rollup import load_rollup
    from ..serve.admission import ServerSaturated
    from ..serve.server import SearchServer
    from ..telemetry.report import summarize
    from ..telemetry.schema import load_events

    if os.path.isdir(root):
        shutil.rmtree(root)  # a stale journal would replay old requests
    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (rows, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    opts = _storm_options()

    server = SearchServer(root, capacity=capacity, workers=workers)
    submitted: List[str] = []
    rejects = 0
    poll_lat: List[float] = []
    t0 = time.perf_counter()
    try:
        server.start()
        # sustained storm: a rejected submit backs off (bounded by the
        # server's retry-after hint) and retries — structured rejects
        # are counted as backpressure events, not lost requests, so the
        # storm keeps the queue pinned at capacity for its whole span
        deadline0 = time.monotonic() + timeout_s
        for i in range(requests):
            while True:
                try:
                    rid = server.submit(
                        X, y, options=opts, niterations=niterations,
                        seed=i,
                    )
                    submitted.append(rid)
                    break
                except ServerSaturated as e:
                    rejects += 1
                    if time.monotonic() > deadline0:
                        break
                    time.sleep(min(e.retry_after_s or 0.1, 0.25))
        # sustained poll loop: every poll() call is timed — its latency
        # is the client-visible responsiveness of the server lock under
        # concurrent worker/journal traffic
        pending = set(submitted)
        deadline = time.monotonic() + timeout_s
        while pending and time.monotonic() < deadline:
            for rid in list(pending):
                tp = time.perf_counter()
                snap = server.poll(rid)
                poll_lat.append(time.perf_counter() - tp)
                if snap["state"] in ("done", "failed", "cancelled"):
                    pending.discard(rid)
            time.sleep(poll_interval_s)
        wall = time.perf_counter() - t0
    finally:
        server.stop(drain=False, timeout=30.0)

    snaps = {rid: server.poll(rid) for rid in submitted}
    done = [r for r, s in snaps.items() if s["state"] == "done"]
    failed = [r for r, s in snaps.items() if s["state"] == "failed"]
    unfinished = sorted(set(submitted) - set(done) - set(failed)
                        - {r for r, s in snaps.items()
                           if s["state"] == "cancelled"})
    shed = [r for r, s in snaps.items()
            if s.get("sample_rows") is not None]

    cache_hit_rate = None
    serve_stream = os.path.join(root, "serve_telemetry.jsonl")
    if os.path.exists(serve_stream):
        summary = summarize(load_events(serve_stream))
        cache_hit_rate = (summary.get("serve", {})
                          .get("cache", {}).get("hit_rate"))

    # per-tenant cost attribution: the server's graftledger rollup
    # (written on every request completion) gives each request's
    # device-seconds; the max/min spread is the fairness headline — a
    # storm of IDENTICAL searches should cost every tenant about the
    # same, so a wide spread means scheduling skew, not workload skew
    ledger: Optional[Dict[str, Any]] = None
    rollup = load_rollup(root)
    if rollup and rollup.get("requests"):
        per_req = {
            rid: round(float(acct.get("device_s", 0.0)), 6)
            for rid, acct in sorted(rollup["requests"].items())
        }
        costs = [c for c in per_req.values() if c > 0.0]
        spread = (round(max(costs) / min(costs), 3)
                  if costs and min(costs) > 0.0 else None)
        totals = rollup.get("totals", {})
        ledger = {
            "requests": len(per_req),
            "device_seconds": per_req,
            "total_device_s": round(float(totals.get("device_s", 0.0)), 6),
            "total_evals": totals.get("num_evals"),
            "fairness_spread": spread,  # max/min per-request device_s
        }

    report = {
        "schema": LOAD_SCHEMA,
        "t": time.time(),
        "config": {
            "requests": requests, "workers": workers,
            "capacity": capacity, "rows": rows,
            "niterations": niterations,
        },
        "submitted": len(submitted),
        "rejected": rejects,
        "completed": len(done),
        "failed": len(failed),
        "unfinished": len(unfinished),
        "shed": len(shed),
        "shed_fraction": (len(shed) / len(submitted)
                          if submitted else None),
        "wall_s": round(wall, 3),
        "requests_per_sec": (round(len(done) / wall, 3)
                             if wall > 0 else None),
        "poll_latency_s": {
            "samples": len(poll_lat),
            "p50": percentile(poll_lat, 50),
            "p99": percentile(poll_lat, 99),
            "max": max(poll_lat) if poll_lat else None,
        },
        "cache_hit_rate": cache_hit_rate,
        "ledger": ledger,
        "serve_telemetry": serve_stream,
    }
    p99 = report["poll_latency_s"]["p99"]
    log(f"load: {len(done)}/{len(submitted)} done "
        f"(+{rejects} rejected, {len(shed)} shed) in {wall:.1f}s — "
        f"{report['requests_per_sec']} req/s, "
        f"p99 poll {'-' if p99 is None else format(p99, '.4f')}s, "
        f"cache hit rate "
        f"{'-' if cache_hit_rate is None else format(cache_hit_rate, '.0%')}")
    if ledger is not None:
        log(f"load: ledger {ledger['requests']} request(s), "
            f"{ledger['total_device_s']:.3f} device-s total, "
            f"fairness spread (max/min device-s) "
            f"{'-' if ledger['fairness_spread'] is None else ledger['fairness_spread']}")
    # a storm where admission wedged and some requests were NEVER
    # accepted (the retry loop ran out its deadline) must fail too —
    # submitted==0 with zero failures is not a healthy server
    report["ok"] = (not failed and not unfinished
                    and len(submitted) == requests)
    return report
