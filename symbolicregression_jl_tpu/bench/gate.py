"""The regression gate: fresh matrix result vs committed baseline.

A baseline (``benchmarks/baseline.json``, schema
``graftbench.baseline.v1``) pins per-cell metrics plus per-metric noise
bands calibrated from repeated seed runs (``bench run --repeats N
--baseline-out ...``). ``diff_result`` compares a fresh
``graftbench.result.v1`` record against it:

- **quality** metrics (best_loss, pareto_volume) gate HARD — any
  regression beyond their (narrow) band fails, whatever the platform.
  ROADMAP item 3 trades bit-exactness for speed; this is the line it
  must not cross.
- **throughput** metrics (evals_per_sec, host_fraction, recompiles)
  gate at their calibrated band on a DEVICE platform; on CPU only the
  collapse-floor / blowup-ceiling backstops fail the gate, and band
  excursions report as non-failing ``soft`` findings — absolute CPU
  wall-clock does not transfer across hosts (a 2-core CI runner runs
  the matrix at a fraction of the calibration host's rate with
  bit-identical quality), and a throughput gate that cries wolf gets
  deleted.
- a baseline cell MISSING from the fresh result is a hard failure (a
  crashing variant must not silently drop out of coverage), as is a
  schema or matrix-kind mismatch.

Improvements beyond band are reported (so a better baseline gets
re-pinned) but never fail. Pure host-side JSON — no jax.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from typing import Any, Dict, List, Optional

from .matrix import RESULT_SCHEMA

__all__ = [
    "BASELINE_SCHEMA", "GATED_METRICS", "Band", "Finding",
    "calibrate_bands", "make_baseline", "load_baseline", "diff_result",
    "format_findings",
]

BASELINE_SCHEMA = "graftbench.baseline.v1"


@dataclasses.dataclass(frozen=True)
class Band:
    """Noise band for one metric. ``direction`` names the REGRESSION
    direction ("higher" = an increase is bad). A fresh value regresses
    when it crosses ``base`` by more than rel*|base| + abs in that
    direction; ``kind`` picks hard (quality) vs CPU-widened
    (throughput) gating."""

    direction: str  # "higher" | "lower" (which way is worse)
    kind: str       # "quality" | "throughput"
    rel: float = 0.0
    abs: float = 0.0


# Default bands — the floor; calibration (repeated seed runs) can only
# WIDEN them, so a lucky calibration pair can't produce a hair-trigger
# gate. Quality floors are tight: the search is deterministic given
# (seed, platform), so best_loss moves only when semantics change.
GATED_METRICS: Dict[str, Band] = {
    "best_loss": Band(direction="higher", kind="quality",
                      rel=0.05, abs=1e-7),
    "pareto_volume": Band(direction="lower", kind="quality",
                          rel=0.10, abs=1e-7),
    "evals_per_sec": Band(direction="lower", kind="throughput",
                          rel=0.30),
    "host_fraction": Band(direction="higher", kind="throughput",
                          rel=0.50, abs=0.10),
    "recompiles": Band(direction="higher", kind="throughput",
                       rel=0.25, abs=8),
}
# Deliberately NOT gated: "peak_live_bytes" (graftgauge) — live-array
# byte counts vary with jax version, platform allocator, and process
# history, so diffing them against a committed baseline would flake;
# `bench trend` displays the trajectory instead.

# CPU wall-clock on shared CI cores is noisy; throughput bands widen by
# this factor when REPORTING on a CPU result (quality bands never
# widen). On CPU the band is informational only — see diff_result.
CPU_THROUGHPUT_BAND_FACTOR = 2.0

# Backstops on EVERY gated metric, any platform and band width (a
# noisy calibration can push rel past 1.0, where base - margin goes
# negative and the "lower" band would never fire; an unbounded
# "higher" margin likewise): a fresh value below COLLAPSE_FLOOR x
# baseline ("lower is worse" metrics) or above max(BLOWUP_CEILING x
# baseline, the metric's UN-widened abs band) ("higher is worse") is
# ALWAYS a regression — a collapse, a quality blow-up, or a recompile
# storm must not hide inside a wide band. For throughput on CPU these
# backstops are also the ONLY failing checks (absolute CPU wall-clock
# does not transfer across hosts; band excursions go "soft").
COLLAPSE_FLOOR_FRACTION = 0.10
BLOWUP_CEILING_FACTOR = 10.0


@dataclasses.dataclass
class Finding:
    cell: str
    metric: str
    # regression | soft (CPU throughput excursion, non-failing) |
    # improvement | ok | missing_cell | schema | note
    status: str
    base: Optional[float] = None
    fresh: Optional[float] = None
    allowed: Optional[float] = None
    note: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _spread_band(values: List[float], default: Band) -> Band:
    """Widen ``default`` to cover the observed spread of repeated runs
    of the same cell (x2 safety), never narrowing below the floor."""
    finite = [v for v in values if v is not None]
    if len(finite) < 2:
        return default
    lo, hi = min(finite), max(finite)
    mid = (lo + hi) / 2.0
    if mid == 0:
        return dataclasses.replace(
            default, abs=max(default.abs, 2.0 * (hi - lo)))
    rel_spread = (hi - lo) / abs(mid)
    return dataclasses.replace(
        default, rel=max(default.rel, 2.0 * rel_spread))


def calibrate_bands(results: List[Dict[str, Any]]) -> Dict[str, Band]:
    """Per-metric noise bands from >=2 repeated matrix runs: for each
    metric, the widest per-cell spread observed across repeats, floored
    at the GATED_METRICS defaults."""
    bands = dict(GATED_METRICS)
    if len(results) < 2:
        return bands
    cell_ids = set().union(*(r.get("cells", {}) for r in results))
    for metric, default in GATED_METRICS.items():
        widest = default
        for cid in cell_ids:
            vals = [
                r["cells"][cid]["metrics"].get(metric)
                for r in results if cid in r.get("cells", {})
            ]
            cand = _spread_band(vals, default)
            if (cand.rel, cand.abs) > (widest.rel, widest.abs):
                widest = cand
        bands[metric] = widest
    return bands


def make_baseline(
    results: List[Dict[str, Any]],
    bands: Optional[Dict[str, Band]] = None,
) -> Dict[str, Any]:
    """Schema-versioned baseline from >=1 matrix runs of the same
    matrix kind: per-cell metric medians across repeats + bands
    (calibrated from the repeats unless given)."""
    if not results:
        raise ValueError("need at least one matrix result")
    kinds = {r.get("matrix") for r in results}
    if len(kinds) != 1:
        raise ValueError(f"mixed matrix kinds {kinds} cannot baseline")
    bands = bands or calibrate_bands(results)
    cell_ids = sorted(set().union(*(r.get("cells", {}) for r in results)))
    cells: Dict[str, Any] = {}
    for cid in cell_ids:
        recs = [r["cells"][cid] for r in results
                if cid in r.get("cells", {})]
        metrics: Dict[str, Any] = {}
        keys = set().union(*(rec["metrics"] for rec in recs))
        for k in sorted(keys):
            vals = sorted(
                rec["metrics"][k] for rec in recs
                if isinstance(rec["metrics"].get(k), (int, float))
            )
            if k in GATED_METRICS and any(
                    not math.isfinite(v) for v in vals):
                # a NaN pinned here would permanently fail every later
                # gate (and json.dump writes NaN without complaint) —
                # refuse the pin instead
                raise ValueError(
                    f"refusing to pin baseline: non-finite {k} in "
                    f"cell {cid}: {vals}")
            metrics[k] = vals[len(vals) // 2] if vals else None
        cells[cid] = {"metrics": metrics,
                      "variant": recs[0].get("variant"),
                      "seed": recs[0].get("seed")}
    from .matrix import library_provenance

    return {
        "schema": BASELINE_SCHEMA,
        "matrix": results[0].get("matrix"),
        "platform": results[0].get("platform"),
        "created": time.strftime("%Y-%m-%d", time.gmtime()),
        "provenance": library_provenance(),
        "repeats": len(results),
        "cpu_throughput_band_factor": CPU_THROUGHPUT_BAND_FACTOR,
        "bands": {
            m: {"direction": b.direction, "kind": b.kind,
                "rel": b.rel, "abs": b.abs}
            for m, b in bands.items()
        },
        "cells": cells,
    }


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        baseline = json.load(f)
    if baseline.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: baseline schema {baseline.get('schema')!r} != "
            f"{BASELINE_SCHEMA!r} — regenerate it with "
            "`python -m symbolicregression_jl_tpu.bench run "
            "--baseline-out <path>`")
    return baseline


def _bands_of(baseline: Dict[str, Any]) -> Dict[str, Band]:
    bands = {}
    for m, d in (baseline.get("bands") or {}).items():
        bands[m] = Band(direction=d["direction"], kind=d["kind"],
                        rel=float(d.get("rel", 0.0)),
                        abs=float(d.get("abs", 0.0)))
    for m, b in GATED_METRICS.items():
        bands.setdefault(m, b)
    return bands


def diff_result(
    result: Dict[str, Any], baseline: Dict[str, Any],
    cells_filter: Optional[List[str]] = None,
) -> List[Finding]:
    """All findings from gating ``result`` against ``baseline``; the
    gate fails iff any finding has status regression/missing_cell/
    schema (see :func:`gate_failed`).

    ``cells_filter`` restricts the diff to those baseline cell ids (a
    deliberately sliced dev run — ``gate --variants plain`` — must not
    hard-fail on every cell it was ASKED not to run); None = all.
    """
    findings: List[Finding] = []
    if result.get("schema") != RESULT_SCHEMA:
        findings.append(Finding(
            cell="*", metric="schema", status="schema",
            note=(f"result schema {result.get('schema')!r} != "
                  f"{RESULT_SCHEMA!r}")))
        return findings
    if baseline.get("schema") != BASELINE_SCHEMA:
        findings.append(Finding(
            cell="*", metric="schema", status="schema",
            note=(f"baseline schema {baseline.get('schema')!r} != "
                  f"{BASELINE_SCHEMA!r}")))
        return findings
    if result.get("matrix") != baseline.get("matrix"):
        findings.append(Finding(
            cell="*", metric="matrix", status="schema",
            note=(f"matrix kind {result.get('matrix')!r} does not match "
                  f"baseline {baseline.get('matrix')!r}")))
        return findings

    base_prov = baseline.get("provenance") or {}
    fresh_prov = result.get("provenance") or {}
    drifted = [
        f"{lib} {fresh_prov[lib]} vs baseline's {base_prov[lib]}"
        for lib in ("jax", "numpy")
        if base_prov.get(lib) and fresh_prov.get(lib)
        and base_prov[lib] != fresh_prov[lib]
    ]
    if drifted:
        # a jax/XLA or numpy upgrade can move the chaotic search
        # trajectory past the hard quality bands: under drift,
        # quality-band excursions gate SOFT (the backstops stay hard)
        # so an unpinned dev machine isn't red after every release —
        # CI pins both libraries to the baseline's provenance, so the
        # quality gate stays hard where it matters
        findings.append(Finding(
            cell="*", metric="provenance", status="note",
            note=(", ".join(drifted) + " — quality-band excursions "
                  "gate soft under version drift; re-pin via `bench "
                  "run --repeats 2 --baseline-out`")))

    bands = _bands_of(baseline)
    cpu = result.get("platform") == "cpu"
    cpu_factor = float(baseline.get(
        "cpu_throughput_band_factor", CPU_THROUGHPUT_BAND_FACTOR))
    cells = result.get("cells", {})
    for cid, base_cell in sorted(baseline.get("cells", {}).items()):
        if cells_filter is not None and cid not in cells_filter:
            continue
        fresh_cell = cells.get(cid)
        if fresh_cell is None:
            err = (result.get("failures", {}).get(cid) or {}).get("error")
            findings.append(Finding(
                cell=cid, metric="*", status="missing_cell",
                note=err or "cell absent from fresh result"))
            continue
        for metric, band in bands.items():
            base = base_cell["metrics"].get(metric)
            fresh = fresh_cell["metrics"].get(metric)
            if base is None:
                continue
            if fresh is None:
                findings.append(Finding(
                    cell=cid, metric=metric, status="regression",
                    base=base, note="metric missing from fresh result"))
                continue
            if not math.isfinite(fresh) or not math.isfinite(base):
                # every NaN comparison is False — without this check a
                # quality collapse to NaN/inf would gate as "ok", and a
                # NaN pinned into the baseline (json.dump writes it)
                # would silently disable the metric forever
                findings.append(Finding(
                    cell=cid, metric=metric, status="regression",
                    base=base, fresh=fresh,
                    note=(f"non-finite value (base={base!r}, "
                          f"fresh={fresh!r})")))
                continue
            widen = (cpu_factor
                     if cpu and band.kind == "throughput" else 1.0)
            margin = (band.rel * widen) * abs(base) + band.abs * widen
            if band.direction == "higher":
                allowed = base + margin
                # ceiling floored at the UN-widened abs band (the
                # headroom near base~0), never the widened margin —
                # else the ceiling re-opens the hole it plugs
                allowed = min(allowed,
                              max(base * BLOWUP_CEILING_FACTOR,
                                  band.abs))
                regressed = fresh > allowed
                improved = fresh < base - margin
            else:
                allowed = base - margin
                if base > 0:
                    allowed = max(allowed,
                                  base * COLLAPSE_FLOOR_FRACTION)
                regressed = fresh < allowed
                improved = fresh > base + margin
            status = ("regression" if regressed
                      else "improvement" if improved else "ok")
            softenable = (
                (cpu and band.kind == "throughput")  # wall-clock does
                # not transfer across hosts
                or (bool(drifted) and band.kind == "quality")  # the
                # trajectory legitimately moves across jax/numpy
                # releases; CI pins versions so this never fires there
            )
            if status == "regression" and softenable:
                # a band excursion is a soft (reported, non-failing)
                # finding unless it crosses the collapse floor /
                # blowup ceiling — those backstops always gate hard
                if band.direction == "lower":
                    hard = base > 0 and (
                        fresh < base * COLLAPSE_FLOOR_FRACTION)
                else:
                    hard = fresh > max(base * BLOWUP_CEILING_FACTOR,
                                       band.abs)
                if not hard:
                    status = "soft"
            findings.append(Finding(
                cell=cid, metric=metric, status=status,
                base=base, fresh=fresh, allowed=allowed))
    # fresh cells the baseline doesn't know (a newly added variant)
    # are UNGATED — surface that, non-failing, so a green gate can't
    # silently imply coverage the baseline doesn't provide
    for cid in sorted(set(cells) - set(baseline.get("cells", {}))):
        findings.append(Finding(
            cell=cid, metric="*", status="note",
            note=("cell not in baseline — ungated; re-pin the "
                  "baseline to cover it")))
    return findings


def gate_failed(findings: List[Finding]) -> bool:
    return any(f.status in ("regression", "missing_cell", "schema")
               for f in findings)


def format_findings(findings: List[Finding],
                    verbose: bool = False) -> str:
    lines: List[str] = []
    for f in findings:
        if f.status == "ok" and not verbose:
            continue
        if f.status in ("regression", "soft", "improvement", "ok"):
            lines.append(
                f"{f.status.upper():<12} {f.cell:<18} {f.metric:<15} "
                f"base={f.base:.6g} fresh={f.fresh:.6g} "
                f"allowed={f.allowed:.6g}"
                if f.fresh is not None and f.allowed is not None else
                f"{f.status.upper():<12} {f.cell:<18} {f.metric:<15} "
                f"{f.note}")
        else:
            lines.append(
                f"{f.status.upper():<12} {f.cell:<18} {f.metric:<15} "
                f"{f.note}")
    n_reg = sum(f.status == "regression" for f in findings)
    n_soft = sum(f.status == "soft" for f in findings)
    n_miss = sum(f.status == "missing_cell" for f in findings)
    n_imp = sum(f.status == "improvement" for f in findings)
    n_ok = sum(f.status == "ok" for f in findings)
    lines.append(
        f"gate: {n_ok} ok, {n_imp} improved, {n_reg} regressed"
        + (f", {n_soft} soft (non-failing)" if n_soft else "")
        + f", {n_miss} missing — "
        + ("FAIL" if gate_failed(findings) else "PASS"))
    return "\n".join(lines)
