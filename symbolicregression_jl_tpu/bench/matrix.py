"""The fixed benchmark matrix: variants x seeds, one subprocess per cell.

``run_matrix`` executes plain / template / parametric / island-sharded
cells at 2 seeds each (CPU-sized ``mini`` shapes for CI; chip-sized
``full`` via ``bench run --full``), telemetry on, and collects per-cell
metrics from the graftscope JSONL (bench/cell.py + bench/extract.py).
Results are schema-versioned (``graftbench.result.v1``) so the gate can
refuse to diff apples against oranges.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .cell import CELL_SENTINEL, FULL, MINI, VARIANTS

__all__ = ["RESULT_SCHEMA", "MATRIX_SHAPES", "library_provenance",
           "matrix_cells", "run_matrix"]


def library_provenance() -> Dict[str, Optional[str]]:
    """jax/numpy versions behind a result or baseline. Quality bands
    gate hard, and a jax/XLA upgrade can legitimately move the chaotic
    search trajectory — the gate surfaces a version mismatch loudly so
    a red gate right after an upgrade reads as "re-pin the baseline",
    not as a mystery regression."""
    versions: Dict[str, Optional[str]] = {}
    for name in ("jax", "numpy"):
        try:
            versions[name] = __import__(name).__version__
        except Exception:  # noqa: BLE001 - provenance is best-effort
            versions[name] = None
    return versions

RESULT_SCHEMA = "graftbench.result.v1"

MATRIX_SHAPES = {"mini": MINI, "full": FULL}

DEFAULT_SEEDS = (0, 1)

# Per-cell subprocess budget: a hung cell must fail the matrix, not
# wedge CI (mirrors the per-leg dryrun budgets, __graft_entry__.py).
CELL_TIMEOUT_S = float(os.environ.get("SR_BENCH_CELL_BUDGET", 600))


def matrix_cells(
    variants: Sequence[str] = VARIANTS,
    seeds: Sequence[int] = DEFAULT_SEEDS,
) -> List[Tuple[str, str, int]]:
    """[(cell_id, variant, seed)] for the requested matrix slice."""
    bad = [v for v in variants if v not in VARIANTS]
    if bad:
        raise ValueError(f"unknown variants {bad}; pick from {VARIANTS}")
    return [(f"{v}/seed{s}", v, s) for v in variants for s in seeds]


def _cell_env(variant: str, shape: Dict[str, Any], matrix: str
              ) -> Dict[str, str]:
    env = dict(os.environ)
    env.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")
    if matrix == "mini":
        # the CI matrix is a CPU matrix even on a chip host — the gate
        # baselines are platform-tagged and CPU-calibrated
        env["JAX_PLATFORMS"] = "cpu"
    if variant.startswith("sharded"):  # sharded + sharded-mesh
        shards = int(shape.get("shards") or 0)
        if shards > 1:
            # must be set before the child imports jax; append so a
            # pre-set XLA_FLAGS keeps its other flags (XLA takes the
            # last occurrence of a repeated flag — examples/multi_device)
            flag = f"--xla_force_host_platform_device_count={shards}"
            if flag not in env.get("XLA_FLAGS", "").split():
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "") + " " + flag).strip()
    return env


def _run_cell_subprocess(
    cell_id: str, variant: str, seed: int, shape: Dict[str, Any],
    matrix: str, workdir: str,
) -> Dict[str, Any]:
    spec = {
        "cell_id": cell_id, "variant": variant, "seed": seed,
        "shape": shape, "out_dir": os.path.join(workdir, "cells"),
    }
    cmd = [sys.executable, "-m", "symbolicregression_jl_tpu.bench",
           "_cell", json.dumps(spec)]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True,
            env=_cell_env(variant, shape, matrix),
            timeout=CELL_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return {"cell_id": cell_id, "variant": variant, "seed": seed,
                "error": f"cell timeout after {CELL_TIMEOUT_S:.0f}s"}
    wall = time.perf_counter() - t0
    line = next(
        (ln for ln in reversed(proc.stdout.splitlines())
         if ln.startswith(CELL_SENTINEL + " ")), None)
    if proc.returncode != 0 or line is None:
        return {
            "cell_id": cell_id, "variant": variant, "seed": seed,
            "error": (f"cell exited rc={proc.returncode} without a "
                      f"result line: {proc.stderr[-500:]}"),
        }
    try:
        rec = json.loads(line[len(CELL_SENTINEL) + 1:])
    except json.JSONDecodeError as e:
        # a corrupt sentinel line (interleaved stdout, partial flush)
        # is that CELL's failure, not the whole matrix run's
        return {"cell_id": cell_id, "variant": variant, "seed": seed,
                "error": f"unparseable cell result line: {e}"}
    rec["subprocess_wall_s"] = round(wall, 2)
    return rec


def run_matrix(
    *,
    matrix: str = "mini",
    variants: Sequence[str] = VARIANTS,
    seeds: Sequence[int] = DEFAULT_SEEDS,
    workdir: Optional[str] = None,
    log=print,
) -> Dict[str, Any]:
    """Run the matrix; returns the schema-versioned result record.

    Cells that fail land in ``failures`` (with stderr tails) instead of
    ``cells`` — the gate treats a baseline cell missing from a fresh
    result as a hard regression, so a crashing variant cannot silently
    drop out of coverage.
    """
    shape = MATRIX_SHAPES[matrix]
    workdir = workdir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "graftbench")
    os.makedirs(workdir, exist_ok=True)
    cells: Dict[str, Any] = {}
    failures: Dict[str, Any] = {}
    t0 = time.time()
    for cell_id, variant, seed in matrix_cells(variants, seeds):
        rec = _run_cell_subprocess(
            cell_id, variant, seed, shape, matrix, workdir)
        if "error" in rec:
            failures[cell_id] = rec
            log(f"  {cell_id:<18} FAILED: {rec['error'][:120]}")
        else:
            cells[cell_id] = rec
            m = rec["metrics"]
            bl = m.get("best_loss")
            log(f"  {cell_id:<18} "
                f"evals/s={(m.get('evals_per_sec') or 0):.0f} "
                f"best_loss={'-' if bl is None else format(bl, '.4g')} "
                f"recompiles={m.get('recompiles')} "
                f"({rec['wall_s']:.1f}s)")
    # platform from what the cells actually ran on (each records
    # jax.default_backend()), not the matrix kind: a --full run on a
    # CPU-only host must still get the CPU throughput-band widening
    backends = {rec.get("backend") for rec in cells.values()}
    platform = ("cpu" if backends <= {"cpu"} else "device")
    return {
        "schema": RESULT_SCHEMA,
        "matrix": matrix,
        "platform": platform,
        "provenance": library_provenance(),
        "t": time.time(),
        "wall_s": round(time.time() - t0, 1),
        "shape": shape,
        "cells": cells,
        "failures": failures,
    }
