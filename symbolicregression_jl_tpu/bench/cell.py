"""One matrix cell: a small, fully-specified search run with telemetry on.

Executed in a SUBPROCESS per cell (``python -m
symbolicregression_jl_tpu.bench _cell '<spec json>'``) so every cell
gets a clean jax session — the sharded cell needs
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` set before jax
imports, and per-cell process isolation keeps one cell's compile cache
pollution, retrace state, or crash from contaminating the rest of the
matrix. The parent (bench/matrix.py) sets the env and parses the
``GRAFTBENCH_CELL`` JSON line this module prints.

Metrics come from the cell's graftscope telemetry JSONL via
bench/extract.py — not from ad-hoc timers — so the gate measures
exactly what production observability reports. That automatically
includes the graftgauge ride-along metrics ("peak_live_bytes",
"anomalies"): each cell records its memory watermark, and `bench
trend` surfaces footprint creep across rounds without the gate diffing
platform-dependent byte counts.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict

CELL_SENTINEL = "GRAFTBENCH_CELL"

# Mini (CPU/CI) shapes: small enough that a full 4x2 matrix stays in
# CI budget (each cell ~10-45 s on CPU incl. trace; the persistent
# compile cache below makes repeat geometries cheap), big enough that
# quality metrics move when the search regresses. Chip-sized shapes
# (--full) mirror the bench.py headline config.
MINI = dict(rows=128, populations=4, population_size=16,
            ncycles=8, maxsize=8, niterations=3,
            tournament_selection_n=4, shards=2)
FULL = dict(rows=10_000, populations=512,
            population_size=256, ncycles=100, maxsize=30, niterations=3,
            tournament_selection_n=16, shards=0)  # 0 = all devices

# "sharded" = legacy GSPMD island sharding; "sharded-mesh" = the same
# problem/shapes on the graftmesh shard_map runtime (mesh/MeshEngine,
# per-shard finalize-dedup, explicit collectives) so mesh perf/quality
# is gated from day one (docs/SCALING.md). The "plain-staged" /
# "plain-bf16" / "plain-staged-bf16" variants are the plain cell with
# the graftstage modes on (docs/PRECISION.md) — same problem, same
# shapes, so their quality gates measure exactly what staging/bf16
# trade away.
VARIANTS = ("plain", "template", "parametric", "sharded", "sharded-mesh",
            "plain-staged", "plain-bf16", "plain-staged-bf16")


def _problem(shape: Dict[str, Any], variant: str):
    """Deterministic per-variant problem. The rng seed is FIXED (1234):
    the search seed varies across matrix cells, the data never does —
    quality deltas then attribute to the search, not the sample."""
    import numpy as np

    rng = np.random.default_rng(1234)
    n = int(shape["rows"])
    X = rng.uniform(-2.0, 2.0, (n, 2)).astype(np.float32)
    extra = None
    if variant == "template":
        # truth matches the template structure f(x1)^2 + g(x2)
        y = ((1.5 * X[:, 0]) ** 2 + np.cos(2.0 * X[:, 1])
             ).astype(np.float32)
    elif variant == "parametric":
        category = rng.integers(0, 3, n)
        amp = np.array([1.0, 2.0, 3.0], np.float32)[category]
        y = (amp * np.cos(X[:, 0]) + X[:, 1]).astype(np.float32)
        extra = {"class": category}
    else:  # plain / sharded share the problem; only the mesh differs
        y = (np.cos(2.13 * X[:, 0]) + 0.5 * X[:, 1]).astype(np.float32)
    return X, y, extra


def _options(shape: Dict[str, Any], variant: str, out_dir: str):
    from ..core.options import Options
    from ..models import template_spec
    from ..models.spec import ParametricExpressionSpec

    spec = None
    if variant == "template":
        spec = template_spec(expressions=("f", "g"))(
            lambda f, g, x1, x2: f(x1) * f(x1) + g(x2)
        )
    elif variant == "parametric":
        spec = ParametricExpressionSpec(max_parameters=1)
    staged = variant in ("plain-staged", "plain-staged-bf16")
    bf16 = variant in ("plain-bf16", "plain-staged-bf16")
    return Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=int(shape["maxsize"]),
        populations=int(shape["populations"]),
        population_size=int(shape["population_size"]),
        ncycles_per_iteration=int(shape["ncycles"]),
        tournament_selection_n=int(shape["tournament_selection_n"]),
        optimizer_probability=0.0,  # keep mini cells deterministic-fast
        expression_spec=spec,
        output_directory=out_dir,
        telemetry=True,
        eval_precision="bf16" if bf16 else "f32",
        staged_eval=staged,
    )


def run_cell(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Run one cell per its JSON spec; returns the cell result record
    (metrics extracted from the telemetry JSONL)."""
    # Persistent XLA compile cache: matrix cells are subprocesses, and
    # without it every cell would pay full compile for an identical
    # geometry (quality_bench.py sets the same knob for its legs).
    import jax

    cache = os.path.join(
        tempfile.gettempdir(), f"jax_graftbench_cache_{os.getuid()}")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from ..api.search import equation_search
    from ..telemetry.schema import load_events
    from .extract import extract_metrics

    variant = spec["variant"]
    seed = int(spec["seed"])
    shape = dict(spec["shape"])
    cell_id = spec["cell_id"]
    out_dir = spec["out_dir"]
    run_id = cell_id.replace("/", "_")

    X, y, extra = _problem(shape, variant)
    options = _options(shape, variant, out_dir)

    runtime_options = None
    if variant == "sharded-mesh":
        from ..api.search import RuntimeOptions

        runtime_options = RuntimeOptions(
            niterations=int(shape["niterations"]), mesh_runtime=True,
        )

    t0 = time.perf_counter()
    equation_search(
        X, y, options=options, extra=extra,
        niterations=int(shape["niterations"]),
        runtime_options=runtime_options,
        verbosity=0, run_id=run_id, seed=seed,
    )
    wall_s = time.perf_counter() - t0

    telemetry_path = os.path.join(out_dir, run_id, "telemetry.jsonl")
    metrics = extract_metrics(load_events(telemetry_path))
    return {
        "cell_id": cell_id,
        "variant": variant,
        "seed": seed,
        "n_devices": len(jax.devices()),
        "backend": jax.default_backend(),
        "wall_s": round(wall_s, 2),
        "telemetry": telemetry_path,
        "metrics": metrics,
    }


def cell_main(spec_json: str) -> int:
    """Subprocess entry: run the cell, print the sentinel result line."""
    rec = run_cell(json.loads(spec_json))
    print(f"{CELL_SENTINEL} {json.dumps(rec)}", flush=True)
    return 0
