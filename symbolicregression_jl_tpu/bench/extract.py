"""Metric extraction: graftscope JSONL -> the gate's flat metric dict.

The matrix runner never times anything itself — every gated number
comes out of the same telemetry stream production runs emit
(telemetry/report.py's :func:`~..telemetry.report.metrics_view`), so a
perf regression visible to the gate is by construction visible to
observability, and vice versa.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..telemetry.report import metrics_view, summarize

__all__ = ["extract_metrics", "GATE_METRIC_KEYS"]

# The subset of metrics_view keys the regression gate diffs; the rest
# ride along in result files as context (docs/BENCHMARKING.md).
# Ride-along (NOT gated) examples: "anomalies" (graftpulse detector
# events) and "peak_live_bytes" (graftgauge memory watermark — `bench
# trend` displays the worst cell, but absolute byte counts are too
# platform-dependent to diff against a committed baseline).
GATE_METRIC_KEYS = (
    "evals_per_sec", "best_loss", "pareto_volume", "host_fraction",
    "recompiles",
)


def extract_metrics(events: List[dict]) -> Dict[str, Any]:
    """Flat per-cell metrics from a validated graftscope event list."""
    return metrics_view(summarize(events))
