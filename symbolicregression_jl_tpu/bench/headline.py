"""Headline benchmark: full-dataset expression evaluations per second.

Mirrors the reference's primary live metric — "full dataset evaluations
per second" (Δnum_evals/Δt, /root/reference/src/SymbolicRegression.jl:1158-1171)
— on the reference benchmark problem (benchmarks.jl: 5 features, ops
{+,-,*,/} ∪ {exp,abs}, maxsize=30, target
cos(2.13x₁)+0.5x₂|x₃|^0.9−0.3|x₄|^1.5) scaled to the BASELINE.json
north-star 10k-row dataset.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}. The
repo-root ``bench.py`` is a thin wrapper over this module (the driver
runs ``python bench.py`` every round and archives the line as
BENCH_r0N.json — ``bench trend`` folds that history).

`vs_baseline` compares against the MEASURED CPU-multithreaded rate:
profiling/cpu_baseline.py measures a per-node-vectorized numpy
evaluator at 8.1e3 evals/s *per core* on this host
(transcendental-dominated, within a small factor of the reference's
fused LoopVectorization interpreter per core), i.e. ~6.5e4 evals/s for
an 8-core multithreaded host. Rounds 1-3 reported against a 1e4
round-1 estimate (a 1-2-core rate); that legacy ratio is demoted to
the `vs_baseline_legacy_1e4` field for cross-round continuity
(BENCH_r01-r03 used it).
"""

from __future__ import annotations

import json
import time

import numpy as np

from .projection import v5e8_comm_efficiency

MEASURED_CPU_EVALS_PER_SEC = 6.5e4   # 8-core extrapolation, BASELINE.md
LEGACY_CPU_EVALS_PER_SEC = 1.0e4     # round-1 estimate (1-2 cores)

N_ROWS = 10_000
N_FEATURES = 5
WARMUP_ITERS = 1
MEASURE_ITERS = 3


def main() -> None:
    import argparse

    import jax

    from .. import Options, search_key
    from ..core.dataset import make_dataset
    from ..evolve.engine import Engine
    from ..evolve.step import resolve_sample_rows
    from ..telemetry.schema import SCHEMA_VERSION

    # graftstage A/B knobs (docs/PRECISION.md): the headline defaults to
    # the committed f32/full-eval config; --staged / --bf16 measure the
    # staged sample-then-rescore path and the bf16 row tiles on the same
    # problem. The emitted provenance block always records which mode
    # produced the number.
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--staged", action="store_true",
                    help="staged sample-then-rescore candidate eval")
    ap.add_argument("--bf16", action="store_true",
                    help="bf16 eval row tiles (f32 reduction spine)")
    ap.add_argument("--sample-fraction", type=float, default=0.125,
                    help="screening sample fraction (staged mode)")
    ap.add_argument("--rescore-fraction", type=float, default=0.25,
                    help="full-eval rescore fraction (staged mode)")
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (N_ROWS, N_FEATURES)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
        + 1e-1 * rng.standard_normal(N_ROWS)
    ).astype(np.float32)

    # Island count is the TPU-native scaling axis (SURVEY.md §2.4): more
    # islands amortize the per-cycle machinery over more concurrent
    # evaluations in the same launches (profiling/config_sweep.py picks
    # the per-chip config); with multiple devices visible the island
    # axis shards over them — the multi-chip number is one
    # `python bench.py` away, with 512 LOCAL islands per chip.
    n_dev = len(jax.devices())
    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        populations=512 * n_dev,  # island count peaks at 512 on v5e-1
        population_size=256,  # (profiling/config_sweep.py, round 3)
        tournament_selection_n=16,
        ncycles_per_iteration=100,
        save_to_file=False,
        eval_precision="bf16" if args.bf16 else "f32",
        staged_eval=args.staged,
        staged_sample_fraction=args.sample_fraction,
        rescore_fraction=args.rescore_fraction,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)

    mesh = None
    if n_dev > 1:
        from ..parallel.mesh import (
            make_mesh, shard_device_data, shard_search_state)

        mesh = make_mesh(jax.devices(), n_island_shards=n_dev)
        engine = Engine(options, ds.nfeatures, n_island_shards=n_dev,
                        mesh=mesh)
        data = shard_device_data(ds.data, mesh)
    else:
        engine = Engine(options, ds.nfeatures)
        data = ds.data

    state = engine.init_state(
        search_key(0), data, options.populations
    )
    if mesh is not None:
        state = shard_search_state(state, mesh)

    # Warmup (compile) iterations, excluded from timing.
    for _ in range(WARMUP_ITERS):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    evals_before = float(state.num_evals)

    t0 = time.perf_counter()
    for _ in range(MEASURE_ITERS):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    elapsed = time.perf_counter() - t0

    evals = float(state.num_evals) - evals_before
    rate = evals / elapsed
    rec = {
        "metric": "full_dataset_expr_evals_per_sec_10k_rows",
        "value": round(rate, 1),
        "unit": "evals/s",
        "vs_baseline": round(rate / MEASURED_CPU_EVALS_PER_SEC, 3),
        "vs_baseline_legacy_1e4": round(
            rate / LEGACY_CPU_EVALS_PER_SEC, 3),
        "n_devices": n_dev,
        # Candidate-eval path provenance (round 6): the in-kernel
        # loss->cost epilogue state and launch geometry, so headline
        # deltas across rounds attribute to the right knob.
        "fuse_cost_epilogue": bool(engine.cfg.fuse_cost),
        "eval_tree_block": engine.cfg.eval_tree_block,
        "eval_tile_rows": engine.cfg.eval_tile_rows,
        # graftstage provenance (round 7, docs/PRECISION.md): the eval
        # precision and staging geometry behind the number — BENCH_r0*
        # artifacts stay self-describing across the new variants.
        "eval_precision": "bf16" if engine.cfg.eval_bf16 else "f32",
        "staged_eval": bool(engine.cfg.staged_eval),
        "staged_sample_rows": (
            resolve_sample_rows(engine.cfg, N_ROWS)
            if engine.cfg.staged_eval else None),
        "rescore_fraction": (
            engine.cfg.rescore_fraction
            if engine.cfg.staged_eval else None),
        # graftscope provenance (round 7): whether the device counters
        # rode the measured iterations (they are off for the headline —
        # the bench measures the bare hot loop) and the schema version a
        # telemetry-enabled rerun of this config would emit, so bench
        # JSON and telemetry JSONL from the same build can be joined.
        "telemetry": {
            "schema": SCHEMA_VERSION,
            "counters_enabled": bool(engine.cfg.collect_telemetry),
        },
    }
    if n_dev == 1:
        # Projected v5e-8: measured single-chip rate x 8 devices x the
        # communication-bound efficiency from the closed-form ICI model
        # (the per-chip program at 512 local islands IS the measured
        # single-chip program; migration/HoF collectives are the only
        # cross-chip traffic, < 0.2% of iteration time at the
        # partitioner's worst-case bound). Outside a repo checkout the
        # model file is absent — the measured line still prints, just
        # without the projection fields.
        try:
            eff, src = v5e8_comm_efficiency(
                elapsed / MEASURE_ITERS,
                islands=512 * 8, population_size=256, maxsize=30,
                topn=12, n_devices=8, ici_gbps=400.0,
            )
        except FileNotFoundError:
            eff = None
        if eff is not None:
            proj = rate * 8 * min(eff, 1.0)
            rec["projected_v5e8"] = round(proj, 1)
            rec["projected_v5e8_vs_baseline"] = round(
                proj / MEASURED_CPU_EVALS_PER_SEC, 2)
            rec["projection_comm_efficiency"] = round(min(eff, 1.0), 4)
            rec["projection_source"] = src
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
