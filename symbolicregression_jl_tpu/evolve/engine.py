"""The per-iteration engine: islands evolve, optimize, simplify, migrate.

One call = one reference "iteration" for *all* islands at once
(the reference dispatches each (output, population) pair to a worker,
src/SymbolicRegression.jl:1253-1296; here the island axis is vmapped and
sharded over the device mesh, so the whole iteration is one XLA program):

    s_r_cycle (ncycles of bulk generation steps, annealing ramp)
    -> optimize_and_simplify_population (constant folding + batched BFGS)
    -> finalize costs (full-dataset re-eval when batching)
    -> hall-of-fame merge across islands
    -> migration (island <- best-sub-pops of all islands, island <- HoF)
    -> running-statistics update (frequency histogram, windowing)

Lineage ref rotation mirrors src/SingleIteration.jl:99-137.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P_

from ..core.dataset import DeviceData
from ..parallel.mesh import ISLAND_AXIS

try:  # jax >= 0.8: stable API (check_rep became check_vma)
    from jax import shard_map as _jax_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _jax_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map
from ..core.losses import loss_to_cost
from ..core.options import Options
from ..ops.complexity import ComplexityTables, build_complexity_tables, \
    compute_complexity_batch
from ..ops.encoding import TreeBatch
from .constant_opt import (
    OptimizerConfig,
    optimize_constants_batch,
    optimize_constants_fused,
)
from .population import PopulationState, init_params, init_population
from .simplify import fold_constants_batch
from .step import (
    EvolveConfig,
    HofState,
    _member_take_onehot,
    empty_hof,
    eval_cost_batch,
    evolve_config_from_options,
    s_r_cycle,
    update_hof,
)

__all__ = ["SearchDeviceState", "Engine"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class RunningStats:
    """RunningSearchStatistics (src/AdaptiveParsimony.jl:20-32)."""

    frequencies: jax.Array            # [maxsize]
    normalized_frequencies: jax.Array  # [maxsize]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SearchDeviceState:
    """All device-resident search state (the SearchState analogue,
    src/SearchUtils.jl:584-603, minus host bookkeeping)."""

    pops: PopulationState   # leading island axis [I, P, ...]
    hof: HofState           # global best-per-complexity [maxsize, ...]
    stats: RunningStats
    birth: jax.Array        # [I] int32 per-island birth counters
    ref: jax.Array          # [I] int32 per-island lineage counters
    num_evals: jax.Array    # scalar float32
    key: jax.Array          # PRNG key
    # graftscope counters for the LAST iteration (options.telemetry;
    # telemetry/counters.py IterationTelemetry, summed over islands) —
    # reset in-graph each iteration, fetched by the host Telemetry hub
    # with the per-iteration state pull. None when telemetry is off.
    telem: Optional[object] = None


def _move_window(freq, window_size: float, maxsize: int):
    """Shrink frequencies toward 1 so they sum to window_size
    (move_window!, src/AdaptiveParsimony.jl:55-87; smooth equivalent of the
    reference's iterative uniform subtraction)."""
    total = jnp.sum(freq)
    excess_scale = (window_size - maxsize) / jnp.maximum(total - maxsize, 1e-9)
    scaled = 1.0 + (freq - 1.0) * jnp.minimum(excess_scale, 1.0)
    return jnp.where(total > window_size, scaled, freq)


class Engine:
    """Holds jitted computation for a fixed (options, dataset-shape) pair."""

    def __init__(self, options: Options, nfeatures: int, dtype=jnp.float32,
                 window_size: int = 100_000, n_params: int = 0,
                 n_classes: int = 0, template=None, n_data_shards: int = 1,
                 n_island_shards: int = 1, mesh=None):
        self.options = options
        self.nfeatures = nfeatures
        self.dtype = dtype
        self.template = template
        self.n_island_shards = n_island_shards
        self.mesh = mesh
        if template is not None:
            # Template parameters ride the per-member parameter storage
            # as a flat [total_params, 1] bank.
            n_params = template.total_params
            n_classes = 1 if n_params else 0
        self.cfg: EvolveConfig = evolve_config_from_options(
            options, nfeatures, n_params, n_classes, template=template,
            n_data_shards=n_data_shards, n_island_shards=n_island_shards,
        )
        # Pallas kernels have no GSPMD partitioning rule: when the island
        # axis is sharded AND turbo is on, the island-local phases run
        # under shard_map so each device drives its own kernel launches
        # on local shards (SURVEY.md §2.4 TPU mapping; the jnp fallback
        # partitions cleanly and needs none of this).
        if self.cfg.turbo and n_island_shards > 1 and mesh is None:
            # Without the mesh the island-local phases cannot be
            # shard_map'ed and the Pallas kernels would hit GSPMD with
            # no partitioning rule — fall back to the jnp interpreter,
            # which partitions cleanly. (The cost epilogue lives in the
            # fused kernel, so it goes with it.)
            self.cfg = self.cfg._replace(turbo=False, fuse_cost=False)
        self._shard_islands = (
            self.cfg.turbo and n_island_shards > 1 and mesh is not None
        )
        self.tables: ComplexityTables = build_complexity_tables(options, nfeatures)
        self.opt_cfg = OptimizerConfig(
            iterations=options.optimizer_iterations,
            nrestarts=options.optimizer_nrestarts,
            # bf16 step-size selection only on the real-TPU fused path
            # (interpret-mode runs keep f32 so CPU tests match the
            # reference semantics bit-for-bit).
            ls_bf16=(options.optimizer_bf16_linesearch
                     and self.cfg.turbo and not self.cfg.interpret),
        )
        self.window_size = float(window_size)
        self._build_jits()

    def _build_jits(self) -> None:
        """(Re)create the jitted entry points against the CURRENT
        ``self.cfg`` and drop every cached compiled program. Called once
        from ``__init__`` and again by ``degrade_eval_tile_rows`` — the
        graftshield degradation ladder rewrites the launch geometry and
        the old traces must not serve it."""
        self._iteration = jax.jit(self._iteration_impl, donate_argnums=(0,))
        self._init_state = jax.jit(self._init_state_impl, static_argnums=(2,))
        for attr in ("_chunk_cache", "_epilogue_jit", "_prelude_jit",
                     "_reseed_jit", "_invalid_frac_jit"):
            if hasattr(self, attr):
                delattr(self, attr)

        # (cost, loss, complexity) for a flat batch of host-encoded trees —
        # the guess-seeding / warm-start re-eval path.
        def eval_cost_flat(trees, data, member_params=None):
            return eval_cost_batch(
                trees, data, self.options.elementwise_loss, self.tables,
                self.cfg.operators, self.cfg.parsimony,
                member_params=member_params,
                turbo=self.cfg.turbo, interpret=self.cfg.interpret,
                loss_function=self.options.resolved_loss_function,
                dim_penalty=self.cfg.dim_penalty,
                wildcard_constants=self.cfg.wildcard_constants,
                template=self.cfg.template,
                tree_block=self.cfg.eval_tree_block,
                tile_rows=self.cfg.eval_tile_rows,
                fuse_cost=self.cfg.fuse_cost,
                bf16=self.cfg.eval_bf16,
            )

        self._eval_cost = jax.jit(eval_cost_flat)

    def degrade_eval_tile_rows(self, floor: int = 512) -> Optional[int]:
        """graftshield degradation step (shield/degrade.py): halve the
        candidate-eval kernel's row-tile cap and drop the compiled
        programs so the next dispatch re-lowers at the smaller launch
        geometry (smaller live buffers per launch under RESOURCE_
        EXHAUSTED pressure). Returns the new tile rows, or None when
        already at the floor (the ladder is exhausted)."""
        cur = int(self.cfg.eval_tile_rows)
        new = max(cur // 2, int(floor))
        if new >= cur:
            return None
        self.cfg = self.cfg._replace(eval_tile_rows=new)
        self._build_jits()
        return new

    @property
    def n_params(self) -> int:
        return self.cfg.n_params

    @property
    def n_classes(self) -> int:
        return self.cfg.n_classes

    # ------------------------------------------------------------------
    def _dedup_eligible(self) -> bool:
        """Whether the fused finalize-dedup path exists for this config
        (plain f32 expressions on the fused kernel; see
        ops/fused_eval.fused_loss_dedup's caller gates)."""
        cfg = self.cfg
        return cfg.turbo and cfg.template is None and cfg.n_params == 0

    def _use_dedup(self, sharded: bool) -> bool:
        """Finalize-dedup policy hook. The legacy engine forfeits dedup
        whenever the island axis is sharded (its dup-stats/global view
        would sort across devices every iteration); mesh.MeshEngine
        overrides this to run the dedup PER SHARD inside shard_map,
        which is bit-exact and needs no collective."""
        del sharded
        return self._dedup_eligible() and self.n_island_shards == 1

    def _epilogue_draws(self, k_opt, I: int):
        """The epilogue's host-static optimizer-selection sizing plus
        its island-major random draws — one definition shared by the
        legacy and mesh epilogues so the streams can never diverge
        between runtimes. Returns ``(k_sel, scores, gate, ko2)``."""
        options = self.options
        P = self.cfg.population_size
        k_sel = max(1, round(P * options.optimizer_probability))
        gate_p = min(P * options.optimizer_probability / k_sel, 1.0)
        # static options-scalar read, not a traced value
        opt_kind_on = float(options.mutation_weights.optimize) > 0  # graftlint: disable=GL003
        if opt_kind_on:
            # Size the selection to cover the expected number of members
            # marked by `optimize`-kind mutations this iteration (the
            # reference runs its optimize branch unconditionally per
            # draw, src/Mutate.jl:571-658) — marks beyond k_sel slots
            # would otherwise be dropped.
            wvec = options.mutation_weights.as_vector()
            # static host numpy reads of options, not traced values
            frac_opt = float(options.mutation_weights.optimize) / max(  # graftlint: disable=GL003
                float(wvec.sum()), 1e-12  # graftlint: disable=GL003
            )
            expected = self.cfg.n_slots * self.cfg.ncycles * frac_opt
            k_sel = max(k_sel, min(P, math.ceil(expected)))
        do_optimize = options.should_optimize_constants and (
            options.optimizer_probability > 0 or opt_kind_on
        )
        scores = gate = None
        ko2 = k_opt
        if do_optimize:
            ko1, ko2, ko3 = jax.random.split(k_opt, 3)
            scores = jax.random.uniform(ko1, (I, P))
            gate = jax.random.bernoulli(ko3, gate_p, (I, k_sel))
        return k_sel, scores, gate, ko2

    # ------------------------------------------------------------------
    def init_state(self, key, data: DeviceData, n_islands: int,
                   initial_trees: Optional[TreeBatch] = None,
                   initial_params: Optional[jax.Array] = None) -> SearchDeviceState:
        state = self._init_state(key, data, n_islands, initial_trees,
                                 initial_params)
        if self.options.debug_checks:
            self._audit_state(state, where="init_state")
        return state

    def _audit_state(self, state: SearchDeviceState, where: str) -> None:
        """graftlint runtime audit (options.debug_checks): re-check the
        postfix-encoding invariants on the device-resident population
        after mutation/crossover/migration have rewritten it. Pulls the
        tables to host — debug tier only."""
        from ..lint.runtime import validate_programs

        cfg = self.cfg
        # Template members carry a per-slot subexpression axis whose
        # feature counts vary by slot; skip the feat-range check there.
        nfeat = None if cfg.template is not None else self.nfeatures
        n_params = None if cfg.template is not None else cfg.n_params
        validate_programs(
            state.pops.trees, cfg.operators, nfeatures=nfeat,
            n_params=n_params, where=f"engine {where}: population",
        )
        # HoF slots only exist where `exists`; empty slots hold the
        # all-padding single-constant tree, which is itself valid.
        validate_programs(
            state.hof.trees, cfg.operators, nfeatures=nfeat,
            n_params=n_params, where=f"engine {where}: hall of fame",
        )

    def _init_state_impl(self, key, data: DeviceData, n_islands: int,
                         initial_trees: Optional[TreeBatch] = None,
                         initial_params: Optional[jax.Array] = None):
        cfg = self.cfg
        P = cfg.population_size
        k_init, k_params, k_state = jax.random.split(key, 3)

        if initial_trees is None:
            keys = jax.random.split(k_init, n_islands)
            if cfg.template is not None:
                from .population import init_template_population

                trees = jax.vmap(
                    lambda k: init_template_population(
                        k, P, cfg.template, cfg.mctx, self.dtype
                    )
                )(keys)
            else:
                trees = jax.vmap(
                    lambda k: init_population(k, P, cfg.mctx, self.dtype)
                )(keys)
        else:
            trees = initial_trees
        if initial_params is None:
            params = init_params(
                k_params, (n_islands, P), cfg.n_params, cfg.n_classes, self.dtype
            )
        else:
            params = initial_params

        cost, loss, cx = jax.vmap(
            lambda t, p: eval_cost_batch(
                t, data, self.options.elementwise_loss, self.tables,
                cfg.operators, cfg.parsimony, member_params=p,
                turbo=cfg.turbo, interpret=cfg.interpret,
                loss_function=self.options.resolved_loss_function,
                dim_penalty=cfg.dim_penalty,
                wildcard_constants=cfg.wildcard_constants,
                template=cfg.template,
                tree_block=cfg.eval_tree_block,
                tile_rows=cfg.eval_tile_rows,
                fuse_cost=cfg.fuse_cost,
                bf16=cfg.eval_bf16,
            )
        )(trees, params)

        pops = PopulationState(
            trees=trees,
            cost=cost,
            loss=loss,
            complexity=cx,
            birth=jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (n_islands, P)),
            ref=jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.int32), (n_islands, P)
            ) + jnp.arange(n_islands, dtype=jnp.int32)[:, None] * 1_000_000,
            parent=jnp.full((n_islands, P), -1, jnp.int32),
            params=params,
        )
        freq = jnp.ones((cfg.maxsize,), jnp.float32)
        stats = RunningStats(
            frequencies=freq, normalized_frequencies=freq / jnp.sum(freq)
        )
        telem = None
        if cfg.collect_telemetry:
            # Pre-seed the telemetry slot so the first iteration's input
            # pytree already has the counter structure — otherwise the
            # None -> IterationTelemetry switch would cost one extra
            # trace of the iteration program.
            from ..telemetry.counters import empty_iteration_telemetry

            telem = empty_iteration_telemetry(cfg.maxsize)
        return SearchDeviceState(
            pops=pops,
            hof=empty_hof(cfg.maxsize, cfg.max_nodes, self.dtype,
                          cfg.n_params, cfg.n_classes,
                          template_k=(cfg.template.n_subexpressions
                                      if cfg.template else 0)),
            stats=stats,
            birth=jnp.full((n_islands,), P, jnp.int32),
            ref=jnp.full((n_islands,), P, jnp.int32),
            num_evals=jnp.float32(n_islands * P),
            key=k_state,
            telem=telem,
        )

    # ------------------------------------------------------------------
    def run_iteration(self, state: SearchDeviceState, data: DeviceData,
                      cur_maxsize: int,
                      chunk_sizes: Optional[Sequence[int]] = None,
                      should_stop=None):
        """One full iteration.

        ``chunk_sizes`` (summing to ``ncycles_per_iteration``) splits the
        evolve phase into multiple launches with the host ``should_stop``
        callback polled between them (budget checks — the reference
        checks per dispatched cycle batch,
        src/SymbolicRegression.jl:1202-1209). A stop mid-iteration skips
        the remaining chunks but still runs the epilogue (optimize /
        simplify / finalize / migrate) exactly once, so chunked and
        single-launch iterations are otherwise bit-identical: the
        annealing ramp and per-cycle RNG fold-ins use global cycle
        indices.

        ``cur_maxsize`` may be a host int or an already-uploaded device
        scalar: a host int costs one (tiny) host→device transfer per
        call, so hot loops that pin a transfer budget (graftlint's
        ``no_transfer`` guard) pass ``jnp.int32(cur_maxsize)`` uploaded
        once outside the loop — it only changes during maxsize warmup.
        """
        if not isinstance(cur_maxsize, jax.Array):
            cur_maxsize = jnp.int32(cur_maxsize)
        if not chunk_sizes or list(chunk_sizes) == [self.cfg.ncycles]:
            out = self._iteration(state, data, cur_maxsize)
            if self.options.debug_checks:
                new_state = out[0] if self.cfg.record_events else out
                self._audit_state(new_state, where="run_iteration")
            return out
        assert sum(chunk_sizes) == self.cfg.ncycles, (
            f"chunk_sizes {chunk_sizes} must sum to {self.cfg.ncycles}"
        )
        cfg = self.cfg
        # Same key derivation as the single-launch path (bit-identical).
        # One jitted prelude instead of ~20 eager op dispatches: on the
        # tunneled TPU backend each distinct eager op costs ~1 s of
        # one-time compile (the HoF-pytree broadcast_to alone logged
        # 19 s in profiling/compile_breakdown.py), so the first
        # iteration of a quickstart paid ~25 s here.
        cur_maxsize, key, k_cycle, k_opt, k_mig, batch_idx, carry = (
            self._prelude_fn(state.key, cur_maxsize,
                             data.y.shape[0], state.birth.shape[0],
                             state.pops.cost.dtype))
        pops, birth, ref = state.pops, state.birth, state.ref
        c0 = 0
        ev_chunks = []
        tele = None
        for i, nc in enumerate(chunk_sizes):
            fn = self._chunk_fn(nc, batching=batch_idx is not None)
            out = fn(
                pops, birth, ref, state.stats.normalized_frequencies, data,
                cur_maxsize, k_cycle, batch_idx, jnp.int32(c0), carry
            )
            pops, best_seen, nev, birth, ref, marks = out[:6]
            pos = 6
            if cfg.collect_telemetry:
                tele = out[pos]
                pos += 1
            if cfg.record_events:
                ev_chunks.append(out[pos])
            carry = (best_seen, nev, marks)
            if cfg.collect_telemetry:
                carry = carry + (tele,)
            c0 += nc
            if should_stop is not None and i < len(chunk_sizes) - 1:
                # Offer this iteration's partial evals lazily: only a
                # max_evals budget needs them, and materializing the sum
                # would force a blocking device sync per chunk for
                # everyone else (quit/timeout polls stay sync-free).
                eval_fraction = (
                    cfg.batch_size / data.y.shape[0] if cfg.batching else 1.0
                )
                chunk_nev = nev

                def pending(nv=chunk_nev, ef=eval_fraction):
                    return float(jnp.sum(nv)) * ef

                if should_stop(pending):
                    break
        evolved = (pops, best_seen, nev, birth, ref, marks)
        if cfg.collect_telemetry:
            evolved = evolved + (tele,)
        new_state = self._epilogue_fn(
            state, data, cur_maxsize, evolved, key, k_opt, k_mig, batch_idx
        )
        if self.options.debug_checks:
            self._audit_state(new_state, where="run_iteration(chunked)")
        if cfg.record_events:
            events = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=1), *ev_chunks)
            return new_state, events
        return new_state

    @property
    def _prelude_fn(self):
        """Jitted chunked-iteration prelude: key split, minibatch draw,
        and the first chunk's explicit empty carry (the same values
        s_r_cycle would build internally — one evolve program then
        serves every chunk instead of compiling a second carry-less
        variant, which costs tens of seconds at device scale)."""
        if not hasattr(self, "_prelude_jit"):
            cfg = self.cfg
            P = cfg.population_size

            def iteration_prelude(key, cur_maxsize, nrows, I, cost_dtype):
                key, k_batch, k_cycle, k_opt, k_mig = jax.random.split(key, 5)
                batch_idx = None
                if cfg.batching:
                    batch_idx = jax.random.randint(
                        k_batch, (cfg.batch_size,), 0, nrows)
                # cost_dtype (not self.dtype): must match the carry-less
                # path's pops.cost.dtype so every chunk shares one
                # compiled program and chunked == single-launch.
                hof0 = empty_hof(
                    cfg.maxsize, cfg.max_nodes, cost_dtype,
                    cfg.n_params, cfg.n_classes,
                    template_k=(cfg.template.n_subexpressions
                                if cfg.template else 0))
                carry = (
                    jax.tree.map(
                        lambda x: jnp.broadcast_to(x, (I,) + x.shape), hof0),
                    jnp.zeros((I,), jnp.float32),
                    (jnp.zeros((I, P), jnp.bool_),
                     jnp.zeros((I, P), jnp.bool_)),
                )
                if cfg.collect_telemetry:
                    from ..telemetry.counters import empty_cycle_telemetry

                    carry = carry + (jax.tree.map(
                        lambda x: jnp.broadcast_to(x, (I,) + x.shape),
                        empty_cycle_telemetry()),)
                return cur_maxsize, key, k_cycle, k_opt, k_mig, batch_idx, \
                    carry

            self._prelude_jit = jax.jit(iteration_prelude,
                                        static_argnums=(2, 3, 4))
        return self._prelude_jit

    def _chunk_fn(self, ncycles: int, batching: bool):
        """Jitted evolve-chunk for a given (static) chunk length."""
        if not hasattr(self, "_chunk_cache"):
            self._chunk_cache = {}
        k = (ncycles, batching)
        if k not in self._chunk_cache:
            cfg = self.cfg._replace(ncycles=ncycles)

            def _chunk(pops, birth, ref, stats_nf, data, cm, kc, bi, c0,
                       carry):
                return self._evolve_part(pops, birth, ref, stats_nf, data,
                                         cm, kc, bi, c0, carry, cfg)

            # Named so jax_log_compiles / compile_breakdown.py attribute
            # compile seconds to the evolve program per chunk length.
            _chunk.__name__ = f"evolve_chunk_c{ncycles}"
            self._chunk_cache[k] = jax.jit(_chunk)
        return self._chunk_cache[k]

    @property
    def _epilogue_fn(self):
        if not hasattr(self, "_epilogue_jit"):
            def iteration_epilogue(state, data, cm, evolved, key, ko, km, bi):
                return self._epilogue_part(state, data, cm, evolved, key, ko,
                                           km, bi, self.cfg)

            self._epilogue_jit = jax.jit(iteration_epilogue)
        return self._epilogue_jit

    def _evolve_part(self, pops, birth, ref, stats_nf, data, cur_maxsize,
                     k_cycle, batch_idx, c0, carry, cfg: EvolveConfig):
        """The evolve phase: cfg.ncycles bulk generation steps for all
        islands (one chunk). ``carry`` = (best_seen, nev, marks) from
        prior chunks of the same iteration.

        Under a sharded island axis with turbo, the per-island vmap runs
        inside shard_map so each device dispatches the Pallas kernels on
        its local islands (no cross-island ops exist in s_r_cycle).
        Per-island RNG keys are computed globally first, so shard layout
        never changes the streams."""
        I = birth.shape[0]
        cycle_keys = jax.random.split(k_cycle, I)
        total = self.cfg.ncycles  # the FULL iteration's cycle count
        has_batch = batch_idx is not None
        has_carry = carry is not None

        def run(ck, p, b, r, ci, snf, dat, cm, bi, c0_):
            def island_cycle(k, pop, bb, rr, cin):
                return s_r_cycle(
                    k, pop, dat, snf, cm, bb, rr, cfg,
                    self.options, self.tables,
                    self.options.elementwise_loss,
                    batch_idx=bi, c0=c0_, total_cycles=total, carry_in=cin,
                )

            if ci is None:
                return jax.vmap(
                    lambda k, pp, bb, rr: island_cycle(k, pp, bb, rr, None)
                )(ck, p, b, r)
            return jax.vmap(island_cycle)(ck, p, b, r, ci)

        args = (cycle_keys, pops, birth, ref, carry, stats_nf, data,
                cur_maxsize, batch_idx, c0)
        if not self._shard_islands:
            return run(*args)

        isl = lambda tree: jax.tree.map(lambda _: P_(ISLAND_AXIS), tree)
        rep = lambda tree: jax.tree.map(lambda _: P_(), tree)
        in_specs = (
            P_(ISLAND_AXIS), isl(pops), P_(ISLAND_AXIS), P_(ISLAND_AXIS),
            isl(carry) if has_carry else None,
            P_(), rep(data), P_(),
            P_() if has_batch else None, P_(),
        )
        out_specs = isl(jax.eval_shape(run, *args))
        return _shard_map(run, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)(*args)

    def _iteration_impl(self, state: SearchDeviceState, data: DeviceData,
                        cur_maxsize, cfg: Optional[EvolveConfig] = None):
        cfg = cfg if cfg is not None else self.cfg

        key, k_batch, k_cycle, k_opt, k_mig = jax.random.split(state.key, 5)

        # Minibatch indices: one batch per iteration, as in s_r_cycle
        # (src/SingleIteration.jl:40).
        batch_idx = None
        if cfg.batching:
            batch_idx = jax.random.randint(
                k_batch, (cfg.batch_size,), 0, data.y.shape[0]
            )

        # ---- evolve all islands: ncycles bulk generation steps ----
        evolved = self._evolve_part(
            state.pops, state.birth, state.ref,
            state.stats.normalized_frequencies, data, cur_maxsize,
            k_cycle, batch_idx, jnp.int32(0), None, cfg,
        )
        n = 7 if cfg.collect_telemetry else 6
        events = None
        if cfg.record_events:
            events = evolved[n]
            evolved = evolved[:n]
        new_state = self._epilogue_part(
            state, data, cur_maxsize, evolved, key, k_opt, k_mig, batch_idx,
            cfg,
        )
        if cfg.record_events:
            return new_state, events
        return new_state

    def _island_epilogue(self, pops: PopulationState, ref, simp_mark,
                         opt_mark, scores, gate, opt_key, data: DeviceData,
                         cur_maxsize, batch_idx, cfg: EvolveConfig,
                         k_sel: int, use_dedup: bool, sharded: bool):
        """The island-LOCAL epilogue: fold/simplify, constant optimize,
        full-dataset finalize, lineage ref rotation. No cross-island
        communication — shard_map-able over the island axis (SURVEY.md
        §2.4 TPU mapping). All random draws (``scores``, ``gate``,
        ``opt_key``) are made by the caller so shard layouts cannot
        change the streams; under shard_map the fused optimizer's key is
        decorrelated per shard via axis_index.

        Returns (pops, ref, f_calls[1]).
        """
        options = self.options
        tables = self.tables
        el_loss = options.elementwise_loss
        I = pops.cost.shape[0]  # LOCAL island count under shard_map
        P = cfg.population_size

        # ---- optimize & simplify (src/SingleIteration.jl:68-96) ----
        # `simplify`-kind mutations are deferred to here (see
        # generation_step): with should_simplify the whole population is
        # folded anyway; otherwise fold just the marked members.
        if cfg.template is not None:
            # Template members fold per subexpression
            # (simplify_tree! maps over the inner expressions,
            # /root/reference/src/TemplateExpression.jl:881-891).
            K = cfg.template.n_subexpressions

            def fold(trees):  # [I, P, K, L]
                flat = trees.reshape(I, P * K)
                out = fold_constants_batch(flat, cfg.operators)
                return out.reshape(I, P, K)
        else:
            fold = lambda t: fold_constants_batch(t, cfg.operators)
        if cfg.should_simplify:
            pops = dataclasses.replace(pops, trees=fold(pops.trees))
        # static options-scalar read, not a traced value
        elif float(options.mutation_weights.simplify) > 0:  # graftlint: disable=GL003
            folded = fold(pops.trees)
            from .mutation import _select_tree

            pops = dataclasses.replace(
                pops, trees=_select_tree(simp_mark, folded, pops.trees)
            )

        f_calls_total = jnp.zeros((1,), jnp.float32)
        # static options-scalar read, not a traced value
        opt_kind_on = float(options.mutation_weights.optimize) > 0  # graftlint: disable=GL003
        if scores is not None:
            if opt_kind_on:
                # `optimize`-kind mutations (deferred from the cycle; see
                # generation_step) claim selection slots first and bypass
                # the probability gate (src/Mutate.jl's optimize branch
                # runs unconditionally on the member).
                scores = scores + 10.0 * opt_mark.astype(scores.dtype)
            _, sel_idx = jax.lax.top_k(scores, k_sel)  # [I, k_sel]
            if opt_kind_on:
                sel_marked = jnp.take_along_axis(opt_mark, sel_idx, axis=1)
                gate = gate | sel_marked

            if sharded:
                # decorrelate the shards' optimizer restart draws
                opt_key = jax.random.fold_in(
                    opt_key, jax.lax.axis_index(ISLAND_AXIS))
            if cfg.turbo and cfg.template is None and cfg.n_params == 0:
                # One flattened launch across the local islands: the
                # fused BFGS batches its line search through the Pallas
                # kernel. (Templates and parametric members always take
                # the jnp branch below — their joint constant+parameter
                # optimization differentiates through the combiner /
                # parameter gathers.)
                sub = jax.vmap(
                    lambda t, i: jax.tree.map(
                        lambda x: jnp.take(x, i, axis=0), t
                    )
                )(pops.trees, sel_idx)
                flat_sub = jax.tree.map(
                    lambda x: x.reshape((I * k_sel,) + x.shape[2:]), sub
                )
                new_const_flat, improved, _, f_calls = optimize_constants_fused(
                    opt_key, flat_sub, gate.reshape(I * k_sel), data,
                    el_loss, cfg.operators, self.opt_cfg,
                    batch_idx=batch_idx, interpret=cfg.interpret,
                )
                new_const_sub = new_const_flat.reshape(I, k_sel, -1)
            else:
                opt_keys = jax.random.split(opt_key, I)

                if cfg.template is not None:
                    from .constant_opt import optimize_constants_template

                    def island_opt(k, trees: TreeBatch, idx, g, p):
                        sub = jax.tree.map(
                            lambda x: jnp.take(x, idx, axis=0), trees
                        )
                        sub_p = jnp.take(p, idx, axis=0)
                        return optimize_constants_template(
                            k, sub, g, data, el_loss, cfg.operators,
                            self.opt_cfg, cfg.template,
                            batch_idx=batch_idx, params=sub_p,
                            # D call sites need second-order AD (grad of
                            # the derivative); the fused kernels' custom
                            # VJP is first-order only, so those
                            # structures optimize on the jvp-composable
                            # interpreter path.
                            fused=cfg.turbo and not cfg.template.uses_deriv,
                            interpret=cfg.interpret,
                        )
                else:
                    def island_opt(k, trees: TreeBatch, idx, g, p):
                        sub = jax.tree.map(
                            lambda x: jnp.take(x, idx, axis=0), trees
                        )
                        sub_p = jnp.take(p, idx, axis=0)
                        return optimize_constants_batch(
                            k, sub, g, data, el_loss,
                            cfg.operators, self.opt_cfg, batch_idx=batch_idx,
                            params=sub_p,
                        )
                (new_const_sub, improved, _, f_calls,
                 new_params_sub) = jax.vmap(island_opt)(
                    opt_keys, pops.trees, sel_idx, gate, pops.params
                )
                new_params = jax.vmap(lambda p, i, np_: p.at[i].set(np_))(
                    pops.params, sel_idx, new_params_sub
                )
                pops = dataclasses.replace(pops, params=new_params)
            new_const = jax.vmap(lambda c, i, nc: c.at[i].set(nc))(
                pops.trees.const, sel_idx, new_const_sub
            )
            pops = dataclasses.replace(
                pops, trees=dataclasses.replace(pops.trees, const=new_const)
            )
            f_calls_total = jnp.sum(f_calls).reshape(1)

        pops = self._finalize_costs(pops, data, cfg, use_dedup)

        # Lineage rotation (src/SingleIteration.jl:99-104).
        new_refs = ref[:, None] + jnp.arange(P, dtype=jnp.int32)[None, :]
        pops = dataclasses.replace(pops, parent=pops.ref, ref=new_refs)
        ref = ref + P
        return pops, ref, f_calls_total

    def _finalize_costs(self, pops: PopulationState, data: DeviceData,
                        cfg: EvolveConfig, use_dedup: bool
                        ) -> PopulationState:
        """Finalize costs on the full dataset (finalize_costs,
        src/Population.jl:182-196; always re-eval after simplify/opt).

        With ``use_dedup`` the island axis flattens (instead of vmapping)
        so the fused path dedups the ~40-55% of members that are
        identical copies across the converged populations
        (migration/tournament clones — measured in profiling/dup_rate.py).
        Single-shard island layouts only: under a sharded island axis
        the dedup's global sorts would need cross-device collectives
        every iteration for a ~1.03-1.15x local win."""
        options = self.options
        tables = self.tables
        el_loss = options.elementwise_loss
        I, P = pops.cost.shape
        if use_dedup:
            flat_trees = jax.tree.map(
                lambda x: x.reshape((I * P,) + x.shape[2:]), pops.trees)
            flat_params = pops.params.reshape(
                (I * P,) + pops.params.shape[2:])
            cost, loss, cx = eval_cost_batch(
                flat_trees, data, el_loss, tables, cfg.operators,
                cfg.parsimony, member_params=flat_params,
                turbo=cfg.turbo, interpret=cfg.interpret,
                loss_function=options.resolved_loss_function,
                dim_penalty=cfg.dim_penalty,
                wildcard_constants=cfg.wildcard_constants,
                template=cfg.template, dedup=True,
                tree_block=cfg.eval_tree_block,
                tile_rows=cfg.eval_tile_rows,
                bf16=cfg.eval_bf16,
            )
            cost, loss, cx = (cost.reshape(I, P), loss.reshape(I, P),
                              cx.reshape(I, P))
        else:
            cost, loss, cx = jax.vmap(
                lambda t, p: eval_cost_batch(
                    t, data, el_loss, tables, cfg.operators, cfg.parsimony,
                    member_params=p,
                    turbo=cfg.turbo, interpret=cfg.interpret,
                    loss_function=options.resolved_loss_function,
                    dim_penalty=cfg.dim_penalty,
                    wildcard_constants=cfg.wildcard_constants,
                    template=cfg.template,
                    tree_block=cfg.eval_tree_block,
                    tile_rows=cfg.eval_tile_rows,
                    fuse_cost=cfg.fuse_cost,
                    bf16=cfg.eval_bf16,
                )
            )(pops.trees, pops.params)
        return dataclasses.replace(pops, cost=cost, loss=loss,
                                   complexity=cx)

    def _epilogue_part(self, state: SearchDeviceState, data: DeviceData,
                       cur_maxsize, evolved, key, k_opt, k_mig, batch_idx,
                       cfg: EvolveConfig):
        """Everything after the cycles: optimize & simplify, full-dataset
        finalize, lineage rotation, HoF merge, migration, running stats
        (runs exactly once per iteration, chunked or not).

        The island-local parts run through ``_island_epilogue`` — under
        ``shard_map`` when the island axis is sharded and turbo is on
        (Pallas kernels have no GSPMD partitioning rule; shard_map runs
        them per-device on local shards). Cross-island parts (hall-of-
        fame merge, migration, running stats) stay in GSPMD-land where
        XLA inserts the collectives.
        """
        options = self.options
        tables = self.tables
        el_loss = options.elementwise_loss
        I = state.birth.shape[0]
        P = cfg.population_size
        eval_fraction = (
            cfg.batch_size / data.y.shape[0] if cfg.batching else 1.0
        )

        if cfg.collect_telemetry:
            pops, best_seen, nev, birth, ref, marks, tele = evolved
        else:
            pops, best_seen, nev, birth, ref, marks = evolved
            tele = None
        simp_mark, opt_mark = marks  # [I, P] bools
        num_evals = state.num_evals + jnp.sum(nev) * eval_fraction

        # All epilogue randomness is drawn here, island-major, so the
        # shard layout cannot change the streams (src/SingleIteration.jl
        # :77-85 per-member coin flips).
        k_sel, scores, gate, ko2 = self._epilogue_draws(k_opt, I)

        if self._shard_islands:
            isl = lambda tree: jax.tree.map(lambda _: P_(ISLAND_AXIS), tree)
            rep = lambda tree: jax.tree.map(lambda _: P_(), tree)
            args = (pops, ref, simp_mark, opt_mark, scores, gate, ko2,
                    data, cur_maxsize, batch_idx)
            specs = (isl(pops), P_(ISLAND_AXIS), P_(ISLAND_AXIS),
                     P_(ISLAND_AXIS),
                     None if scores is None else P_(ISLAND_AXIS),
                     None if gate is None else P_(ISLAND_AXIS),
                     rep(ko2), rep(data), P_(),
                     None if batch_idx is None else P_())
            fn = _shard_map(
                lambda *a: self._island_epilogue(
                    *a, cfg=cfg, k_sel=k_sel,
                    use_dedup=self._use_dedup(sharded=True),
                    sharded=True),
                mesh=self.mesh,
                in_specs=specs,
                out_specs=(isl(pops), P_(ISLAND_AXIS), P_(ISLAND_AXIS)),
                check_rep=False,
            )
            pops, ref, f_calls = fn(*args)
        else:
            pops, ref, f_calls = self._island_epilogue(
                pops, ref, simp_mark, opt_mark, scores, gate, ko2, data,
                cur_maxsize, batch_idx, cfg, k_sel,
                self._use_dedup(sharded=False), sharded=False)
        num_evals = num_evals + jnp.sum(f_calls) * eval_fraction
        num_evals = num_evals + I * P  # the finalize re-eval

        # ---- merge best_seen + final pops into the global HoF ----
        hof = state.hof
        flat_best = jax.tree.map(
            lambda x: x.reshape((I * cfg.maxsize,) + x.shape[2:]), best_seen
        )
        hof = update_hof(
            hof,
            PopulationState(
                trees=flat_best.trees,
                cost=jnp.where(flat_best.exists, flat_best.cost, jnp.inf),
                loss=flat_best.loss,
                complexity=flat_best.complexity,
                birth=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                ref=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                parent=jnp.zeros((I * cfg.maxsize,), jnp.int32),
                params=flat_best.params,
            ),
            cfg.maxsize,
        )
        flat_pops = jax.tree.map(
            lambda x: x.reshape((I * P,) + x.shape[2:]), pops
        )
        hof = update_hof(hof, flat_pops, cfg.maxsize)

        # ---- migration (src/Migration.jl:15-37 + main loop :1071-1088) ----
        if options.migration:
            # Pool: topn members of each island (best_sub_pop,
            # src/Population.jl:199-202), shared across islands. Under a
            # sharded island axis XLA turns this reshape into an all_gather.
            topn = min(options.topn, P)
            order = jnp.argsort(pops.cost, axis=1)[:, :topn]  # [I, topn]
            # Batched one-hot row-takes (MXU): the vmapped jnp.take per
            # field serialized into per-iteration kCustom gathers.
            pool = jax.vmap(lambda p, o: _member_take_onehot(p, o, P))(
                pops, order)
            pool = jax.tree.map(
                lambda x: x.reshape((I * topn,) + x.shape[2:]), pool
            )
            # The one-hot float gather clamps non-finite constants; in
            # degenerate/early populations with fewer than topn finite
            # members, inf-cost rows would otherwise enter the pool as
            # silently-finite genomes. Mask them out of the sampling
            # (reference best_sub_pop only ever migrates evaluable
            # members in practice).
            pool_ok = jnp.isfinite(pool.cost)
            km1, km2, km3, km4 = jax.random.split(k_mig, 4)
            pops, birth = _migrate(
                km1, pops, pool, options.fraction_replaced, birth, I, P,
                candidate_mask=pool_ok,
            )
            if options.hof_migration:
                hof_pool = PopulationState(
                    trees=hof.trees,
                    cost=jnp.where(hof.exists, hof.cost, jnp.inf),
                    loss=hof.loss,
                    complexity=hof.complexity,
                    birth=jnp.zeros((cfg.maxsize,), jnp.int32),
                    ref=jnp.zeros((cfg.maxsize,), jnp.int32),
                    parent=jnp.zeros((cfg.maxsize,), jnp.int32),
                    params=hof.params,
                )
                pops, birth = _migrate(
                    km2, pops, hof_pool, options.fraction_replaced_hof,
                    birth, I, P, candidate_mask=hof.exists,
                )

        # ---- running stats update (head-node semantics:
        # src/SymbolicRegression.jl:1054-1060 + move_window/normalize) ----
        sizes = pops.complexity.reshape(-1)
        in_range = (sizes > 0) & (sizes <= cfg.maxsize)
        hist = jnp.zeros((cfg.maxsize,), jnp.float32).at[
            jnp.where(in_range, sizes - 1, 0)
        ].add(in_range.astype(jnp.float32))
        freq = state.stats.frequencies + hist
        freq = _move_window(freq, self.window_size, cfg.maxsize)
        stats = RunningStats(
            frequencies=freq,
            normalized_frequencies=freq / jnp.sum(freq),
        )

        telem = None
        if cfg.collect_telemetry:
            # This iteration's counters: per-island cycle counters summed
            # over the island axis (a collective under a sharded island
            # axis — GSPMD-land, outside the shard_map'd phases), plus
            # the finalize re-eval, the post-migration population
            # loss histogram, and the member-duplication stats that
            # measure the dedup hit-rate. All in-graph: the host fetches
            # state.telem with the per-iteration state pull.
            from ..telemetry.counters import (
                IterationTelemetry,
                loss_histogram,
                member_dup_stats,
            )

            cyc = jax.tree.map(lambda x: jnp.sum(x, axis=0), tele)
            cyc = dataclasses.replace(
                cyc,
                eval_rows=cyc.eval_rows + jnp.int32(I * P),
                eval_launches=cyc.eval_launches + jnp.int32(1),
            )
            if self.n_island_shards > 1:
                # Global dup stats would sort across shards every
                # iteration (see counters.IterationTelemetry docstring);
                # report zeros instead, like the dedup path itself.
                fin_rows = jnp.int32(0)
                fin_unique = jnp.int32(0)
            else:
                fin_rows, fin_unique = member_dup_stats(pops.trees)
            telem = IterationTelemetry(
                cycle=cyc,
                finalize_rows=fin_rows,
                finalize_unique=fin_unique,
                loss_hist=loss_histogram(pops.loss),
                cx_hist=hist.astype(jnp.int32),
            )

        return SearchDeviceState(
            pops=pops, hof=hof, stats=stats, birth=birth, ref=ref,
            num_evals=num_evals, key=key, telem=telem,
        )

    # ------------------------------------------------------------------
    # graftshield quarantine primitives (shield/quarantine.py drives
    # these from the host loop; docs/ROBUSTNESS.md)
    # ------------------------------------------------------------------
    def island_invalid_fractions(self, state: SearchDeviceState):
        """[I] fraction of non-finite member losses per island — the
        NaN-storm detector input. One tiny jitted reduction, never part
        of the iteration program."""
        if not hasattr(self, "_invalid_frac_jit"):
            self._invalid_frac_jit = jax.jit(
                lambda loss: jnp.mean(
                    (~jnp.isfinite(loss)).astype(jnp.float32), axis=1
                )
            )
        return self._invalid_frac_jit(state.pops.loss)

    def reseed_islands(self, state: SearchDeviceState,
                       mask) -> SearchDeviceState:
        """Reseed the islands selected by ``mask`` ([I] bool) from the
        hall of fame, entirely in-graph: each masked island's members
        are replaced by the existing HoF entries tiled across the
        population slots (costs/losses/params carried over — HoF costs
        are full-dataset finalized, so no re-eval is needed). Unmasked
        islands are untouched; with an empty HoF the call is an
        identity. Deterministic — no RNG draws — so interrupted/resumed
        searches quarantine identically."""
        if not hasattr(self, "_reseed_jit"):
            cfg = self.cfg

            def reseed(state, mask):
                P = cfg.population_size
                hof = state.hof
                I = state.pops.cost.shape[0]
                exists = hof.exists
                n_exist = jnp.sum(exists.astype(jnp.int32))
                mask = mask & (n_exist > 0)
                exist_idx = jnp.nonzero(
                    exists, size=cfg.maxsize, fill_value=0)[0]
                slot = jnp.take(
                    exist_idx,
                    jnp.arange(P) % jnp.maximum(n_exist, 1),
                )

                def tile(x):  # hof field [maxsize, ...] -> [P, ...]
                    return jnp.take(x, slot, axis=0)

                def sel(orig, repl):  # orig [I, P, ...], repl [P, ...]
                    m = mask.reshape((I,) + (1,) * (orig.ndim - 1))
                    return jnp.where(
                        m, jnp.broadcast_to(repl[None], orig.shape), orig
                    )

                pops = state.pops
                fresh_ticks = (
                    state.birth[:, None]
                    + jnp.arange(P, dtype=jnp.int32)[None, :]
                )
                new_pops = dataclasses.replace(
                    pops,
                    trees=TreeBatch(
                        arity=sel(pops.trees.arity, tile(hof.trees.arity)),
                        op=sel(pops.trees.op, tile(hof.trees.op)),
                        feat=sel(pops.trees.feat, tile(hof.trees.feat)),
                        const=sel(pops.trees.const, tile(hof.trees.const)),
                        length=sel(pops.trees.length,
                                   tile(hof.trees.length)),
                    ),
                    cost=sel(pops.cost,
                             tile(jnp.where(exists, hof.cost, jnp.inf))),
                    loss=sel(pops.loss, tile(hof.loss)),
                    complexity=sel(pops.complexity, tile(hof.complexity)),
                    birth=jnp.where(mask[:, None], fresh_ticks, pops.birth),
                    parent=jnp.where(
                        mask[:, None],
                        jnp.full_like(pops.parent, -1), pops.parent),
                    ref=jnp.where(mask[:, None], fresh_ticks, pops.ref),
                    params=sel(pops.params, tile(hof.params)),
                )
                bump = mask.astype(jnp.int32) * jnp.int32(P)
                return dataclasses.replace(
                    state, pops=new_pops,
                    birth=state.birth + bump, ref=state.ref + bump,
                )

            self._reseed_jit = jax.jit(reseed)
        return self._reseed_jit(state, mask)


def _migrate(key, pops: PopulationState, pool: PopulationState, frac: float,
             birth, I: int, P: int, candidate_mask=None):
    """Replace each member with a random pool candidate w.p. `frac`
    (binomial-per-member equivalent of the reference's Poisson count with
    random positions, src/Migration.jl:20-35); birth reset to fresh ticks.

    Only ~frac of members actually migrate, so pool rows are gathered
    for a binomial-mean + 3-sigma PACK of replaced slots and scattered
    back — gathering a candidate for every slot serialized into ~370 ms
    of kCustom gathers per iteration at the bench config. Slots past the
    pack bound (beyond ~3 sigma, vanishingly rare) skip migration this
    iteration, mirroring the crossover cand2 pack's overflow rule.

    Known (accepted) bias: the pack rank runs over the flattened I*P
    axis, so when the >3-sigma truncation fires the dropped migrations
    always come from the highest-indexed islands rather than uniformly
    (the reference replaces the full Poisson-sampled count,
    src/Migration.jl:20-35). At 3 sigma this triggers on <0.2% of
    iterations and drops only the tail slots of the last island(s);
    a per-island pack would remove the bias at the cost of I small
    scatters.
    """
    if frac <= 0:
        return pops, birth
    k1, k2 = jax.random.split(key)
    n_pool = pool.cost.shape[0]
    replace = jax.random.bernoulli(k1, frac, (I, P))
    if candidate_mask is not None:
        # Sample only existing candidates.
        logits = jnp.where(candidate_mask, 0.0, -jnp.inf)
        pick = jax.random.categorical(k2, logits, shape=(I, P))
        replace = replace & jnp.any(candidate_mask)
    else:
        pick = jax.random.randint(k2, (I, P), 0, n_pool)

    N = I * P
    # `frac` is a static Python float (options.fraction_replaced*)
    f = min(float(frac), 1.0)  # graftlint: disable=GL003
    kpack = min(N, int(math.ceil(
        N * f + 3.0 * math.sqrt(N * f * (1.0 - f)) + 1.0)))
    flat_replace = replace.reshape(N)
    flat_pick = pick.reshape(N)
    rank = jnp.cumsum(flat_replace.astype(jnp.int32)) - 1
    overflow = flat_replace & (rank >= kpack)
    flat_replace = flat_replace & ~overflow
    replace = flat_replace.reshape(I, P)

    # pack positions: top_k is stable, so the first kpack replaced slots
    # come out in slot order; unreplaced filler rows are dropped at the
    # scatter via an out-of-range target.
    _, pos = jax.lax.top_k(flat_replace.astype(jnp.float32), kpack)
    row_live = jnp.take(flat_replace, pos)
    target = jnp.where(row_live, pos, N)

    picked = pool.member(jnp.take(flat_pick, pos))  # [kpack, ...] gathers

    def scat2(old_field, new_field):
        flat = old_field.reshape((N,) + old_field.shape[2:])
        out = flat.at[target].set(new_field, mode="drop")
        return out.reshape(old_field.shape)

    new_birth_ticks = birth[:, None] + jnp.arange(P, dtype=jnp.int32)[None, :]
    out = PopulationState(
        trees=TreeBatch(
            arity=scat2(pops.trees.arity, picked.trees.arity),
            op=scat2(pops.trees.op, picked.trees.op),
            feat=scat2(pops.trees.feat, picked.trees.feat),
            const=scat2(pops.trees.const, picked.trees.const),
            length=scat2(pops.trees.length, picked.trees.length),
        ),
        cost=scat2(pops.cost, picked.cost),
        loss=scat2(pops.loss, picked.loss),
        complexity=scat2(pops.complexity, picked.complexity),
        birth=jnp.where(replace, new_birth_ticks, pops.birth),
        ref=scat2(pops.ref, picked.ref),
        parent=scat2(pops.parent, picked.parent),
        params=scat2(pops.params, picked.params),
    )
    return out, birth + P
