"""Device-side constant folding (simplify_tree! analogue).

Collapses maximal all-constant subtrees into single constant leaves using
one interpreter pass on a single dummy row plus a compaction gather — the
tensor equivalent of DynamicExpressions' `simplify_tree!` as invoked once
per iteration in optimize_and_simplify_population
(/root/reference/src/SingleIteration.jl:79-85). The algebraic
`combine_operators` rewrites remain host-side (ops.tree.combine_operators)
and run outside the hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.encoding import (
    LEAF_CONST,
    MAX_ARITY,
    TreeBatch,
    _tree_structure_single,
    lane_take,
)
from ..ops.eval import eval_single_tree

__all__ = ["fold_constants_batch"]


def _fold_single(tree: TreeBatch, X1, operators):
    """Fold one tree. X1 is a [F, 1] dummy input."""
    L = tree.arity.shape[0]
    child, size, _ = _tree_structure_single(tree.arity, tree.length)
    slot = jnp.arange(L)
    in_tree = slot < tree.length

    # is_const_subtree via one postfix stack scan.
    def step(carry, k):
        stack, sp = carry
        a = tree.arity[k]
        all_const = jnp.bool_(True)
        for j in range(MAX_ARITY):
            pos = sp - a + j
            is_child = j < a
            all_const = all_const & (
                ~is_child | stack[jnp.maximum(pos, 0)]
            )
        leaf_const = tree.op[k] == LEAF_CONST
        c_k = jnp.where(a == 0, leaf_const, all_const)
        new_sp = sp - a + 1
        stack = stack.at[new_sp - 1].set(c_k)
        return (stack, new_sp), c_k

    # unroll=4 (not full): a fully-unrolled scan fuses into one kLoop
    # whose live set exceeds XLA's scoped-VMEM budget when vmapped over
    # whole populations.
    (_, _), is_const = jax.lax.scan(
        step, (jnp.zeros((L,), jnp.bool_), jnp.int32(0)),
        jnp.arange(L, dtype=jnp.int32), unroll=4,
    )

    # Node values on the dummy row: const-subtree values are X-independent.
    # We need the full buffer, so inline a tiny interpreter via the spans:
    # reuse eval by evaluating each prefix? Cheaper: evaluate once and read
    # the buffer — replicate eval_single_tree's scan but keep buf.
    from ..ops.eval import _apply_tables
    from ..ops.encoding import LEAF_PARAM

    def eval_step(carry, k):
        buf, = carry
        a = tree.arity[k]
        o = tree.op[k]
        children = [
            jax.lax.dynamic_index_in_dim(buf, child[k, j], axis=0, keepdims=False)
            for j in range(MAX_ARITY)
        ]
        x_row = jax.lax.dynamic_index_in_dim(X1, tree.feat[k], axis=0, keepdims=False)
        leaf = jnp.where(o == LEAF_CONST, jnp.broadcast_to(tree.const[k], (1,)), x_row)
        leaf = jnp.where((a == 0) & (o == LEAF_PARAM), jnp.nan, leaf)
        val = _apply_tables(operators, a, o, leaf, children).astype(tree.const.dtype)
        buf = buf.at[k].set(val)
        return (buf,), None

    (buf,), _ = jax.lax.scan(
        eval_step, (jnp.zeros((L, 1), tree.const.dtype),),
        jnp.arange(L, dtype=jnp.int32), unroll=4,
    )
    values = buf[:, 0]

    # A node is *inside* a folded subtree iff some LATER const node's
    # span contains it (postfix: ancestors come after descendants, and
    # const-ness is subtree-contiguous, so "parent is const" ⟺ "covered
    # by any const node's strict span"). covered[c] = ∃ k > c with
    # is_const[k] and start_k <= c — an O(L) exclusive suffix-min of the
    # const spans' starts (no parent pointers, no [L, L] intermediates,
    # which blew XLA's scoped-VMEM budget when vmapped over whole
    # populations).
    BIG = jnp.int32(L + 1)
    start = (slot - size + 1).astype(jnp.int32)
    vals = jnp.where(is_const & in_tree, start, BIG)
    # exclusive suffix-min by doubling shifts (log L slice+min passes —
    # keeps the lowering to plain vector ops)
    m_excl = jnp.concatenate([vals[1:], jnp.full((1,), BIG)])
    sh = 1
    while sh < L:
        m_excl = jnp.minimum(
            m_excl,
            jnp.concatenate([m_excl[sh:], jnp.full((sh,), BIG)]),
        )
        sh *= 2
    parent_is_const = m_excl <= slot
    is_fold_root = is_const & ~parent_is_const & in_tree
    keep = in_tree & (~is_const | is_fold_root)

    # Compact: gather kept slots in order.
    new_len = jnp.sum(keep.astype(jnp.int32))
    order_key = jnp.where(keep, slot, L + slot)  # kept first, stable
    perm = jnp.argsort(order_key)
    g = lambda x: lane_take(x, perm)
    folded_to_leaf = is_fold_root & (tree.arity > 0)
    arity = jnp.where(folded_to_leaf, 0, tree.arity)
    op = jnp.where(folded_to_leaf, LEAF_CONST, tree.op)
    const = jnp.where(is_fold_root, values, tree.const)
    out_mask = slot < new_len
    return TreeBatch(
        arity=jnp.where(out_mask, g(arity), 0),
        op=jnp.where(out_mask, g(op), 0),
        feat=jnp.where(out_mask, g(tree.feat), 0),
        const=jnp.where(out_mask, g(const), 0.0),
        length=new_len,
    )


def fold_constants_batch(trees: TreeBatch, nfeatures: int, operators) -> TreeBatch:
    """Fold constants for a [P, L] batch of trees."""
    X1 = jnp.zeros((nfeatures, 1), trees.const.dtype)
    return jax.vmap(lambda a, o, f, c, ln: _fold_single(
        TreeBatch(a, o, f, c, ln), X1, operators
    ))(trees.arity, trees.op, trees.feat, trees.const, trees.length)
