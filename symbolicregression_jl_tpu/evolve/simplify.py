"""Device-side constant folding (simplify_tree! analogue).

Collapses maximal all-constant subtrees into single constant leaves, the
tensor equivalent of DynamicExpressions' `simplify_tree!` as invoked once
per iteration in optimize_and_simplify_population
(/root/reference/src/SingleIteration.jl:79-85). The algebraic
`combine_operators` rewrites remain host-side (ops.tree.combine_operators)
and run outside the hot path.

Everything here is BATCH-vectorized — no per-member dynamic indexing.
The original implementation vmapped a per-tree routine whose two
`lax.scan`s read stack/buffer slots via `dynamic_index_in_dim`; under
vmap those lower to XLA's serialized kCustom gathers, which cost ~370 ms
per iteration on the whole-population fold at the bench config (eight
23 ms gather fusions — one per unroll segment). The rewrite:

- const-subtree detection in closed form: a subtree is all-constant iff
  its span contains no VAR/PARAM leaf — one prefix sum plus a
  `lane_take` of the span starts (no stack walk);
- node values from an unrolled L-step loop over a [members, L] value
  buffer: child reads are `lane_take` one-hot contractions, the operator
  is selected by a where-chain over the (small) op tables, and the
  buffer update is a masked select — all wide VPU ops.
"""
# graftlint: assume-traced — pure device-kernel module; callers jit/vmap
# these functions from other modules, outside the module-local analysis.

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.encoding import (
    LEAF_CONST,
    TreeBatch,
    _structure_from_arity,
    lane_take,
)

__all__ = ["fold_constants_batch"]


def _select_op_lanes(fns, o, *args):
    """where-chain select over a small op table (no dynamic_index_in_dim,
    which serializes per member under vmap/batching)."""
    out = fns[0](*args)
    for j in range(1, len(fns)):
        out = jnp.where(o == j, fns[j](*args), out)
    return out


def fold_constants_batch(trees: TreeBatch, operators) -> TreeBatch:
    """Fold constants for a [P, L] batch of trees (any leading dims).

    Leaf values of non-const subtrees are never consumed, so no feature
    data is needed — VAR/PARAM leaves evaluate as 0 into dead lanes."""
    arity, op, feat, const, length = (
        trees.arity, trees.op, trees.feat, trees.const, trees.length)
    L = arity.shape[-1]
    slot = jnp.arange(L, dtype=jnp.int32)
    in_tree = slot < length[..., None]

    child, size, _ = _structure_from_arity(arity, need_depth=False)
    start = (slot - size + 1).astype(jnp.int32)

    # is_const[k]: no VAR/PARAM leaf inside span [start(k), k].
    bad = (in_tree & (arity == 0) & (op != LEAF_CONST)).astype(jnp.int32)
    badc = jnp.cumsum(bad, axis=-1)                      # inclusive
    before = jnp.where(
        start > 0,
        lane_take(badc, jnp.maximum(start - 1, 0)),
        0,
    )
    is_const = (badc - before == 0) & in_tree

    # Node values over a [.., L] buffer, one unrolled step per slot:
    # only const-subtree values are consumed, so VAR/PARAM leaves read 0.
    unary_fns = tuple(o_.fn for o_ in operators.unary)
    binary_fns = tuple(o_.fn for o_ in operators.binary)
    leaf_val = jnp.where((arity == 0) & (op == LEAF_CONST), const, 0.0)
    buf = jnp.zeros(arity.shape, const.dtype)
    for k in range(L):
        a = arity[..., k]
        o = op[..., k]
        ch = lane_take(buf, child[..., k, :])            # [..., 2]
        val = leaf_val[..., k]
        if unary_fns:
            un = _select_op_lanes(unary_fns, o, ch[..., 0])
            val = jnp.where(a == 1, un, val)
        if binary_fns:
            bi = _select_op_lanes(binary_fns, o, ch[..., 0], ch[..., 1])
            val = jnp.where(a == 2, bi, val)
        buf = jnp.where(slot == k, val[..., None].astype(const.dtype), buf)
    values = buf

    # A node is *inside* a folded subtree iff some LATER const node's
    # span contains it (postfix: ancestors come after descendants, and
    # const-ness is subtree-contiguous, so "parent is const" ⟺ "covered
    # by any const node's strict span"). covered[c] = ∃ k > c with
    # is_const[k] and start_k <= c — an O(L) exclusive suffix-min of the
    # const spans' starts.
    BIG = jnp.int32(L + 1)
    vals = jnp.where(is_const & in_tree, start, BIG)
    pad = jnp.full(vals.shape[:-1] + (1,), BIG)
    m_excl = jnp.concatenate([vals[..., 1:], pad], axis=-1)
    sh = 1
    while sh < L:
        shifted = jnp.concatenate(
            [m_excl[..., sh:],
             jnp.broadcast_to(BIG, m_excl.shape[:-1] + (sh,))], axis=-1)
        m_excl = jnp.minimum(m_excl, shifted)
        sh *= 2
    parent_is_const = m_excl <= slot
    is_fold_root = is_const & ~parent_is_const & in_tree
    keep = in_tree & (~is_const | is_fold_root)

    # Compact: gather kept slots in order (lane_take one-hot sums).
    new_len = jnp.sum(keep.astype(jnp.int32), axis=-1)
    order_key = jnp.where(keep, slot, L + slot)          # kept first, stable
    perm = jnp.argsort(order_key, axis=-1)
    g = lambda x: lane_take(x, perm)
    folded_to_leaf = is_fold_root & (arity > 0)
    arity2 = jnp.where(folded_to_leaf, 0, arity)
    op2 = jnp.where(folded_to_leaf, LEAF_CONST, op)
    const2 = jnp.where(is_fold_root, values, const)
    out_mask = slot < new_len[..., None]
    return TreeBatch(
        arity=jnp.where(out_mask, g(arity2), 0),
        op=jnp.where(out_mask, g(op2), 0),
        feat=jnp.where(out_mask, g(feat), 0),
        const=jnp.where(out_mask, g(const2), 0.0),
        length=new_len,
    )
