"""The jitted evolution step: tournaments, mutations, accepts, replacement.

This collapses the reference's sequential `reg_evol_cycle`
(/root/reference/src/RegularizedEvolution.jl:13-158) into a bulk device
step: the ``ceil(P / tournament_n)`` steps of one cycle all run in
parallel from the same population snapshot (SURVEY.md §7 design delta 2),
each producing up to two babies (mutation, or crossover's pair) that
replace the oldest members. The reference's retry-until-valid loop
(≤10 attempts, src/Mutate.jl:209-245) becomes a speculative batch over an
attempt axis with first-valid selection.

`s_r_cycle` then scans `ncycles` of these steps over the annealing
temperature ramp (src/SingleIteration.jl:19-66), maintaining the
best-seen-per-complexity mini hall of fame on device.
"""

from __future__ import annotations

import functools
import dataclasses
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.losses import aggregate_loss, loss_to_cost
from ..core.options import (KERNEL_TILE_ROWS, KERNEL_TREE_BLOCK,
                            MUTATION_KINDS, Options)
from ..ops.complexity import (
    ComplexityTables,
    check_constraints_batch,
    compute_complexity_batch,
)
from ..ops.encoding import LEAF_CONST, TreeBatch, tree_structure_arrays
from ..ops.eval import eval_tree_batch
from ..ops.fused_eval import fused_cost, fused_loss, supports_fused_eval
from ..ops.operators import OperatorSet
from . import mutation as M
from .population import PopulationState
from .rng import categorical_from_weights
from .tournament import tournament_select

__all__ = ["CycleEvents", "EvolveConfig", "HofState", "generation_step",
           "s_r_cycle", "empty_hof", "update_hof", "eval_cost_batch"]


class CycleEvents(NamedTuple):
    """Per-cycle genealogy events, one entry per candidate baby [2B]
    (the reference's per-mutation @recorder stream,
    /root/reference/src/RegularizedEvolution.jl:47-75,105-149, emitted
    as int32/f32 side outputs of the already-computed generation step).

    ``kind`` is the sampled mutation-kind index; ``len(MUTATION_KINDS)``
    denotes crossover. Crossover rows carry both parents; ``died_ref``
    is the ref of the (oldest) member the baby replaced. Rows with
    ``accepted == False`` were candidate babies that failed constraints
    / rejection sampling — the reference logs those with their reject
    reason too."""

    kind: jax.Array         # int32 [2B]
    parent_ref: jax.Array   # int32 [2B]
    parent2_ref: jax.Array  # int32 [2B]  (-1 unless crossover)
    child_ref: jax.Array    # int32 [2B]
    died_ref: jax.Array     # int32 [2B]  (-1 when not accepted)
    accepted: jax.Array     # bool  [2B]
    cost_delta: jax.Array   # f32   [2B]  child cost - parent cost
    # Why the candidate was rejected (0 = not rejected; 1 = constraint /
    # no valid candidate; 2 = non-finite cost; 3 = annealing/frequency
    # rejection — src/Mutate.jl:270-355's check chain). A kept-parent
    # fallback row (skip_mutation_failures=False) carries BOTH
    # accepted=True (the parent copy re-enters with a fresh ref) and its
    # mutation's reject reason, mirroring the reference's "failed
    # mutation, re-insert member" event.
    reject_reason: jax.Array  # int32 [2B]

# Mutation-batch row count at or below which concat_pieces' int-field
# takes use the one-hot MXU matmul. Measured (round 5, forced-on vs
# forced-off iterations): 310 rows (the reference's 31x27 config) the
# matmul is 2.5x faster; by 620 rows the masked-sum lowering already
# wins and keeps winning through the bench config's 40,960 rows.
_INT_MATMUL_MAX_ROWS = 512

_KIND = {name: i for i, name in enumerate(MUTATION_KINDS)}
_IMMEDIATE_KINDS = (_KIND["simplify"], _KIND["do_nothing"], _KIND["optimize"],
                    _KIND["form_connection"], _KIND["break_connection"])


class EvolveConfig(NamedTuple):
    """Static engine configuration derived from Options (hashable)."""

    operators: OperatorSet
    maxsize: int
    maxdepth: int
    max_nodes: int           # slot budget L (== maxsize)
    population_size: int
    tournament_n: int
    tournament_p: float
    crossover_probability: float
    annealing: bool
    alpha: float
    use_frequency: bool
    use_frequency_in_tournament: bool
    adaptive_parsimony_scaling: float
    parsimony: float
    skip_mutation_failures: bool
    should_simplify: bool
    attempts: int
    nfeatures: int
    perturbation_factor: float
    probability_negate_constant: float
    ncycles: int
    batching: bool
    batch_size: int
    turbo: bool        # use the fused Pallas eval kernel
    interpret: bool    # pallas interpret mode (non-TPU backends)
    # Dimensional analysis: cost penalty for unit violations (applied only
    # when the dataset carries units), and whether constants are wildcards.
    dim_penalty: float = 1000.0
    wildcard_constants: bool = True
    # Parametric expressions (ParametricExpressionSpec): per-member
    # parameter banks [n_params, n_classes]; 0 = plain expressions.
    n_params: int = 0
    n_classes: int = 0
    # Emit CycleEvents from every generation step (options.use_recorder).
    record_events: bool = False
    # Template expressions (TemplateExpressionSpec): the static structure
    # (combiner + per-key arities); trees gain a leading key axis [K, L]
    # and params hold the flat template parameter bank [total, 1].
    template: "object" = None  # Optional[TemplateStructure]
    # LOCAL island count (post island-sharding) — sizes the per-cycle
    # mutation batch for static lowering choices (see mctx); 0 = unknown
    # (ad-hoc EvolveConfig constructions), treated as large.
    n_islands: int = 0
    # Candidate-eval kernel tuning (options.eval_tree_block /
    # eval_tile_rows; kernel defaults when unset) and the in-kernel
    # loss->cost epilogue gate (round 6, profiling/cycle_attrib.py).
    eval_tree_block: int = 8
    eval_tile_rows: int = 16384
    fuse_cost: bool = False
    # graftstage (docs/PRECISION.md): bf16 candidate-eval row tiles (f32
    # reduction spine) and the staged sample-then-rescore evaluation
    # path. Both default off; the f32/full path is bit-identical with
    # them off. ``staged_sample_rows`` = 0 derives the screening sample
    # as ``staged_sample_fraction`` of the dataset (see
    # ``resolve_sample_rows``); the resolver caps it at
    # ``eval_tile_rows`` so the shield degrade ladder's tile step-down
    # keeps the sample inside one row tile.
    eval_bf16: bool = False
    staged_eval: bool = False
    staged_sample_rows: int = 0
    staged_sample_fraction: float = 0.125
    rescore_fraction: float = 0.25
    # graftscope device counters (options.telemetry): generation_step
    # emits a CycleTelemetry from values it already computed, s_r_cycle
    # accumulates it in the scan carry — the search trajectory is
    # bit-identical with the flag on or off (tests/test_telemetry.py).
    collect_telemetry: bool = False

    @property
    def n_slots(self) -> int:
        # n_evol_cycles = ceil(P / tournament_n), src/RegularizedEvolution.jl:23
        return -(-self.population_size // self.tournament_n)

    @property
    def mctx(self) -> M.MutationContext:
        # Template parameters live in the structure's parameter vectors,
        # not in tree leaves — no LEAF_PARAM sampling for templates.
        # The mutation batch is [islands, n_slots, attempts]: below
        # _INT_MATMUL_MAX_ROWS rows, concat_pieces' int takes route
        # through the one-hot MXU matmul (profiling/trace_machinery.py;
        # RESULTS.md round 5 — 3x cycle win at 31x27, loss at 512x256).
        rows = self.n_islands * self.n_slots * self.attempts
        return M.MutationContext(
            nops=self.operators.nops_tuple(),
            nfeatures=self.nfeatures,
            max_nodes=self.max_nodes,
            perturbation_factor=self.perturbation_factor,
            probability_negate_constant=self.probability_negate_constant,
            n_params=0 if self.template is not None else self.n_params,
            int_take_matmul=0 < rows <= _INT_MATMUL_MAX_ROWS,
        )


def evolve_config_from_options(options: Options, nfeatures: int,
                               n_params: int = 0, n_classes: int = 0,
                               template=None,
                               n_data_shards: int = 1,
                               n_island_shards: int = 1) -> EvolveConfig:
    on_tpu = jax.default_backend() == "tpu"
    turbo = options.turbo if options.turbo is not None else on_tpu
    if turbo and not supports_fused_eval(options.operators):
        turbo = False
    if options.loss_function is not None or options.loss_function_expression is not None:
        turbo = False  # custom whole-prediction losses use the jnp path
    # (Parametric members keep turbo: LEAF_PARAM leaves address the
    # fused kernel's parameter buffer region — see ops/program.py. Their
    # constant+parameter optimization still runs the jnp path, gated in
    # engine.py. Templates keep turbo: the batched template evaluator
    # routes call sites through the fused predict kernel.)
    if n_data_shards > 1:
        # Documented fallback: `pl.pallas_call` does not compose with
        # GSPMD row-sharded operands (it would need a shard_map wrapper
        # with per-shard loss partials); the jnp interpreter partitions
        # cleanly over the data axis, with the final loss reduction
        # lowering to a psum over ICI.
        turbo = False
    # (Template and parametric searches keep turbo under island sharding
    # since round 5: the shard_map treatment in engine._evolve_part /
    # _island_epilogue is pytree-generic — pops.params shards with the
    # population, the template structure is static config, and the fused
    # template/parametric kernels launch per-device on local islands
    # exactly like the plain-expression kernels. Covered by
    # tests/test_sharded_turbo.py and __graft_entry__.dryrun_multichip.)
    geom = options.eval_geometry()
    return EvolveConfig(
        operators=options.operators,
        maxsize=options.maxsize,
        maxdepth=options.maxdepth,
        max_nodes=options.maxsize,
        population_size=options.population_size,
        tournament_n=options.tournament_selection_n,
        tournament_p=options.tournament_selection_p,
        crossover_probability=options.crossover_probability,
        annealing=options.annealing,
        alpha=options.alpha,
        use_frequency=options.use_frequency,
        use_frequency_in_tournament=options.use_frequency_in_tournament,
        adaptive_parsimony_scaling=options.adaptive_parsimony_scaling,
        parsimony=options.parsimony,
        skip_mutation_failures=options.skip_mutation_failures,
        should_simplify=options.should_simplify,
        attempts=options.mutation_attempts,
        nfeatures=nfeatures,
        perturbation_factor=options.perturbation_factor,
        probability_negate_constant=options.probability_negate_constant,
        ncycles=options.ncycles_per_iteration,
        batching=options.batching,
        batch_size=options.batch_size,
        turbo=turbo,
        interpret=not on_tpu,
        dim_penalty=(
            options.dimensional_constraint_penalty
            if options.dimensional_constraint_penalty is not None
            else 1000.0  # src/LossFunctions.jl:236-245 default
        ),
        wildcard_constants=not options.dimensionless_constants_only,
        n_params=n_params,
        n_classes=n_classes,
        template=template,
        record_events=bool(getattr(options, "use_recorder", False)),
        n_islands=max(1, options.populations // max(n_island_shards, 1)),
        # Geometry defaults resolve in ONE place (Options.eval_geometry);
        # checkpointed Options predating the resolver fall back to its
        # defaults through the same path.
        eval_tree_block=geom.tree_block,
        eval_tile_rows=geom.tile_rows,
        # In-kernel loss->cost epilogue: auto-on with turbo (the fused
        # kernel is the only place the epilogue can live); tri-state
        # override for A/B measurement.
        fuse_cost=turbo and (
            getattr(options, "fuse_cost_epilogue", None) is not False
        ),
        collect_telemetry=bool(getattr(options, "telemetry", False)),
        # graftstage knobs (getattr: unpickled pre-graftstage Options
        # carry neither attribute; both modes default off there).
        eval_bf16=getattr(options, "eval_precision", "f32") == "bf16",
        staged_eval=bool(getattr(options, "staged_eval", False)),
        staged_sample_rows=getattr(options, "staged_sample_rows", None) or 0,
        staged_sample_fraction=float(
            getattr(options, "staged_sample_fraction", 0.125)),
        rescore_fraction=float(getattr(options, "rescore_fraction", 0.25)),
    )


# ---------------------------------------------------------------------------
# Mutation weight conditioning (condition_mutation_weights!,
# src/Mutate.jl:101-170)
# ---------------------------------------------------------------------------


def _condition_weights(base_w, tree: TreeBatch, complexity, cur_maxsize,
                       cfg: EvolveConfig, nfeat_dyn=None):
    """``tree`` is the mutation target ([L]; for templates, the chosen
    subexpression); ``nfeat_dyn`` overrides the static feature count with
    the chosen key's arity (templates)."""
    L = cfg.max_nodes
    slot = jnp.arange(L)
    mask = slot < tree.length
    root = tree.length - 1
    root_arity = tree.arity[root]
    root_is_leaf = root_arity == 0
    root_is_const = root_is_leaf & (tree.op[root] == LEAF_CONST)
    has_binary = jnp.any(mask & (tree.arity == 2))
    n_const = jnp.sum(mask & (tree.arity == 0) & (tree.op == LEAF_CONST))

    w = base_w
    zero = jnp.zeros((), base_w.dtype)

    def setw(w, name, val):
        return w.at[_KIND[name]].set(val)

    # Leaf-only equations can't lose or reshuffle operators:
    for name in ("mutate_operator", "swap_operands", "delete_node", "simplify"):
        w = setw(w, name, jnp.where(root_is_leaf, zero, w[_KIND[name]]))
    w = setw(w, "optimize",
             jnp.where(root_is_leaf & ~root_is_const, zero, w[_KIND["optimize"]]))
    w = setw(w, "mutate_constant",
             jnp.where(root_is_leaf & ~root_is_const, zero, w[_KIND["mutate_constant"]]))
    w = setw(w, "mutate_feature",
             jnp.where(root_is_leaf & root_is_const, zero, w[_KIND["mutate_feature"]]))
    w = setw(w, "swap_operands",
             jnp.where(~has_binary, zero, w[_KIND["swap_operands"]]))
    # constant-count scaling (condition_mutate_constant!, :159-170);
    # parametric expressions skip it (the parametric overload is a no-op,
    # /root/reference/src/ParametricExpression.jl:101-112)
    # (templates also skip it: condition_mutate_constant! is a no-op,
    # /root/reference/src/TemplateExpression.jl:869-879)
    if cfg.n_params == 0 and cfg.template is None:
        w = setw(w, "mutate_constant",
                 w[_KIND["mutate_constant"]] * jnp.minimum(8, n_const) / 8.0)
    if nfeat_dyn is not None:
        w = setw(w, "mutate_feature",
                 jnp.where(nfeat_dyn <= 1, zero, w[_KIND["mutate_feature"]]))
    elif cfg.nfeatures <= 1:
        w = setw(w, "mutate_feature", zero)
    too_big = complexity >= cur_maxsize
    w = setw(w, "add_node", jnp.where(too_big, zero, w[_KIND["add_node"]]))
    w = setw(w, "insert_node", jnp.where(too_big, zero, w[_KIND["insert_node"]]))
    # GraphNode-only mutations are always off for tree expressions:
    w = setw(w, "form_connection", zero)
    w = setw(w, "break_connection", zero)
    return w


# ---------------------------------------------------------------------------
# Applying a sampled mutation kind (speculative attempts)
# ---------------------------------------------------------------------------


def _attempt_nu(cfg: EvolveConfig) -> int:
    """Total uniform budget of one speculative mutation attempt."""
    return sum(M.branch_nu(cfg.mctx).values())


def _apply_kind(kind, u_all, tree: TreeBatch, temperature, cur_maxsize,
                cfg: EvolveConfig, structure=None, mctx=None):
    """Apply mutation `kind` to `tree`; returns (tree, structural_ok).

    ``u_all`` is a flat uniform slice of size ``_attempt_nu(cfg)`` — one
    bulk draw serves every branch. ``structure`` is the precomputed
    (child, size, depth) of ``tree`` — shared by every branch and every
    speculative attempt. ``mctx`` overrides ``cfg.mctx`` (templates pass
    a per-key traced ``nfeatures``).
    """
    from .rng import USlice

    mctx = mctx if mctx is not None else cfg.mctx
    budgets = M.branch_nu(mctx)
    s = USlice(u_all)
    branches = []

    def add(name, fn):
        # trace-time staging: the branch table is built and fully
        # consumed within this trace, never mutated across traces
        branches.append((_KIND[name], fn(s.take(budgets[name]))))  # graftlint: disable=GL005

    add("mutate_constant", lambda u: M.mutate_constant(u, tree, temperature, mctx))
    add("mutate_operator", lambda u: M.mutate_operator(u, tree, mctx))
    add("mutate_feature", lambda u: M.mutate_feature(u, tree, mctx))
    add("swap_operands", lambda u: M.swap_operands(u, tree, mctx, structure))
    add("rotate_tree", lambda u: M.rotate_tree(u, tree, mctx, structure))
    add("add_node", lambda u: M.add_node(u, tree, mctx, structure))
    add("insert_node", lambda u: M.insert_random_op(u, tree, mctx, structure))
    add("delete_node", lambda u: M.delete_node(u, tree, mctx, structure))
    add("randomize", lambda u: M.randomize_tree(u, tree, cur_maxsize, mctx))

    out_tree = tree
    out_ok = jnp.bool_(True)
    for kid, (t, ok) in branches:
        hit = kind == kid
        out_tree = M._select_tree(hit, t, out_tree)
        out_ok = jnp.where(hit, ok, out_ok)
    return out_tree, out_ok


def _first_valid(valid, stacked: TreeBatch, fallback: TreeBatch):
    """Select the first attempt with valid=True, else fallback.

    One-hot select over the (small) attempt axis: a traced-scalar index
    here becomes a batched dynamic gather under the (island, slot) vmaps,
    which XLA serializes on TPU (see ops.encoding.lane_take)."""
    any_valid = jnp.any(valid)
    first = jnp.argmax(valid)
    A = valid.shape[0]
    oh = jnp.arange(A) == first

    def pick(x):
        ohx = oh.reshape((A,) + (1,) * (x.ndim - 1))
        return jnp.sum(jnp.where(ohx, x, jnp.zeros((), x.dtype)),
                       axis=0).astype(x.dtype)

    picked = jax.tree.map(pick, stacked)
    return M._select_tree(any_valid, picked, fallback), any_valid


def _check_single(tree: TreeBatch, options, tables, cur_maxsize):
    batched = jax.tree.map(lambda x: x[None], tree)
    ok = check_constraints_batch(batched, options, tables, cur_maxsize)
    return ok[0]


def template_check_batch(trees: TreeBatch, options, tables, cur_maxsize,
                         template) -> jax.Array:
    """check_constraints for template members
    (/root/reference/src/TemplateExpression.jl:917-940): combined
    complexity <= maxsize, per-subtree structural constraints, and no
    subexpression using a feature beyond its declared arity
    (has_invalid_variables, :942-967). ``trees``: [..., K, L]."""
    from ..ops.encoding import LEAF_VAR

    per = check_constraints_batch(trees, options, tables, cur_maxsize)  # [..., K]
    cx = compute_complexity_batch(trees, tables)                        # [..., K]
    ok = jnp.all(per, axis=-1) & (jnp.sum(cx, axis=-1) <= cur_maxsize)
    nfeat = jnp.asarray(template.num_features, jnp.int32)               # [K]
    L = trees.max_nodes
    in_tree = jnp.arange(L) < trees.length[..., None]
    bad_feat = (
        in_tree & (trees.arity == 0) & (trees.op == LEAF_VAR)
        & (trees.feat >= nfeat[:, None])
    )
    return ok & ~jnp.any(bad_feat, axis=(-1, -2))


def _take_sub(trees: TreeBatch, k) -> TreeBatch:
    """Subexpression k of a template member ([K, L] -> [L])."""
    g = lambda x: jax.lax.dynamic_index_in_dim(x, k, axis=0, keepdims=False)
    return TreeBatch(
        arity=g(trees.arity), op=g(trees.op), feat=g(trees.feat),
        const=g(trees.const), length=g(trees.length),
    )


def _put_sub(trees: TreeBatch, sub: TreeBatch, k) -> TreeBatch:
    """Write subexpression k back into a template member."""
    return TreeBatch(
        arity=trees.arity.at[k].set(sub.arity),
        op=trees.op.at[k].set(sub.op),
        feat=trees.feat.at[k].set(sub.feat),
        const=trees.const.at[k].set(sub.const),
        length=trees.length.at[k].set(sub.length),
    )


# ---------------------------------------------------------------------------
# Cost evaluation
# ---------------------------------------------------------------------------


def eval_cost_batch(trees: TreeBatch, data, elementwise_loss, tables,
                    operators, parsimony, batch_idx=None, member_params=None,
                    turbo=False, interpret=False, loss_function=None,
                    dim_penalty=1000.0, wildcard_constants=True,
                    template=None, dedup=False, tree_block=None,
                    tile_rows=None, fuse_cost=False, bf16=False):
    """Batched eval_cost (src/LossFunctions.jl:193-209): (cost, loss, complexity).

    ``turbo`` routes through the fused Pallas eval+loss kernel (the hot
    path); params (parametric expressions) and grad paths use the jnp
    interpreter. ``member_params``: per-tree parameter banks
    [..., n_params, n_classes], expanded to per-row values via the
    dataset's class column (eval_tree_dispatch for ParametricExpression,
    /root/reference/src/ParametricExpression.jl:88-100).

    ``fuse_cost`` additionally fuses the loss->cost epilogue (mean,
    validity, baseline normalization, parsimony penalty) into the
    kernel's final grid step (ops.fused_eval.fused_cost) — bit-identical
    results, fewer per-cycle dispatches. Plain elementwise-loss
    expressions only; custom-loss / template / parametric / dedup
    callers keep the materializing epilogue, gated exactly like turbo.
    ``tree_block`` / ``tile_rows`` override the fused kernel's launch
    geometry (options.eval_tree_block / eval_tile_rows).

    ``bf16`` (options.eval_precision == "bf16") evaluates the row tiles
    in bfloat16 with a float32 reduction spine for loss/cost — rank-
    reliable but not bit-exact vs f32 (docs/PRECISION.md). Applied on
    both the fused kernel and the jnp interpreter fallback so CPU bench
    cells exercise the same numeric contract; template/parametric/
    custom-loss paths stay f32.
    """
    if batch_idx is None:
        X = data.Xt
        y = data.y
        w = data.weights
        class_idx = data.class_idx
    else:
        X = jnp.take(data.Xt, batch_idx, axis=1)
        y = jnp.take(data.y, batch_idx)
        w = None if data.weights is None else jnp.take(data.weights, batch_idx)
        class_idx = (
            None if data.class_idx is None else jnp.take(data.class_idx, batch_idx)
        )
    def _loss_from_pred(pred, valid):
        """Loss from (pred, valid): the custom whole-prediction hook
        (loss_function / loss_function_expression,
        src/LossFunctions.jl:139-159) or the elementwise path — shared by
        the template and plain branches so the custom-loss contract can't
        diverge between them."""
        if loss_function is None:
            return aggregate_loss(elementwise_loss, pred, y, valid, w)
        flat_pred = pred.reshape(-1, pred.shape[-1])
        flat_valid = valid.reshape(-1)
        loss = jax.vmap(lambda p, v: loss_function(p, y, w, v))(
            flat_pred, flat_valid
        ).reshape(valid.shape)
        return jnp.where(
            valid & ~jnp.isnan(loss), loss, jnp.asarray(jnp.inf, loss.dtype)
        )

    if template is not None:
        # Template eval: combiner over subexpression callables
        # (/root/reference/src/TemplateExpression.jl:684-711); complexity
        # is the sum over subtrees (:552-562). Dimensional analysis does
        # not apply to templates (the combiner output has no unit
        # derivation) — documented API exclusion.
        from ..models.template import eval_template_batch

        t_params = (
            member_params[..., :, 0]
            if (member_params is not None and member_params.shape[-2] > 0)
            else None
        )
        pred, valid = eval_template_batch(trees, X, template, operators,
                                          params=t_params,
                                          fused=turbo, interpret=interpret)
        loss = _loss_from_pred(pred, valid)
        complexity = jnp.sum(compute_complexity_batch(trees, tables), axis=-1)
        cost = loss_to_cost(loss, data.baseline_loss, data.use_baseline,
                            complexity, parsimony)
        return cost, loss, complexity
    has_params = member_params is not None and member_params.shape[-2] > 0
    if has_params and class_idx is None:
        raise ValueError(
            "Parametric evaluation requires a `class` column in the dataset"
        )
    tb = tree_block if tree_block is not None else KERNEL_TREE_BLOCK
    tr = tile_rows if tile_rows is not None else KERNEL_TILE_ROWS
    fused_cost_path = (
        turbo and fuse_cost and loss_function is None and not has_params
        and not dedup
    )
    if fused_cost_path:
        # Hot path of the evolve cycle: complexity feeds the kernel's
        # cost epilogue, and (cost, loss) come back final — no
        # post-kernel [T]-shaped dispatches.
        complexity = compute_complexity_batch(trees, tables)
        cost, loss, _valid = fused_cost(
            trees, X, y, w, complexity, operators, elementwise_loss,
            baseline_loss=data.baseline_loss,
            use_baseline=data.use_baseline, parsimony=parsimony,
            tree_block=tb, tile_rows=tr, interpret=interpret, bf16=bf16,
        )
    elif turbo and loss_function is None:
        # Parametric members ride the fused kernel too: their banks
        # materialize as per-row buffer region values inside the kernel
        # (class one-hot contraction), no [T, NP, n] HBM buffers.
        loss, valid = fused_loss(
            trees, X, y, w, operators, elementwise_loss,
            params=member_params if has_params else None,
            class_idx=class_idx if has_params else None,
            tree_block=tb, tile_rows=tr,
            interpret=interpret, dedup=dedup, bf16=bf16,
        )
    else:
        params = (
            jnp.take(member_params, class_idx, axis=-1)  # [..., K, n]
            if has_params else None
        )
        if bf16 and loss_function is None and not has_params:
            # Interpreter-path mirror of the kernel's bf16 row tiles
            # (bf16 value storage, f32 loss reduction): cast X and the
            # constant bank so the eval buffer dtype is bfloat16, then
            # upcast predictions before the loss epilogue.
            trees_b = dataclasses.replace(
                trees, const=trees.const.astype(jnp.bfloat16))
            pred, valid = eval_tree_batch(
                trees_b, X.astype(jnp.bfloat16), operators, params=None)
            pred = pred.astype(jnp.float32)
        else:
            pred, valid = eval_tree_batch(trees, X, operators, params=params)
        loss = _loss_from_pred(pred, valid)
    if not fused_cost_path:
        complexity = compute_complexity_batch(trees, tables)
        cost = loss_to_cost(loss, data.baseline_loss, data.use_baseline,
                            complexity, parsimony)
    if data.x_dims is not None and dim_penalty is not None:
        # Single-sample dimensional check on the full dataset's first row
        # (src/DimensionalAnalysis.jl:223-257); violations add a flat cost
        # penalty (src/LossFunctions.jl:236-245).
        from ..ops.dims_eval import dimensional_violations_batch

        viol = dimensional_violations_batch(
            trees, data.Xt[:, 0], data.x_dims,
            (jnp.zeros((7,), jnp.float32) if data.y_dims is None
             else data.y_dims),
            jnp.bool_(data.y_dims is not None),
            operators, wildcard_constants=wildcard_constants,
        )
        cost = cost + jnp.asarray(dim_penalty, cost.dtype) * viol
    return cost, loss, complexity


# ---------------------------------------------------------------------------
# graftstage: staged sample-then-rescore evaluation (docs/PRECISION.md)
# ---------------------------------------------------------------------------

#: Floor for the screening sample — below this the screen's cost ranking
#: is too noisy to be worth a second launch.
MIN_SAMPLE_ROWS = 64


def resolve_sample_rows(cfg: EvolveConfig, n_rows: int) -> int:
    """Static screening-sample size for the staged eval path.

    Explicit ``staged_sample_rows`` wins; otherwise the sample is
    ``staged_sample_fraction`` of the dataset (or minibatch). The result
    is floored at MIN_SAMPLE_ROWS and capped at both the dataset size and
    ``cfg.eval_tile_rows`` — the latter is the shield degrade ladder
    contract: when ``degrade_eval_tile_rows`` halves the tile, the
    screening sample steps down with it so the screen launch never spans
    more than one row tile (tests/test_staged_eval.py).
    """
    if cfg.staged_sample_rows > 0:
        k = int(cfg.staged_sample_rows)
    else:
        k = int(-(-n_rows * cfg.staged_sample_fraction // 1))
    k = max(MIN_SAMPLE_ROWS, k)
    k = min(k, int(n_rows))
    if cfg.eval_tile_rows:
        k = min(k, int(cfg.eval_tile_rows))
    return max(1, k)


def rescore_count(cfg: EvolveConfig, n_candidates: int) -> int:
    """Static number of screened candidates promoted to the full-dataset
    rescore launch: ceil(rescore_fraction * N), at least 1."""
    r = int(-(-n_candidates * cfg.rescore_fraction // 1))
    return max(1, min(int(n_candidates), r))


# ---------------------------------------------------------------------------
# One bulk generation step (== one reg_evol_cycle)
# ---------------------------------------------------------------------------


def _onehot_rows_i(oh, x):
    """Integer-field row gather via one-hot matmul at HIGHEST precision
    (the default TPU matmul rounds f32 operands to bfloat16, which is
    only exact for integers up to 256); round() recovers the ints."""
    n = x.shape[0]
    out = jnp.round(jnp.matmul(oh, x.reshape(n, -1).astype(oh.dtype),
                               precision=jax.lax.Precision.HIGHEST))
    return out.astype(x.dtype).reshape((oh.shape[0],) + x.shape[1:])


def _onehot_rows_f(oh, x):
    """Float-field row gather via one-hot matmul. Sources are clamped:
    0 * inf = NaN would leak one row's overflowed value into every
    output row; callers that must preserve the NaN *verdict* of a
    selected row track it separately (see the crossover pack)."""
    n = x.shape[0]
    xf = jnp.nan_to_num(x.reshape(n, -1).astype(oh.dtype),
                        nan=3.0e38, posinf=3.0e38, neginf=-3.0e38)
    out = jnp.matmul(oh, xf, precision=jax.lax.Precision.HIGHEST)
    return out.astype(x.dtype).reshape((oh.shape[0],) + x.shape[1:])


def _member_take_onehot(pop: PopulationState, idx: jax.Array, P: int
                        ) -> PopulationState:
    """Batched ``pop.member(idx[b])`` for all slots at once.

    Tree fields ([P, L] or [P, K, L]) gather via a [B, P] one-hot matmul
    (MXU) — XLA's per-lane gather lowering serialized the vmapped
    ``jnp.take`` into a measurable per-cycle cost. Small [P] metadata
    vectors keep plain ``jnp.take``; lineage ids (birth/ref/parent) can
    exceed f32's exact-integer range on long runs, so they must not ride
    the float matmul.
    """
    oh = jax.nn.one_hot(idx, P, dtype=pop.trees.const.dtype)  # [B, P]
    take_tree_i = functools.partial(_onehot_rows_i, oh)
    # Clamped-gather semantics for floats: a parent with overflowed
    # constants yields huge-but-finite copies whose candidate evals go
    # invalid, same outcome as the NaN the old gather propagated.
    take_tree_f = functools.partial(_onehot_rows_f, oh)

    take = lambda x: jnp.take(x, idx, axis=0)
    return PopulationState(
        trees=TreeBatch(
            arity=take_tree_i(pop.trees.arity),
            op=take_tree_i(pop.trees.op),
            feat=take_tree_i(pop.trees.feat),
            const=take_tree_f(pop.trees.const),
            length=take_tree_i(pop.trees.length),
        ),
        cost=take(pop.cost),
        loss=take(pop.loss),
        complexity=take(pop.complexity),
        birth=take(pop.birth),
        ref=take(pop.ref),
        parent=take(pop.parent),
        params=take_tree_f(pop.params),
    )


def generation_step(
    key,
    pop: PopulationState,
    data,
    stats_nf,        # [maxsize] normalized frequencies (frozen per iteration)
    temperature,
    cur_maxsize,
    birth0,          # scalar int32 birth counter
    ref0,            # scalar int32 lineage counter
    cfg: EvolveConfig,
    options: Options,
    tables: ComplexityTables,
    elementwise_loss,
    batch_idx=None,
    marks=None,      # (simplify_mark [P], optimize_mark [P]) bools or None
    return_candidates=False,
) -> Tuple[PopulationState, jax.Array, jax.Array, jax.Array]:
    """Returns (new_pop, num_evals, new_birth0, new_ref0[, new_marks]).

    ``return_candidates`` appends the flat evaluated candidate TreeBatch
    to the return tuple — instrumentation for measuring structural
    duplication in the eval batch (profiling/dup_rate.py); unused
    outputs are DCE'd by jit so the default path is unaffected.

    ``marks`` track members whose sampled mutation kind was `simplify` or
    `optimize`. The reference applies those operations inline inside
    `mutate!` (/root/reference/src/Mutate.jl:571-658); on TPU a per-slot
    fold/BFGS would cost more than the whole cycle, so the member is kept
    unchanged (the reference's return_immediately contract) and the mark
    defers the actual operation to the iteration boundary, where folding
    and constant optimization already run batched over the population.
    """
    B = cfg.n_slots
    A = cfg.attempts
    P = cfg.population_size
    keys = jax.random.split(key, B)

    def tourney(k):
        return tournament_select(
            k, pop.cost, pop.complexity, stats_nf,
            tournament_n=cfg.tournament_n, p=cfg.tournament_p,
            use_frequency=cfg.use_frequency_in_tournament,
            adaptive_parsimony_scaling=cfg.adaptive_parsimony_scaling,
            maxsize=cfg.maxsize,
        )

    from .rng import USlice, u_bernoulli, u_categorical_weights, u_randint

    NKINDS = len(MUTATION_KINDS)
    ATT_NU = _attempt_nu(cfg)
    L2 = 2 * cfg.max_nodes
    TK = 2 if cfg.template is not None else 0  # template key draws
    # one bulk uniform draw covers every non-tournament decision of a slot
    SLOT_NU = 1 + NKINDS + TK + A * ATT_NU + A * L2 + 1 + 1 + 4

    # Tournaments + parent gathers hoisted OUT of the slot vmap: a
    # vmapped `jnp.take` over the member axis lowers to a serialized
    # custom gather (~3.4 ms/cycle at the bench config); batching all B
    # slots' parents into one one-hot matmul per field rides the MXU
    # instead. RNG stream layout (split(k, 3) per slot) is unchanged.
    slot_keys3 = jax.vmap(lambda k: jax.random.split(k, 3))(keys)  # [B, 3, 2]
    i1 = jax.vmap(tourney)(slot_keys3[:, 0])
    i2 = jax.vmap(tourney)(slot_keys3[:, 1])
    m1_all = _member_take_onehot(pop, i1, P)
    m2_all = _member_take_onehot(pop, i2, P)

    def slot_fn(ku_key, i1, i2, m1, m2):
        u = jax.random.uniform(ku_key, (SLOT_NU,))
        s = USlice(u)
        is_xover = u_bernoulli(s.take1(), cfg.crossover_probability)

        base_w = jnp.asarray(options.mutation_weights.as_vector(), jnp.float32)
        if cfg.template is not None:
            # Templates mutate ONE random subexpression
            # (get_contents_for_mutation,
            # /root/reference/src/TemplateExpression.jl:797-821); each key
            # carries its own argument count for feature sampling.
            K = cfg.template.n_subexpressions
            u_tk = s.take(TK)
            k1 = u_randint(u_tk[0], K)
            k2 = u_randint(u_tk[1], K)
            nfeat_arr = jnp.asarray(cfg.template.num_features, jnp.int32)
            tgt1 = _take_sub(m1.trees, k1)
            tgt2 = _take_sub(m2.trees, k2)
            mctx1 = cfg.mctx._replace(nfeatures=nfeat_arr[k1])
            w = _condition_weights(
                base_w, tgt1, m1.complexity, cur_maxsize, cfg,
                nfeat_dyn=nfeat_arr[k1],
            )
        else:
            tgt1 = m1.trees
            tgt2 = m2.trees
            mctx1 = None
            w = _condition_weights(
                base_w, tgt1, m1.complexity, cur_maxsize, cfg,
            )

        # ---- mutation path ----
        kind = u_categorical_weights(s.take(NKINDS), w)
        immediate = jnp.zeros((), jnp.bool_)
        for kid in _IMMEDIATE_KINDS:
            immediate = immediate | (kind == kid)

        # One structure derivation serves all attempts and branches (the
        # input tree is the same); crossover reuses the same tuples below.
        struct1 = M._tree_structure_single(tgt1.arity, tgt1.length)
        struct2 = M._tree_structure_single(tgt2.arity, tgt2.length)

        att_u = s.take(A * ATT_NU).reshape(A, ATT_NU)
        att_trees, att_ok = jax.vmap(
            lambda au: _apply_kind(
                kind, au, tgt1, temperature, cur_maxsize, cfg,
                structure=struct1, mctx=mctx1,
            )
        )(att_u)
        if cfg.template is not None:
            att_trees = jax.vmap(lambda t: _put_sub(m1.trees, t, k1))(att_trees)
            att_cons = template_check_batch(
                att_trees, options, tables, cur_maxsize, cfg.template
            )
        else:
            att_cons = check_constraints_batch(
                att_trees, options, tables, cur_maxsize
            )
        att_valid = att_ok & att_cons
        mut_tree, mut_success = _first_valid(att_valid, att_trees, m1.trees)

        # Parametric: mutate_constant takes the parameter-row branch half
        # the time, leaving the tree untouched
        # (/root/reference/src/ParametricExpression.jl:173-191).
        u_pb = s.take1()
        u_prow = s.take(4)
        mut_params = m1.params
        if cfg.n_params > 0:
            mutate_param = (
                (kind == _KIND["mutate_constant"]) & u_bernoulli(u_pb)
            )
            new_params = M.mutate_parameter_row(
                u_prow, m1.params, temperature, cfg.mctx
            )
            mut_params = jnp.where(mutate_param, new_params, m1.params)
            mut_tree = M._select_tree(mutate_param, m1.trees, mut_tree)
            mut_success = mut_success | mutate_param

        # ---- crossover path ----
        # (templates: each member contributes its chosen subexpression —
        # the keys may differ, validity is re-checked per key arity)
        xa_u = s.take(A * L2).reshape(A, L2)
        c1s, c2s, ok1s, ok2s = jax.vmap(
            lambda au: M.crossover_trees(
                au, tgt1, tgt2, cfg.mctx, struct1, struct2
            )
        )(xa_u)
        if cfg.template is not None:
            c1s = jax.vmap(lambda t: _put_sub(m1.trees, t, k1))(c1s)
            c2s = jax.vmap(lambda t: _put_sub(m2.trees, t, k2))(c2s)
            cons1 = template_check_batch(
                c1s, options, tables, cur_maxsize, cfg.template
            )
            cons2 = template_check_batch(
                c2s, options, tables, cur_maxsize, cfg.template
            )
        else:
            cons1 = check_constraints_batch(c1s, options, tables, cur_maxsize)
            cons2 = check_constraints_batch(c2s, options, tables, cur_maxsize)
        pair_valid = ok1s & ok2s & cons1 & cons2
        xo1, xo_success = _first_valid(pair_valid, c1s, m1.trees)
        xo2, _ = _first_valid(pair_valid, c2s, m2.trees)

        cand1 = M._select_tree(is_xover, xo1, mut_tree)
        cand2 = xo2
        # Crossover exchanges the whole parameter banks (the reference
        # swaps every row, /root/reference/src/ParametricExpression.jl:139-167).
        cand1_params = jnp.where(is_xover, m2.params, mut_params)
        cand2_params = m1.params
        needs_eval1 = jnp.where(is_xover, xo_success, mut_success & ~immediate)
        needs_eval2 = is_xover & xo_success
        return (
            is_xover, i1, i2, kind, immediate, mut_success, xo_success,
            cand1, cand2, cand1_params, cand2_params,
            needs_eval1, needs_eval2, s.take1(),
        )

    (is_xover, i1, i2, kind, immediate, mut_success, xo_success,
     cand1, cand2, cand1_params, cand2_params,
     needs_eval1, needs_eval2, accept_u) = jax.vmap(slot_fn)(
        slot_keys3[:, 2], i1, i2, m1_all, m2_all)

    # ---- one fused eval launch over all candidates ----
    # cand2 (crossover's second child) matters only on crossover slots —
    # ~p_crossover of them (default 0.066). Evaluating it everywhere would
    # double the eval work for a ~7% hit rate, so a small top-k pool of
    # crossover slots is packed into the launch instead; the pool is sized
    # ~3 sigma above the binomial mean, and the (rare) overflow slots fall
    # back to "crossover failed" (parents kept), matching a constraint
    # rejection. (See profiling/RESULTS.md.)
    p_x = cfg.crossover_probability
    import math as _math

    if p_x <= 0.0:
        k2 = 0
    elif p_x >= 0.5:
        k2 = B
    else:
        k2 = min(B, int(_math.ceil(
            B * p_x + 3.0 * _math.sqrt(B * p_x * (1.0 - p_x)) + 1.0
        )))

    def _eval_on(trees, params, idx):
        return eval_cost_batch(
            trees, data, elementwise_loss, tables, cfg.operators,
            cfg.parsimony, batch_idx=idx, member_params=params,
            turbo=cfg.turbo, interpret=cfg.interpret,
            loss_function=options.resolved_loss_function,
            dim_penalty=cfg.dim_penalty,
            wildcard_constants=cfg.wildcard_constants,
            template=cfg.template,
            tree_block=cfg.eval_tree_block, tile_rows=cfg.eval_tile_rows,
            fuse_cost=cfg.fuse_cost, bf16=cfg.eval_bf16,
        )

    # graftstage staged path (docs/PRECISION.md): screen every candidate
    # on a deterministic strided row sample, then rescore only the top
    # rescore_fraction on the full row set. Acceptance and the HoF
    # consume only fully-rescored costs — unrescored candidates carry
    # NaN cost, which both the mutation acceptance (~isnan below) and
    # the crossover xo_nan rejection treat as "candidate failed, keep
    # the parent", so no sample-estimated cost ever enters the
    # population. Row selection reuses the serve overload ladder's
    # strided shed (replay-stable, no RNG).
    n_data_rows = (int(batch_idx.shape[0]) if batch_idx is not None
                   else int(data.y.shape[0]))
    staged = (cfg.staged_eval and cfg.template is None
              and options.resolved_loss_function is None)
    sample_rows = resolve_sample_rows(cfg, n_data_rows) if staged else 0
    staged = staged and sample_rows < n_data_rows

    if staged:
        from ..ops.fused_eval import strided_sample_indices

        strided = jnp.asarray(
            strided_sample_indices(n_data_rows, sample_rows))
        screen_idx = (strided if batch_idx is None
                      else jnp.take(batch_idx, strided))

        def _eval(trees, params):
            bshape = trees.batch_shape
            flat = trees.reshape(-1)
            N = flat.length.shape[0]
            p_flat = params.reshape((N,) + params.shape[len(bshape):])
            # 1) screen: every candidate, sample rows only.
            c_s, l_s, x_s = _eval_on(flat, p_flat, screen_idx)
            R = rescore_count(cfg, N)
            # 2) pack the top-R screened candidates (NaN screens rank
            # last) via the one-hot matmul row-take, exactly like the
            # crossover pool below.
            score = jnp.where(jnp.isnan(c_s), jnp.inf, c_s)
            _, sel_r = jax.lax.top_k(-score, R)
            oh_r = jax.nn.one_hot(sel_r, N, dtype=flat.const.dtype)
            sel_trees = TreeBatch(
                arity=_onehot_rows_i(oh_r, flat.arity),
                op=_onehot_rows_i(oh_r, flat.op),
                feat=_onehot_rows_i(oh_r, flat.feat),
                const=_onehot_rows_f(oh_r, flat.const),
                length=_onehot_rows_i(oh_r, flat.length),
            )
            sel_params = _onehot_rows_f(oh_r, p_flat)
            # The float gather clamps non-finite sources; track rows
            # whose raw genome was bad so their NaN verdict survives
            # the rescore (same contract as the pool's slot_bad2).
            row_bad = (
                ~jnp.all(jnp.isfinite(flat.const.reshape(N, -1)), axis=1)
                | ~jnp.all(jnp.isfinite(p_flat.reshape(N, -1)), axis=1)
            )
            # 3) rescore on the full row set (or the cycle minibatch).
            c_r, l_r, _ = _eval_on(sel_trees, sel_params, batch_idx)
            bad_sel = jnp.take(row_bad, sel_r)
            c_r = jnp.where(bad_sel, jnp.nan, c_r)
            l_r = jnp.where(bad_sel, jnp.asarray(jnp.inf, l_r.dtype), l_r)
            # 4) scatter back; unrescored candidates stay NaN-cost.
            # Complexity is row-count independent — the screen's value
            # is exact for every candidate.
            cost = jnp.full((N,), jnp.nan, c_r.dtype).at[sel_r].set(c_r)
            loss = jnp.full(
                (N,), jnp.inf, l_r.dtype).at[sel_r].set(l_r)
            return (cost.reshape(bshape), loss.reshape(bshape),
                    x_s.reshape(bshape))
    else:
        def _eval(trees, params):
            return _eval_on(trees, params, batch_idx)

    if 0 < k2 < B:
        _, sel2 = jax.lax.top_k(is_xover.astype(jnp.float32), k2)
        # One-hot matmul row-take (vmapped fancy-index gathers serialize
        # on TPU; a where+masked-sum materializes [k2, B, L] per field).
        # HIGHEST precision keeps the f32 pass exact; sources are clamped
        # (0 * inf = NaN would leak across rows), and the rows that DID
        # carry non-finite constants are tracked explicitly so the
        # xo_nan rejection below still fires for them.
        oh2 = jax.nn.one_hot(sel2, B, dtype=cand2.const.dtype)  # [k2, B]
        cand2_sel = TreeBatch(
            arity=_onehot_rows_i(oh2, cand2.arity),
            op=_onehot_rows_i(oh2, cand2.op),
            feat=_onehot_rows_i(oh2, cand2.feat),
            const=_onehot_rows_f(oh2, cand2.const),
            length=_onehot_rows_i(oh2, cand2.length),
        )
        params2_sel = _onehot_rows_f(oh2, cand2_params)
        slot_bad2 = (
            ~jnp.all(jnp.isfinite(cand2.const.reshape(B, -1)), axis=1)
            | ~jnp.all(jnp.isfinite(cand2_params.reshape(B, -1)), axis=1)
        )  # [B] per original slot; scattered onto cost below
        packed = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), cand1, cand2_sel
        )  # [B + k2, ...]
        packed_params = jnp.concatenate([cand1_params, params2_sel], axis=0)
        eval_batch = packed
        n_eval_rows = B + k2
        c_all, l_all, x_all = _eval(packed, packed_params)
        inf = jnp.asarray(jnp.inf, c_all.dtype)

        def unpack(v, default):
            v2 = jnp.full((B,), default, v.dtype).at[sel2].set(v[B:])
            return jnp.stack([v[:B], v2], axis=1)

        cost = unpack(c_all, inf)
        loss = unpack(l_all, inf)
        complexity = unpack(x_all, jnp.int32(1))
        # rows whose raw cand2 carried non-finite constants/params were
        # evaluated on clamped copies; restore the NaN verdict so the
        # xo_nan rejection matches an un-clamped gather
        cost = cost.at[:, 1].set(
            jnp.where(slot_bad2, jnp.nan, cost[:, 1]))
        # slots beyond the pool didn't get cand2 evaluated: treat as a
        # failed crossover (no replacement, no eval counted)
        xover_rank = jnp.cumsum(is_xover.astype(jnp.int32)) - 1
        overflow = is_xover & (xover_rank >= k2)
        xo_success = xo_success & ~overflow
        needs_eval2 = needs_eval2 & ~overflow
    else:
        if k2 == 0:
            # crossover disabled: cand2 is never consulted
            eval_batch = cand1
            n_eval_rows = B
            cost1, loss1, cx1 = _eval(cand1, cand1_params)
            inf = jnp.asarray(jnp.inf, cost1.dtype)
            cost = jnp.stack([cost1, jnp.full((B,), inf)], axis=1)
            loss = jnp.stack([loss1, jnp.full((B,), inf)], axis=1)
            complexity = jnp.stack(
                [cx1, jnp.ones((B,), jnp.int32)], axis=1
            )
        else:
            both = jax.tree.map(
                lambda a, b: jnp.stack([a, b], axis=1), cand1, cand2
            )  # [B, 2, ...]
            both_params = jnp.stack([cand1_params, cand2_params], axis=1)
            eval_batch = jax.tree.map(
                lambda x: x.reshape((2 * B,) + x.shape[2:]), both)
            n_eval_rows = 2 * B
            cost, loss, complexity = _eval(both, both_params)
    needs_eval = jnp.stack([needs_eval1, needs_eval2], axis=1)
    num_evals = jnp.sum(needs_eval.astype(jnp.float32))

    # ---- accept logic (src/Mutate.jl:270-355) ----
    m1_cost = pop.cost[i1]
    m1_loss = pop.loss[i1]
    m1_complexity = pop.complexity[i1]
    after_cost = cost[:, 0]
    after_loss = loss[:, 0]
    after_cx = complexity[:, 0]

    prob = jnp.ones_like(after_cost)
    if cfg.annealing:
        delta = after_cost - m1_cost
        prob = prob * jnp.exp(-delta / (cfg.alpha * temperature + 1e-12))
    if cfg.use_frequency:
        def freq_of(sz):
            in_r = (sz > 0) & (sz <= cfg.maxsize)
            return jnp.where(
                in_r, stats_nf[jnp.clip(sz - 1, 0, cfg.maxsize - 1)], 1e-6
            )
        prob = prob * (freq_of(m1_complexity) / jnp.maximum(freq_of(after_cx), 1e-12)
                       ).astype(prob.dtype)
    anneal_ok = accept_u < jnp.where(jnp.isnan(prob), 0.0, prob)
    accepted_mut = mut_success & ~jnp.isnan(after_cost) & anneal_ok

    # Immediate kinds always "accept" the (unchanged) member, keeping its
    # cost/loss (do_nothing / simplify / optimize, src/Mutate.jl:571-658).
    mut_replace = jnp.where(
        immediate, jnp.bool_(True),
        jnp.where(accepted_mut, True, ~jnp.bool_(cfg.skip_mutation_failures)),
    )
    # m1_all was gathered via the one-hot matmul above — a fresh
    # pop.member(i1) here re-gathers every tree field through XLA's
    # serialized kCustom lowering (~5 ms/cycle at the bench config).
    # The one-hot float gather CLAMPS non-finite constants (see
    # _onehot_rows_f); a kept-parent fallback would otherwise write the
    # clamped genome back into the population, so slots whose parent
    # carried non-finite constants/params get a NaN planted in slot 0 —
    # the stored member stays invalid-on-eval exactly like its parent
    # (whose cost, carried below, is already inf).
    m1_params = m1_all.params
    # Non-finiteness only matters where eval actually reads it (const at
    # live LEAF_CONST leaves — ops/eval.py:91, ops/program.py const_ok —
    # and the param bank), so the bad flag and the NaN plant are both
    # restricted to those lanes: planting only in slot 0 was ignored
    # whenever slot 0 held a VAR/PARAM leaf, letting the clamped genome
    # re-enter with a finite cost at the iteration boundary.
    lane = jnp.arange(pop.trees.const.shape[-1])
    cleaf = ((pop.trees.arity == 0) & (pop.trees.op == LEAF_CONST)
             & (lane < pop.trees.length[..., None]))
    bad_const = jnp.any(
        (cleaf & ~jnp.isfinite(pop.trees.const)).reshape(P, -1), axis=1)
    bad_params = ~jnp.all(jnp.isfinite(pop.params.reshape(P, -1)), axis=1)
    slot_bad1 = jnp.take(bad_const | bad_params, i1)        # [B]
    fb_trees = m1_all.trees
    fb_cleaf = (fb_trees.arity == 0) & (fb_trees.op == LEAF_CONST)
    nan_mark = (
        slot_bad1.reshape((-1,) + (1,) * (fb_trees.const.ndim - 1))
        & fb_cleaf)
    fb_trees = dataclasses.replace(
        fb_trees, const=jnp.where(nan_mark, jnp.nan, fb_trees.const))
    # When the parent's non-finiteness lived in its params, the clamped
    # param bank needs the same invalid marker.
    bad_p1 = jnp.take(bad_params, i1)
    m1_params = jnp.where(
        bad_p1.reshape((-1,) + (1,) * (m1_params.ndim - 1)),
        jnp.nan, m1_params)
    accept1 = accepted_mut & ~immediate
    baby1_tree = M._select_tree(accept1, cand1, fb_trees)
    baby1_params = jnp.where(
        accept1.reshape(accept1.shape + (1, 1)), cand1_params, m1_params
    )
    baby1_cost = jnp.where(accept1, after_cost, m1_cost)
    baby1_loss = jnp.where(accept1, after_loss, m1_loss)
    baby1_cx = jnp.where(accept1, after_cx, m1_complexity)

    # Crossover babies replace unconditionally when constraints passed
    # (crossover_generation, src/Mutate.jl:661-733).
    xo_nan = jnp.isnan(cost[:, 0]) | jnp.isnan(cost[:, 1])
    xo_replace = xo_success & ~xo_nan

    tele = None
    if cfg.collect_telemetry:
        from ..telemetry.counters import step_telemetry

        tele = step_telemetry(
            kind=kind, is_xover=is_xover, immediate=immediate,
            accepted_mut=accepted_mut, xo_replace=xo_replace,
            mut_success=mut_success, xo_success=xo_success,
            after_cost=after_cost, xo_nan=xo_nan, anneal_ok=anneal_ok,
            cost=cost, needs_eval1=needs_eval1, needs_eval2=needs_eval2,
            n_eval_rows=n_eval_rows,
            n_screen_rows=n_eval_rows if staged else 0,
            n_rescore_rows=(rescore_count(cfg, n_eval_rows)
                            if staged else 0),
        )

    replace1 = jnp.where(is_xover, xo_replace, mut_replace)
    replace2 = is_xover & xo_replace
    baby1_tree = M._select_tree(is_xover, cand1, baby1_tree)
    baby1_params = jnp.where(
        is_xover.reshape(is_xover.shape + (1, 1)), cand1_params, baby1_params
    )
    baby1_cost = jnp.where(is_xover, cost[:, 0], baby1_cost)
    baby1_loss = jnp.where(is_xover, loss[:, 0], baby1_loss)
    baby1_cx = jnp.where(is_xover, complexity[:, 0], baby1_cx)

    babies = jax.tree.map(lambda a, b: jnp.stack([a, b], axis=1), baby1_tree, cand2)
    baby_params = jnp.stack([baby1_params, cand2_params], axis=1)  # [B,2,K,C]
    baby_cost = jnp.stack([baby1_cost, cost[:, 1]], axis=1)
    baby_loss = jnp.stack([baby1_loss, loss[:, 1]], axis=1)
    baby_cx = jnp.stack([baby1_cx, complexity[:, 1]], axis=1)
    baby_parent = jnp.stack([pop.ref[i1], pop.ref[i2]], axis=1)
    replace = jnp.stack([replace1, replace2], axis=1)  # [B, 2]

    # ---- replace oldest members (distinct targets per baby) ----
    flat_replace = replace.reshape(-1)
    nb = flat_replace.shape[0]
    flat_babies = jax.tree.map(lambda x: x.reshape(nb, *x.shape[2:]), babies)
    order = jnp.argsort(pop.birth)  # oldest first
    rank = jnp.cumsum(flat_replace.astype(jnp.int32)) - 1
    # When more than P babies replace in one step (possible only when
    # tournament_n is low enough that 2*n_slots > P), ranks clip to the
    # same slot; scatter order for colliding indices is UNDEFINED in
    # XLA, so the superseded rows are routed to the drop slot instead —
    # the LAST replacement deterministically survives (matching the
    # reference's sequential oldest-replacement order) and the event
    # log below agrees with the population by construction.
    nrep = jnp.sum(flat_replace.astype(jnp.int32))
    survives = flat_replace & ((rank < P - 1) | (rank == nrep - 1))
    target = jnp.where(
        survives, order[jnp.clip(rank, 0, P - 1)], P  # P = drop slot
    )

    def scatter(dst, src):
        return dst.at[target].set(src, mode="drop")

    new_trees = TreeBatch(
        arity=scatter(pop.trees.arity, flat_babies.arity),
        op=scatter(pop.trees.op, flat_babies.op),
        feat=scatter(pop.trees.feat, flat_babies.feat),
        const=scatter(pop.trees.const, flat_babies.const),
        length=scatter(pop.trees.length, flat_babies.length),
    )
    new_birth = birth0 + jnp.arange(nb, dtype=jnp.int32)
    new_ref = ref0 + jnp.arange(nb, dtype=jnp.int32)

    events = None
    if cfg.record_events:
        XO = jnp.int32(len(MUTATION_KINDS))  # crossover pseudo-kind
        k1 = jnp.where(is_xover, XO, kind)
        # child-2 rows exist only for crossover slots; -1 marks the
        # phantom rows so they never count as rejected crossovers
        k2_kind = jnp.where(is_xover, XO, -1)
        parent2_1 = jnp.where(is_xover, pop.ref[i2], -1)
        parent_cost2 = jnp.stack([m1_cost, pop.cost[i2]], axis=1)
        # Rejection reasons (codes in the CycleEvents docstring).
        # "invalid" covers any non-finite candidate cost: +inf losses
        # (invalid evals map to inf, not NaN) would otherwise fall
        # through to prob=0 and be mislabeled "annealing".
        mut_reason = jnp.where(
            ~mut_success, 1,
            jnp.where(~jnp.isfinite(after_cost), 2,
                      jnp.where(~anneal_ok, 3, 0))).astype(jnp.int32)
        xo_reason = jnp.where(
            ~xo_success, 1, jnp.where(xo_nan, 2, 0)).astype(jnp.int32)
        reason1 = jnp.where(
            is_xover, xo_reason, jnp.where(immediate, 0, mut_reason))
        reason2 = jnp.where(is_xover, xo_reason, 0)
        events = CycleEvents(
            kind=jnp.stack([k1, k2_kind], axis=1).reshape(-1),
            parent_ref=baby_parent.reshape(-1),
            parent2_ref=jnp.stack([parent2_1, pop.ref[i1]],
                                  axis=1).reshape(-1),
            child_ref=new_ref,
            died_ref=jnp.where(
                survives,
                jnp.take(pop.ref, order[jnp.clip(rank, 0, P - 1)]), -1),
            accepted=survives,
            cost_delta=(baby_cost.reshape(-1)
                        - parent_cost2.reshape(-1)),
            reject_reason=jnp.stack(
                [reason1, reason2], axis=1).reshape(-1),
        )
    new_pop = PopulationState(
        trees=new_trees,
        cost=scatter(pop.cost, baby_cost.reshape(-1)),
        loss=scatter(pop.loss, baby_loss.reshape(-1)),
        complexity=scatter(pop.complexity, baby_cx.reshape(-1)),
        birth=scatter(pop.birth, new_birth),
        ref=scatter(pop.ref, new_ref),
        parent=scatter(pop.parent, baby_parent.reshape(-1)),
        params=scatter(
            pop.params, baby_params.reshape(nb, *baby_params.shape[2:])
        ),
    )
    if marks is None:
        out = (new_pop, num_evals, birth0 + nb, ref0 + nb)
        if cfg.collect_telemetry:
            out = out + (tele,)
        if cfg.record_events:
            out = out + (events,)
        if return_candidates:
            out = out + (eval_batch,)
        return out
    # Deferred simplify/optimize marks ride the replacement scatter: the
    # surviving copy of the member carries the flag; replaced slots that
    # got ordinary babies are cleared.
    simp_mark, opt_mark = marks
    not_xover = ~is_xover
    flag1_simp = not_xover & (kind == _KIND["simplify"]) & replace1
    flag1_opt = not_xover & (kind == _KIND["optimize"]) & replace1
    zeros2 = jnp.zeros_like(flag1_simp)
    simp_flags = jnp.stack([flag1_simp, zeros2], axis=1).reshape(-1)
    opt_flags = jnp.stack([flag1_opt, zeros2], axis=1).reshape(-1)
    new_marks = (
        scatter(simp_mark, simp_flags),
        scatter(opt_mark, opt_flags),
    )
    out = (new_pop, num_evals, birth0 + nb, ref0 + nb, new_marks)
    if cfg.collect_telemetry:
        out = out + (tele,)
    if cfg.record_events:
        out = out + (events,)
    if return_candidates:
        out = out + (eval_batch,)
    return out


# ---------------------------------------------------------------------------
# Best-seen hall of fame (per complexity), device-resident
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class HofState:
    trees: TreeBatch      # [..., maxsize, L]
    cost: jax.Array       # [..., maxsize]
    loss: jax.Array       # [..., maxsize]
    complexity: jax.Array  # [..., maxsize] int32
    exists: jax.Array     # [..., maxsize] bool
    params: jax.Array     # [..., maxsize, n_params, n_classes]


def empty_hof(maxsize: int, max_nodes: int, dtype,
              n_params: int = 0, n_classes: int = 0,
              template_k: int = 0) -> HofState:
    """``template_k`` > 0 gives HoF trees the template key axis
    [maxsize, K, L]."""
    tree_shape = (maxsize, template_k) if template_k else (maxsize,)
    return HofState(
        trees=TreeBatch.empty(tree_shape, max_nodes, dtype),
        cost=jnp.full((maxsize,), jnp.inf, dtype),
        loss=jnp.full((maxsize,), jnp.inf, dtype),
        complexity=jnp.zeros((maxsize,), jnp.int32),
        exists=jnp.zeros((maxsize,), jnp.bool_),
        params=jnp.zeros((maxsize, n_params, n_classes), dtype),
    )


def update_hof(hof: HofState, pop: PopulationState, maxsize: int) -> HofState:
    """Per-complexity best update (s_r_cycle's best_examples_seen,
    src/SingleIteration.jl:53-62). Unbatched (single island)."""
    P = pop.cost.shape[-1]
    sizes = jnp.arange(1, maxsize + 1)[:, None]  # [maxsize, 1]
    m = (pop.complexity[None, :] == sizes)       # [maxsize, P]
    cost_m = jnp.where(m, pop.cost[None, :], jnp.inf)
    best_idx = jnp.argmin(cost_m, axis=1)
    best_cost = jnp.take_along_axis(cost_m, best_idx[:, None], axis=1)[:, 0]
    better = best_cost < hof.cost

    def pick(hof_field, pop_field):
        gathered = jnp.take(pop_field, best_idx, axis=0)
        shape = (maxsize,) + (1,) * (gathered.ndim - 1)
        return jnp.where(better.reshape(shape), gathered, hof_field)

    return HofState(
        trees=TreeBatch(
            arity=pick(hof.trees.arity, pop.trees.arity),
            op=pick(hof.trees.op, pop.trees.op),
            feat=pick(hof.trees.feat, pop.trees.feat),
            const=pick(hof.trees.const, pop.trees.const),
            length=pick(hof.trees.length, pop.trees.length),
        ),
        cost=jnp.where(better, best_cost, hof.cost),
        loss=pick(hof.loss, pop.loss),
        complexity=pick(hof.complexity, pop.complexity),
        exists=hof.exists | better,
        params=pick(hof.params, pop.params),
    )


def s_r_cycle(
    key,
    pop: PopulationState,
    data,
    stats_nf,
    cur_maxsize,
    birth0,
    ref0,
    cfg: EvolveConfig,
    options: Options,
    tables: ComplexityTables,
    elementwise_loss,
    batch_idx=None,
    c0=None,
    total_cycles: Optional[int] = None,
    carry_in=None,
):
    """``cfg.ncycles`` generation steps over the annealing ramp; returns
    (pop, best_seen_hof, num_evals, birth0, ref0, marks).

    Chunked execution (host budget checks between chunks): ``c0`` is the
    global cycle offset, ``total_cycles`` the full iteration's cycle
    count (annealing ramp + per-cycle key fold-in use the *global* index,
    so chunked and single-launch iterations are bit-identical), and
    ``carry_in`` = (best_seen, num_evals, marks) accumulated by prior
    chunks.
    """
    ncycles = cfg.ncycles
    total = total_cycles if total_cycles is not None else ncycles
    tele0 = None
    if carry_in is not None:
        if cfg.collect_telemetry:
            hof0, nev0, marks0, tele0 = carry_in
        else:
            hof0, nev0, marks0 = carry_in
    else:
        hof0 = empty_hof(
            cfg.maxsize, cfg.max_nodes, pop.cost.dtype, cfg.n_params,
            cfg.n_classes,
            template_k=(cfg.template.n_subexpressions if cfg.template else 0),
        )
        P = pop.cost.shape[0]
        marks0 = (jnp.zeros((P,), jnp.bool_), jnp.zeros((P,), jnp.bool_))
        nev0 = jnp.float32(0.0)
        if cfg.collect_telemetry:
            from ..telemetry.counters import empty_cycle_telemetry

            tele0 = empty_cycle_telemetry()
    if c0 is None:
        c0 = jnp.int32(0)

    def cycle(carry, c):
        pop, hof, birth, ref, nev, marks, tele = carry
        gc = c + c0  # global cycle index
        if cfg.annealing and total > 1:
            temperature = 1.0 - gc.astype(pop.cost.dtype) / (total - 1)
        else:
            temperature = jnp.asarray(1.0, pop.cost.dtype)
        k = jax.random.fold_in(key, gc)
        out = generation_step(
            k, pop, data, stats_nf, temperature, cur_maxsize, birth, ref,
            cfg, options, tables, elementwise_loss, batch_idx=batch_idx,
            marks=marks,
        )
        pop, nev_c, birth, ref, marks = out[:5]
        pos = 5
        if cfg.collect_telemetry:
            from ..telemetry.counters import add_cycle_telemetry

            tele = add_cycle_telemetry(tele, out[pos])
            pos += 1
        events = out[pos] if cfg.record_events else None
        hof = update_hof(hof, pop, cfg.maxsize)
        return (pop, hof, birth, ref, nev + nev_c, marks, tele), events

    (pop, hof, birth0, ref0, num_evals, marks, tele), events = jax.lax.scan(
        cycle, (pop, hof0, birth0, ref0, nev0, marks0, tele0),
        jnp.arange(ncycles, dtype=jnp.int32),
    )
    ret = (pop, hof, num_evals, birth0, ref0, marks)
    if cfg.collect_telemetry:
        ret = ret + (tele,)
    if cfg.record_events:
        # events: CycleEvents of [ncycles, 2B] arrays
        ret = ret + (events,)
    return ret
