"""RNG helpers for device-side evolution.

Threaded `jax.random` keys replace the reference's global RNG; keys are
split per (island, cycle, slot, purpose) so runs are reproducible with a
seed (deterministic-mode semantics of src/Utils.jl:14-24 fall out for
free: device evolution is always deterministic given the key).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["randint_dyn", "masked_choice", "categorical_from_weights"]


def randint_dyn(key, n, shape=()):
    """Uniform integer in [0, n) with a *traced* upper bound (n >= 1)."""
    u = jax.random.uniform(key, shape)
    return jnp.minimum((u * n).astype(jnp.int32), jnp.asarray(n - 1, jnp.int32))


def masked_choice(key, mask):
    """Uniform choice among True entries of ``mask`` (1-D).

    Returns (index, has_any). When no entry is True, index is 0 and
    has_any False — callers must treat the pick as a failed attempt.
    """
    logits = jnp.where(mask, 0.0, -jnp.inf)
    has_any = jnp.any(mask)
    idx = jnp.where(
        has_any, jax.random.categorical(key, logits), jnp.int32(0)
    ).astype(jnp.int32)
    return idx, has_any


def categorical_from_weights(key, weights):
    """Sample an index proportional to non-negative ``weights`` (1-D)."""
    logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-30)), -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)
