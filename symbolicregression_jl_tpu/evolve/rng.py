"""RNG helpers for device-side evolution.

Threaded `jax.random` keys replace the reference's global RNG; keys are
split per (island, cycle, slot, purpose) so runs are reproducible with a
seed (deterministic-mode semantics of src/Utils.jl:14-24 fall out for
free: device evolution is always deterministic given the key).
"""
# graftlint: assume-traced — pure device-kernel module; callers jit/vmap
# these functions from other modules, outside the module-local analysis.

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["randint_dyn", "masked_choice", "categorical_from_weights",
           "USlice", "u_randint", "u_masked_choice", "u_bernoulli",
           "u_normal", "u_categorical_weights"]


def randint_dyn(key, n, shape=()):
    """Uniform integer in [0, n) with a *traced* upper bound (n >= 1)."""
    u = jax.random.uniform(key, shape)
    return jnp.minimum((u * n).astype(jnp.int32), jnp.asarray(n - 1, jnp.int32))


def masked_choice(key, mask):
    """Uniform choice among True entries of ``mask`` (1-D).

    Returns (index, has_any). When no entry is True, index is 0 and
    has_any False — callers must treat the pick as a failed attempt.
    """
    logits = jnp.where(mask, 0.0, -jnp.inf)
    has_any = jnp.any(mask)
    idx = jnp.where(
        has_any, jax.random.categorical(key, logits), jnp.int32(0)
    ).astype(jnp.int32)
    return idx, has_any


def categorical_from_weights(key, weights):
    """Sample an index proportional to non-negative ``weights`` (1-D)."""
    logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-30)), -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Bulk-uniform randomness
#
# Chained `jax.random.split` / sampler calls cost one device op each; the
# evolution step made ~1000 of them per cycle, dominating machinery time.
# Instead each consumer takes static slices of ONE pre-generated uniform
# vector and derives ints / Bernoullis / normals / categoricals with
# plain arithmetic that fuses into its surroundings.
# ---------------------------------------------------------------------------


class USlice:
    """Static-cursor view over a flat uniform(0,1) vector.

    The cursor is *trace-time-only* state by design: ``i`` is a static
    Python int advanced while the kernel traces, so every ``take``
    lowers to a static slice. The instance never outlives one trace
    (kernels construct it from their own ``u`` argument)."""

    def __init__(self, u):
        self.u = u  # graftlint: disable=GL005
        self.i = 0  # graftlint: disable=GL005

    def take(self, n: int):
        s = jax.lax.slice_in_dim(self.u, self.i, self.i + n)
        self.i += n  # graftlint: disable=GL005 (static trace-time cursor)
        return s

    def take1(self):
        return self.take(1)[0]


def u_randint(u, n):
    """Uniform int in [0, n) from one uniform scalar (traced n >= 1)."""
    return jnp.minimum((u * n).astype(jnp.int32), jnp.asarray(n - 1, jnp.int32))


def u_masked_choice(u_vec, mask):
    """Uniform choice among True entries from a [len(mask)] uniform slice."""
    has_any = jnp.any(mask)
    idx = jnp.argmax(jnp.where(mask, u_vec, -1.0)).astype(jnp.int32)
    return jnp.where(has_any, idx, 0), has_any


def u_bernoulli(u, p=0.5):
    return u < p


def u_normal(u):
    """Standard normal via the inverse CDF (elementwise, fusable)."""
    from jax.scipy.special import ndtri

    return ndtri(jnp.clip(u, 1e-7, 1.0 - 1e-7))


def u_categorical_weights(u_vec, weights):
    """Index ~ weights (1-D, non-negative) via the Gumbel trick on a
    [len(weights)] uniform slice."""
    g = -jnp.log(-jnp.log(jnp.clip(u_vec, 1e-12, 1.0 - 1e-7)))
    logits = jnp.where(
        weights > 0, jnp.log(jnp.maximum(weights, 1e-30)), -jnp.inf
    )
    return jnp.argmax(logits + g).astype(jnp.int32)
