"""Batched constant optimization via jax.grad — replaces Optim.jl BFGS +
Enzyme/Mooncake AD (/root/reference/src/ConstantOptimization.jl).

All selected members are optimized in one launch: a vmapped BFGS with
backtracking line search over the tree's constant slots (masked to the
actual constant leaves), with `optimizer_nrestarts` perturbed restarts as
an extra batched axis (src/ConstantOptimization.jl:90-100). Acceptance
only when the best minimum beats the pre-optimization loss (:102-113).

The reference switches to Newton for single-constant trees (:38-47); BFGS
with backtracking converges equivalently for 1-D problems, so one code
path serves all arities.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core.losses import aggregate_loss, loss_to_cost
from ..ops.encoding import LEAF_CONST, TreeBatch, tree_structure_arrays
from ..ops.eval import eval_single_tree
from ..ops.fused_eval import fused_grad_multi, fused_loss_multi
from ..ops.program import compile_program

__all__ = ["OptimizerConfig", "optimize_constants_batch",
           "optimize_constants_fused", "optimize_constants_template"]


class OptimizerConfig(NamedTuple):
    iterations: int = 8          # optimizer_iterations default, src/Options.jl:989
    nrestarts: int = 2           # optimizer_nrestarts, :616
    max_linesearch: int = 8
    c1: float = 1e-4             # Armijo condition coefficient
    shrink: float = 0.5
    # bfloat16 line-search evals (fused path only): doubles the
    # variants-per-dispatch of the dominant kernel; candidate losses
    # only pick the step size, and the accepted point is re-verified at
    # f32 (descent guard in `optimize_constants_fused`).
    ls_bf16: bool = False
    # Kernel launch plan for the fused path (see profiling/opt_bench.py
    # for the sweep behind these defaults): V-chunk sizes and VMEM
    # budgets for the line-search (`fused_loss_multi`) and gradient
    # (`fused_grad_multi`) kernels. `None` = the kernels' own defaults.
    ls_v_chunk: Optional[int] = None
    ls_tile_budget: Optional[int] = None
    grad_v_chunk: Optional[int] = None
    grad_tile_budget: Optional[int] = None
    tree_block: Optional[int] = None
    # Demote line-search-failed rows to 1-step programs (fused path),
    # freezing them for the remaining iterations, and skip members with
    # no constant leaves entirely; f_calls counts only live rows
    # (reference analogue: Optim.jl's convergence stop,
    # src/ConstantOptimization.jl:86-100). Default OFF: measured on the
    # bench config, <5% of rows ever fail their breadth-C line search
    # (profiling/opt_bench.py), so the saving is marginal — and a failed
    # row is NOT exactly dead in this implementation (the pushed zero
    # pair resets the two-loop gamma scaling to 1, so the next direction
    # differs and can recover), making the freeze a slight semantic
    # deviation as well.
    early_exit: bool = False


def _bfgs_minimize(f, x0, mask, cfg: OptimizerConfig):
    """Minimize f over masked dims of x0. Returns (x_best, f_best, f_calls).

    Fixed-iteration BFGS with backtracking; masked (non-constant) dims have
    zero gradient and identity Hessian rows, so they never move.
    """
    n = x0.shape[0]
    eye = jnp.eye(n, dtype=x0.dtype)
    vg = jax.value_and_grad(f)

    def masked_grad(x):
        v, g = vg(x)
        g = jnp.where(mask, g, 0.0)
        g = jnp.where(jnp.isfinite(g), g, 0.0)
        return v, g

    f0, g0 = masked_grad(x0)

    def one_iteration(carry, _):
        x, fx, g, H, calls = carry
        d = -(H @ g)
        dg = jnp.dot(d, g)
        use_sd = dg >= 0
        d = jnp.where(use_sd, -g, d)
        dg = jnp.where(use_sd, -jnp.dot(g, g), dg)

        def ls_step(ls, _):
            t, best_t, best_f, done = ls
            x_try = x + t * d
            f_try = f(x_try)
            ok = (f_try <= fx + cfg.c1 * t * dg) & jnp.isfinite(f_try)
            take = ok & ~done
            best_t = jnp.where(take, t, best_t)
            best_f = jnp.where(take, f_try, best_f)
            return (t * cfg.shrink, best_t, best_f, done | ok), None

        (_, t_star, f_star, found), _ = jax.lax.scan(
            ls_step,
            (jnp.ones((), x.dtype), jnp.zeros((), x.dtype), fx, jnp.bool_(False)),
            None, length=cfg.max_linesearch,
        )
        s = t_star * d
        x_new = x + s
        f_new, g_new = masked_grad(x_new)
        f_new = jnp.where(found, f_new, fx)
        x_new = jnp.where(found, x_new, x)
        g_new = jnp.where(found, g_new, g)
        y = g_new - g
        sy = jnp.dot(s, y)
        rho = jnp.where(jnp.abs(sy) > 1e-10, 1.0 / sy, 0.0)
        I_rs = eye - rho * jnp.outer(s, y)
        H_new = I_rs @ H @ I_rs.T + rho * jnp.outer(s, s)
        H_new = jnp.where(jnp.isfinite(H_new).all() & (rho != 0), H_new, H)
        calls = calls + cfg.max_linesearch + 1
        return (x_new, f_new, g_new, H_new, calls), None

    (x, fx, _, _, calls), _ = jax.lax.scan(
        one_iteration, (x0, f0, g0, eye, jnp.float32(1.0)), None,
        length=cfg.iterations,
    )
    return x, fx, calls


def optimize_constants_fused(
    key,
    trees: TreeBatch,          # [P, L]
    do_opt: jax.Array,         # [P] bool — which members to optimize
    data,
    elementwise_loss,
    operators,
    cfg: OptimizerConfig,
    batch_idx: Optional[jax.Array] = None,
    interpret: bool = False,
    return_diag: bool = False,
):
    """TPU-shaped BFGS: the line search is batched *across* members and
    candidate step sizes into one fused-kernel launch per BFGS iteration
    (candidates = constant-vector variants riding the multi-variant
    kernels' variants axis — one instruction dispatch per unique tree),
    and the gradient comes from the fused forward+backward kernel
    (`fused_grad_multi`) — no [T, L, n] interpreter buffers ever touch
    HBM. Sequential depth per iteration is 2 kernel launches.

    Semantics match `optimize_constants_batch` (same Armijo backtracking,
    restarts, accept-if-better rule); restarts ride the member axis.
    """
    P, L = trees.arity.shape
    R = cfg.nrestarts + 1
    if batch_idx is None:
        X, y, w = data.Xt, data.y, data.weights
    else:
        X = jnp.take(data.Xt, batch_idx, axis=1)
        y = jnp.take(data.y, batch_idx)
        w = None if data.weights is None else jnp.take(data.weights, batch_idx)

    F = X.shape[0]

    # Compile the tree structures ONCE and optimize directly in the
    # program's *compressed* constant space (ops/program.py): the
    # optimization variables are cvals [*, CMAX], the fused gradient
    # kernel already produces gradients in that space, and the L-BFGS
    # state halves. The [P, L, L] span math and all slot scatters stay
    # out of the BFGS loop; the winning constants scatter back into
    # slot order once at the end.
    prog = compile_program(trees, F, len(operators.binary))
    CM = prog.cmax
    used = (jnp.arange(CM, dtype=jnp.int32)[None, :]
            < prog.nconst[:, None])  # [P, CM]

    # Expand members × restarts: x0 and perturbed starts x0*(1+0.5ε)
    # (src/ConstantOptimization.jl:90-100).
    eps = jax.random.normal(key, (P, cfg.nrestarts, CM), trees.const.dtype)
    base = prog.cvals
    starts = jnp.concatenate(
        [base[:, None], base[:, None] * (1.0 + 0.5 * eps)], axis=1,
    )  # [P, R, CM]
    x = starts.reshape(P * R, CM)
    mask_r = jnp.repeat(used, R, axis=0)  # [P*R, CM]

    grad_kw = dict(interpret=interpret)
    if cfg.grad_v_chunk is not None:
        grad_kw["v_chunk"] = cfg.grad_v_chunk
    if cfg.grad_tile_budget is not None:
        grad_kw["tile_budget"] = cfg.grad_tile_budget
    ls_kw = dict(interpret=interpret)
    if cfg.ls_v_chunk is not None:
        ls_kw["v_chunk"] = cfg.ls_v_chunk
    if cfg.ls_tile_budget is not None:
        ls_kw["tile_budget"] = cfg.ls_tile_budget
    if cfg.tree_block is not None:
        grad_kw["tree_block"] = cfg.tree_block
        ls_kw["tree_block"] = cfg.tree_block

    def vg(consts, pg):  # [P*R, CM] -> (loss [P*R], grad [P*R, CM])
        # R restart variants of one tree share the multi-variant grad
        # kernel's variants axis (same dispatch-amortization as the line
        # search below).
        cv = consts.reshape(P, R, CM)
        loss, _, gcomp = fused_grad_multi(
            pg, cv, X, y, w, F, operators, elementwise_loss,
            **grad_kw,
        )
        grad = gcomp.reshape(P * R, CM)
        return loss.reshape(P * R), jnp.where(mask_r, grad, 0.0)

    ts = cfg.shrink ** jnp.arange(cfg.max_linesearch, dtype=x.dtype)  # [C]
    C = cfg.max_linesearch

    def fused_many(cand_x, pg):  # [P*R, C, CM] -> loss [P*R, C]
        # All R*C constant variants of one tree ride the multi-variant
        # kernel's variants axis: ONE instruction-stream dispatch per
        # tree instead of R*C replicated trees (the per-step scalar
        # dispatch is the dominant kernel cost).
        cv = cand_x.reshape(P, R * C, CM)
        loss, _ = fused_loss_multi(
            pg, cv, X, y, w, F, operators, elementwise_loss,
            bf16=cfg.ls_bf16, **ls_kw)
        return loss.reshape(P * R, C)

    fx0, g0 = vg(x, prog)
    calls0 = jnp.ones((P * R,), jnp.float32)
    # Early-exit bookkeeping: rows start live unless the member is
    # gated off or the tree has no constants to optimize.
    if cfg.early_exit:
        active0 = jnp.repeat(do_opt & (prog.nconst > 0), R)
    else:
        active0 = jnp.ones((P * R,), jnp.bool_)

    # L-BFGS two-loop recursion instead of dense-H BFGS: the [m, L, L]
    # Hessian-approximation updates dominated optimizer time on TPU (tiny
    # per-member matrices hit pathological layouts); the recursion is a
    # few dozen vector ops on [m, L]. History covers every iteration of
    # our fixed budget, so search directions match full BFGS in exact
    # arithmetic.
    M = P * R
    hlen = min(int(cfg.iterations), 8)
    S0 = jnp.zeros((hlen, M, CM), x.dtype)
    Y0 = jnp.zeros((hlen, M, CM), x.dtype)
    rho0 = jnp.zeros((hlen, M), x.dtype)

    def lbfgs_direction(g, S, Y, rho):
        # newest (s, y, rho) at index 0; empty history slots have rho == 0
        # and drop out of the recursion as exact no-ops.
        q = g
        alphas = []
        for i in range(hlen):
            alpha = rho[i] * jnp.sum(S[i] * q, axis=1)       # [M]
            q = q - alpha[:, None] * Y[i]
            alphas.append(alpha)
        yy = jnp.sum(Y[0] * Y[0], axis=1)
        sy = jnp.sum(S[0] * Y[0], axis=1)
        gamma = jnp.where((rho[0] != 0) & (yy > 0),
                          sy / jnp.maximum(yy, 1e-30), 1.0)
        q = q * jnp.clip(gamma, 1e-8, 1e8)[:, None]
        for i in reversed(range(hlen)):
            beta = rho[i] * jnp.sum(Y[i] * q, axis=1)
            q = q + (alphas[i] - beta)[:, None] * S[i]
        return -q

    def bfgs_iter(carry, _):
        x, fx, g, S, Y, rho, calls, active = carry
        if cfg.early_exit:
            # Trees with every restart row dead run 1-step programs in
            # both kernels (per-tree dynamic trip counts); their outputs
            # are garbage and fully gated out below via ``active``.
            tree_live = jnp.any(active.reshape(P, R), axis=1)
            pg = dataclasses.replace(
                prog, nsteps=jnp.where(tree_live, prog.nsteps, 1))
        else:
            pg = prog
        d = lbfgs_direction(g, S, Y, rho)
        dg = jnp.sum(d * g, axis=1)
        use_sd = (dg >= 0) | ~jnp.all(jnp.isfinite(d), axis=1)
        d = jnp.where(use_sd[:, None], -g, d)
        dg = jnp.where(use_sd, -jnp.sum(g * g, axis=1), dg)

        # all candidate steps in ONE fused launch: [P*R, C, CM]
        cand_x = x[:, None, :] + ts[None, :, None] * d[:, None, :]
        f_cand = fused_many(cand_x, pg)
        armijo = (
            f_cand <= fx[:, None] + cfg.c1 * ts[None, :] * dg[:, None]
        ) & jnp.isfinite(f_cand)
        any_ok = jnp.any(armijo, axis=1) & active
        first = jnp.argmax(armijo, axis=1)
        t_star = jnp.where(any_ok, ts[first], 0.0)
        s = t_star[:, None] * d
        x_new = x + s
        f_new, g_new = vg(x_new, pg)
        # Descent guard at f32: with an exact line search Armijo already
        # implies f_new < fx, but bf16 candidate losses (~3 significant
        # digits) can accept a step that is uphill at full precision —
        # reject it here using the f32 loss the gradient kernel just
        # computed anyway.
        any_ok = any_ok & (f_new <= fx)
        s = jnp.where(any_ok[:, None], s, 0.0)
        x_new = jnp.where(any_ok[:, None], x_new, x)
        f_new = jnp.where(any_ok, f_new, fx)
        g_new = jnp.where(any_ok[:, None], g_new, g)
        yv = g_new - g
        sy = jnp.sum(s * yv, axis=1)
        rho_new = jnp.where(jnp.abs(sy) > 1e-10, 1.0 / sy, 0.0)
        # push the new (s, y, rho) pair; drop the oldest
        S = jnp.concatenate([s[None], S[:-1]], axis=0)
        Y = jnp.concatenate([yv[None], Y[:-1]], axis=0)
        rho = jnp.concatenate([rho_new[None], rho[:-1]], axis=0)
        calls = calls + (C + 1) * active.astype(calls.dtype)
        new_active = any_ok if cfg.early_exit else active
        return (x_new, f_new, g_new, S, Y, rho, calls, new_active), (
            jnp.sum(active) if return_diag else jnp.zeros((), jnp.int32))

    (x, fx, g, _, _, _, calls, _), diag = jax.lax.scan(
        bfgs_iter, (x, fx0, g0, S0, Y0, rho0, calls0, active0), None,
        length=cfg.iterations,
    )

    # best over restarts, accept iff better than the original loss;
    # restart 0 starts at trees.const, so its initial value IS the baseline.
    baseline = fx0.reshape(P, R)[:, 0]
    fx = jnp.where(jnp.isnan(fx), jnp.inf, fx).reshape(P, R)
    xs = x.reshape(P, R, CM)
    best_r = jnp.argmin(fx, axis=1)
    f_best = jnp.take_along_axis(fx, best_r[:, None], axis=1)[:, 0]
    x_best = jnp.take_along_axis(xs, best_r[:, None, None], axis=1)[:, 0]
    improved = do_opt & (f_best < baseline) & jnp.isfinite(f_best)
    # one scatter back to slot order for the winners
    scattered = trees.const.at[jnp.arange(P)[:, None], prog.cslot].set(
        x_best, mode="drop")
    new_const = jnp.where(improved[:, None], scattered, trees.const)
    f_calls = jnp.sum(calls.reshape(P, R), axis=1) * do_opt
    out = (new_const, improved, jnp.where(improved, f_best, baseline), f_calls)
    if return_diag:
        return out + (diag,)   # [iterations] live-row counts
    return out


def optimize_constants_template(
    key,
    trees: TreeBatch,          # [P, K, L]
    do_opt: jax.Array,         # [P] bool
    data,
    elementwise_loss,
    operators,
    cfg: OptimizerConfig,
    template,                  # models.template.TemplateStructure
    batch_idx: Optional[jax.Array] = None,
    params: Optional[jax.Array] = None,   # [P, total_params, 1]
    fused: bool = False,
    interpret: bool = False,
):
    """Joint optimization of every subexpression's constants plus the
    template parameter vectors as one flat vector per member
    (get_scalar_constants for TemplateExpression includes parameters,
    /root/reference/src/TemplateExpression.jl:411-448).

    Structured like `optimize_constants_fused`: members × restarts ride
    one batch axis, each L-BFGS step is ONE batched template eval for
    the gradient (through `fused_predict_ad`'s cotangent-seeded backward
    kernel when ``fused``) and ONE for all line-search candidates — no
    per-member interpreter buffers.

    Returns (new_const [P, K, L], improved [P], new_loss [P],
    f_calls [P], new_params [P, total_params, 1]).
    """
    from ..models.template import eval_template_batch

    P, K, L = trees.arity.shape
    T = template.total_params
    R = cfg.nrestarts + 1
    C = cfg.max_linesearch
    D = K * L + T
    if batch_idx is None:
        X, y, w = data.Xt, data.y, data.weights
    else:
        X = jnp.take(data.Xt, batch_idx, axis=1)
        y = jnp.take(data.y, batch_idx)
        w = None if data.weights is None else jnp.take(data.weights, batch_idx)

    slot = jnp.arange(L)
    cmask = (
        (slot[None, None, :] < trees.length[..., None])
        & (trees.arity == 0) & (trees.op == LEAF_CONST)
    )  # [P, K, L]
    xmask = jnp.concatenate(
        [cmask.reshape(P, K * L), jnp.ones((P, T), jnp.bool_)], axis=1
    )  # [P, D]
    x0 = jnp.concatenate([
        trees.const.reshape(P, K * L),
        params[..., 0] if (params is not None and T > 0)
        else jnp.zeros((P, T), trees.const.dtype),
    ], axis=1)  # [P, D]

    def rep(a, r):
        return jnp.repeat(a, r, axis=0)

    def loss_of(xb, reps):  # xb [P*reps, D] -> loss [P*reps]
        m = xb.shape[0]
        c = jnp.where(
            rep(cmask, reps).reshape(m, K, L),
            xb[:, : K * L].reshape(m, K, L),
            rep(trees.const, reps),
        )
        member = TreeBatch(
            arity=rep(trees.arity, reps), op=rep(trees.op, reps),
            feat=rep(trees.feat, reps), const=c,
            length=rep(trees.length, reps),
        )
        pred, valid = eval_template_batch(
            member, X, template, operators,
            params=xb[:, K * L:] if T else None,
            fused=fused, interpret=interpret,
        )
        return aggregate_loss(elementwise_loss, pred, y, valid, w)

    def vg(xb, reps):
        # Remat: on the unfused path (CPU / turbo off) the interpreter's
        # per-node residuals for the whole member×restart batch would
        # otherwise live through the backward pass at once.
        @jax.checkpoint
        def total(xx):
            loss = loss_of(xx, reps)
            return jnp.sum(jnp.where(jnp.isfinite(loss), loss, 0.0)), loss

        g, loss = jax.grad(total, has_aux=True)(xb)
        g = jnp.where(rep(xmask, reps), g, 0.0)
        g = jnp.where(jnp.isfinite(g), g, 0.0)
        return loss, g

    # members × restarts: x0 plus perturbed starts x0*(1+0.5 eps)
    eps = jax.random.normal(key, (P, cfg.nrestarts, D), x0.dtype)
    starts = jnp.concatenate(
        [x0[:, None], x0[:, None] * (1.0 + 0.5 * eps)], axis=1
    ).reshape(P * R, D)

    fx0, g0 = vg(starts, R)
    ts = cfg.shrink ** jnp.arange(C, dtype=x0.dtype)
    M = P * R
    hlen = min(int(cfg.iterations), 8)
    S0 = jnp.zeros((hlen, M, D), x0.dtype)
    Y0 = jnp.zeros((hlen, M, D), x0.dtype)
    rho0 = jnp.zeros((hlen, M), x0.dtype)

    def lbfgs_direction(g, S, Y, rho):
        q = g
        alphas = []
        for i in range(hlen):
            alpha = rho[i] * jnp.sum(S[i] * q, axis=1)
            q = q - alpha[:, None] * Y[i]
            alphas.append(alpha)
        yy = jnp.sum(Y[0] * Y[0], axis=1)
        sy = jnp.sum(S[0] * Y[0], axis=1)
        gamma = jnp.where((rho[0] != 0) & (yy > 0),
                          sy / jnp.maximum(yy, 1e-30), 1.0)
        q = q * jnp.clip(gamma, 1e-8, 1e8)[:, None]
        for i in reversed(range(hlen)):
            beta = rho[i] * jnp.sum(Y[i] * q, axis=1)
            q = q + (alphas[i] - beta)[:, None] * S[i]
        return -q

    def bfgs_iter(carry, _):
        x, fx, g, S, Y, rho, calls = carry
        d = lbfgs_direction(g, S, Y, rho)
        dg = jnp.sum(d * g, axis=1)
        use_sd = (dg >= 0) | ~jnp.all(jnp.isfinite(d), axis=1)
        d = jnp.where(use_sd[:, None], -g, d)
        dg = jnp.where(use_sd, -jnp.sum(g * g, axis=1), dg)

        cand_x = x[:, None, :] + ts[None, :, None] * d[:, None, :]
        f_cand = loss_of(cand_x.reshape(M * C, D), R * C).reshape(M, C)
        armijo = (
            f_cand <= fx[:, None] + cfg.c1 * ts[None, :] * dg[:, None]
        ) & jnp.isfinite(f_cand)
        any_ok = jnp.any(armijo, axis=1)
        first = jnp.argmax(armijo, axis=1)
        t_star = jnp.where(any_ok, ts[first], 0.0)
        s = t_star[:, None] * d
        x_new = x + s
        f_new, g_new = vg(x_new, R)
        x_new = jnp.where(any_ok[:, None], x_new, x)
        f_new = jnp.where(any_ok, f_new, fx)
        g_new = jnp.where(any_ok[:, None], g_new, g)
        yv = g_new - g
        sy = jnp.sum(s * yv, axis=1)
        rho_new = jnp.where(jnp.abs(sy) > 1e-10, 1.0 / sy, 0.0)
        S = jnp.concatenate([s[None], S[:-1]], axis=0)
        Y = jnp.concatenate([yv[None], Y[:-1]], axis=0)
        rho = jnp.concatenate([rho_new[None], rho[:-1]], axis=0)
        return (x_new, f_new, g_new, S, Y, rho, calls + C + 1), None

    calls0 = jnp.ones((M,), jnp.float32)
    (xf, fxf, _, _, _, _, calls), _ = jax.lax.scan(
        bfgs_iter, (starts, fx0, g0, S0, Y0, rho0, calls0), None,
        length=cfg.iterations,
    )

    baseline = fx0.reshape(P, R)[:, 0]
    fxf = jnp.where(jnp.isnan(fxf), jnp.inf, fxf).reshape(P, R)
    xs = xf.reshape(P, R, D)
    best_r = jnp.argmin(fxf, axis=1)
    f_best = jnp.take_along_axis(fxf, best_r[:, None], axis=1)[:, 0]
    x_best = jnp.take_along_axis(xs, best_r[:, None, None], axis=1)[:, 0]
    improved = do_opt & (f_best < baseline) & jnp.isfinite(f_best)
    new_const = jnp.where(
        improved[:, None] & cmask.reshape(P, K * L),
        x_best[:, : K * L], trees.const.reshape(P, K * L),
    ).reshape(P, K, L)
    new_p = jnp.where(
        improved[:, None], x_best[:, K * L:], x0[:, K * L:]
    )
    new_loss = jnp.where(improved, f_best, baseline)
    f_calls = jnp.sum(calls.reshape(P, R), axis=1) * do_opt
    new_params = (
        new_p[..., None] if params is not None
        else jnp.zeros((P, 0, 1), trees.const.dtype)
    )
    return new_const, improved, new_loss, f_calls, new_params


def optimize_constants_batch(
    key,
    trees: TreeBatch,          # [P, L]
    do_opt: jax.Array,         # [P] bool — which members to optimize
    data,
    elementwise_loss,
    operators,
    cfg: OptimizerConfig,
    batch_idx: Optional[jax.Array] = None,
    params: Optional[jax.Array] = None,      # [P, K, C] parameter banks
):
    """Optimize constants of selected trees; returns (new_const [P, L],
    improved [P] bool, new_loss [P], f_calls [P]) — plus new_params
    [P, K, C] as the last element when ``params`` is given.

    With ``params``, the parameter banks are optimized *jointly* with the
    tree constants as one flattened vector (the reference includes all
    parameters in the optimization vector,
    /root/reference/src/ParametricExpression.jl:169-171).
    """
    P, L = trees.arity.shape
    parametric = params is not None and params.shape[-2] > 0
    if batch_idx is None:
        X, y, w = data.Xt, data.y, data.weights
        class_idx = data.class_idx
    else:
        X = jnp.take(data.Xt, batch_idx, axis=1)
        y = jnp.take(data.y, batch_idx)
        w = None if data.weights is None else jnp.take(data.weights, batch_idx)
        class_idx = (
            None if data.class_idx is None else jnp.take(data.class_idx, batch_idx)
        )
    if parametric:
        K, C = params.shape[-2:]
        KC = K * C
    else:
        KC = 0

    child, _, _ = tree_structure_arrays(trees, need_depth=False)
    slot = jnp.arange(L)

    def member_fn(k, arity, op, feat, const0, length, ch, active, p0):
        cmask = (slot < length) & (arity == 0) & (op == LEAF_CONST)
        x0 = jnp.concatenate([const0, p0.reshape(-1)])
        mask = jnp.concatenate(
            [cmask, jnp.ones((KC,), jnp.bool_)]
        )

        # Remat: recompute the interpreter forward during the backward pass
        # instead of storing per-slot scan residuals — the population ×
        # restarts vmap would otherwise multiply them into HBM-filling
        # buffers on large datasets.
        @jax.checkpoint
        def f(x):
            c = jnp.where(cmask, x[:L], const0)
            if parametric:
                p_rows = jnp.take(x[L:].reshape(K, C), class_idx, axis=-1)
            else:
                p_rows = None
            pred, valid = eval_single_tree(arity, op, feat, c, length, ch, X,
                                           operators, params=p_rows)
            return aggregate_loss(elementwise_loss, pred, y, valid, w)

        baseline = f(x0)

        def run_from(x_init):
            return _bfgs_minimize(f, x_init, mask, cfg)

        # main start + nrestarts perturbed starts (x0 * (1 + 0.5 eps))
        eps = jax.random.normal(k, (cfg.nrestarts, L + KC), x0.dtype)
        starts = jnp.concatenate(
            [x0[None], x0[None] * (1.0 + 0.5 * eps)], axis=0
        )
        xs, fs, calls = jax.vmap(run_from)(starts)
        best = jnp.argmin(jnp.where(jnp.isnan(fs), jnp.inf, fs))
        x_best, f_best = xs[best], fs[best]
        improved = active & (f_best < baseline) & jnp.isfinite(f_best)
        new_const = jnp.where(improved & cmask, x_best[:L], const0)
        new_p = jnp.where(improved, x_best[L:], x0[L:]).reshape(p0.shape)
        return new_const, improved, jnp.where(improved, f_best, baseline), (
            jnp.sum(calls) * active
        ), new_p

    keys = jax.random.split(key, P)
    p_in = (
        params if parametric
        else jnp.zeros((P, 0), trees.const.dtype)
    )
    new_const, improved, new_loss, f_calls, new_params = jax.vmap(member_fn)(
        keys, trees.arity, trees.op, trees.feat, trees.const, trees.length,
        child, do_opt, p_in,
    )
    if params is not None:
        return new_const, improved, new_loss, f_calls, new_params.reshape(params.shape)
    return new_const, improved, new_loss, f_calls
