"""Batched constant optimization via jax.grad — replaces Optim.jl BFGS +
Enzyme/Mooncake AD (/root/reference/src/ConstantOptimization.jl).

All selected members are optimized in one launch: a vmapped BFGS with
backtracking line search over the tree's constant slots (masked to the
actual constant leaves), with `optimizer_nrestarts` perturbed restarts as
an extra batched axis (src/ConstantOptimization.jl:90-100). Acceptance
only when the best minimum beats the pre-optimization loss (:102-113).

The reference switches to Newton for single-constant trees (:38-47); BFGS
with backtracking converges equivalently for 1-D problems, so one code
path serves all arities.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core.losses import aggregate_loss, loss_to_cost
from ..ops.encoding import LEAF_CONST, TreeBatch, tree_structure_arrays
from ..ops.eval import eval_single_tree

__all__ = ["OptimizerConfig", "optimize_constants_batch"]


class OptimizerConfig(NamedTuple):
    iterations: int = 8          # optimizer_iterations default, src/Options.jl:989
    nrestarts: int = 2           # optimizer_nrestarts, :616
    max_linesearch: int = 8
    c1: float = 1e-4             # Armijo condition coefficient
    shrink: float = 0.5


def _bfgs_minimize(f, x0, mask, cfg: OptimizerConfig):
    """Minimize f over masked dims of x0. Returns (x_best, f_best, f_calls).

    Fixed-iteration BFGS with backtracking; masked (non-constant) dims have
    zero gradient and identity Hessian rows, so they never move.
    """
    n = x0.shape[0]
    eye = jnp.eye(n, dtype=x0.dtype)
    vg = jax.value_and_grad(f)

    def masked_grad(x):
        v, g = vg(x)
        g = jnp.where(mask, g, 0.0)
        g = jnp.where(jnp.isfinite(g), g, 0.0)
        return v, g

    f0, g0 = masked_grad(x0)

    def one_iteration(carry, _):
        x, fx, g, H, calls = carry
        d = -(H @ g)
        dg = jnp.dot(d, g)
        use_sd = dg >= 0
        d = jnp.where(use_sd, -g, d)
        dg = jnp.where(use_sd, -jnp.dot(g, g), dg)

        def ls_step(ls, _):
            t, best_t, best_f, done = ls
            x_try = x + t * d
            f_try = f(x_try)
            ok = (f_try <= fx + cfg.c1 * t * dg) & jnp.isfinite(f_try)
            take = ok & ~done
            best_t = jnp.where(take, t, best_t)
            best_f = jnp.where(take, f_try, best_f)
            return (t * cfg.shrink, best_t, best_f, done | ok), None

        (_, t_star, f_star, found), _ = jax.lax.scan(
            ls_step,
            (jnp.ones((), x.dtype), jnp.zeros((), x.dtype), fx, jnp.bool_(False)),
            None, length=cfg.max_linesearch,
        )
        s = t_star * d
        x_new = x + s
        f_new, g_new = masked_grad(x_new)
        f_new = jnp.where(found, f_new, fx)
        x_new = jnp.where(found, x_new, x)
        g_new = jnp.where(found, g_new, g)
        y = g_new - g
        sy = jnp.dot(s, y)
        rho = jnp.where(jnp.abs(sy) > 1e-10, 1.0 / sy, 0.0)
        I_rs = eye - rho * jnp.outer(s, y)
        H_new = I_rs @ H @ I_rs.T + rho * jnp.outer(s, s)
        H_new = jnp.where(jnp.isfinite(H_new).all() & (rho != 0), H_new, H)
        calls = calls + cfg.max_linesearch + 1
        return (x_new, f_new, g_new, H_new, calls), None

    (x, fx, _, _, calls), _ = jax.lax.scan(
        one_iteration, (x0, f0, g0, eye, jnp.float32(1.0)), None,
        length=cfg.iterations,
    )
    return x, fx, calls


def optimize_constants_batch(
    key,
    trees: TreeBatch,          # [P, L]
    do_opt: jax.Array,         # [P] bool — which members to optimize
    data,
    elementwise_loss,
    operators,
    cfg: OptimizerConfig,
    batch_idx: Optional[jax.Array] = None,
):
    """Optimize constants of selected trees; returns (new_const [P, L],
    improved [P] bool, new_loss [P], f_calls [P])."""
    P, L = trees.arity.shape
    if batch_idx is None:
        X, y, w = data.Xt, data.y, data.weights
    else:
        X = jnp.take(data.Xt, batch_idx, axis=1)
        y = jnp.take(data.y, batch_idx)
        w = None if data.weights is None else jnp.take(data.weights, batch_idx)

    child, _, _ = tree_structure_arrays(trees)
    slot = jnp.arange(L)

    def member_fn(k, arity, op, feat, const0, length, ch, active):
        mask = (slot < length) & (arity == 0) & (op == LEAF_CONST)

        def f(x):
            c = jnp.where(mask, x, const0)
            pred, valid = eval_single_tree(arity, op, feat, c, length, ch, X,
                                           operators)
            return aggregate_loss(elementwise_loss, pred, y, valid, w)

        baseline = f(const0)

        def run_from(x_init):
            return _bfgs_minimize(f, x_init, mask, cfg)

        # main start + nrestarts perturbed starts (x0 * (1 + 0.5 eps))
        eps = jax.random.normal(k, (cfg.nrestarts, L), const0.dtype)
        starts = jnp.concatenate(
            [const0[None], const0[None] * (1.0 + 0.5 * eps)], axis=0
        )
        xs, fs, calls = jax.vmap(run_from)(starts)
        best = jnp.argmin(jnp.where(jnp.isnan(fs), jnp.inf, fs))
        x_best, f_best = xs[best], fs[best]
        improved = active & (f_best < baseline) & jnp.isfinite(f_best)
        new_const = jnp.where(improved & mask, x_best, const0)
        return new_const, improved, jnp.where(improved, f_best, baseline), (
            jnp.sum(calls) * active
        )

    keys = jax.random.split(key, P)
    return jax.vmap(member_fn)(
        keys, trees.arity, trees.op, trees.feat, trees.const, trees.length,
        child, do_opt,
    )
