"""Piece-concatenation gathers: structural tree edits as index arithmetic.

In postfix order every subtree is a contiguous slot range, so every
structural mutation of the reference (insert/delete/append/prepend/rotate/
crossover, /root/reference/src/MutationFunctions.jl) can be expressed as
"concatenate these source spans in this order" — one gather per field, no
pointer surgery, fully vmappable and jit-compatible with static shapes.

The generic helper takes up to NP pieces, each ``(start, len)`` into a
combined source array (possibly the concatenation of several trees plus a
scratch buffer of newly created nodes), and produces the output tree.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops.encoding import TreeBatch

__all__ = ["concat_pieces", "combine_sources", "tree_fields", "make_tree"]


def tree_fields(t: TreeBatch):
    return (t.arity, t.op, t.feat, t.const)


def make_tree(arity, op, feat, const, length) -> TreeBatch:
    return TreeBatch(arity=arity, op=op, feat=feat, const=const, length=length)


def combine_sources(*trees: TreeBatch):
    """Concatenate several unbatched trees' field arrays along the slot axis.

    Piece starts for tree ``i`` are offset by ``i * L``.
    """
    arity = jnp.concatenate([t.arity for t in trees])
    op = jnp.concatenate([t.op for t in trees])
    feat = jnp.concatenate([t.feat for t in trees])
    const = jnp.concatenate([t.const for t in trees])
    return arity, op, feat, const


def concat_pieces(
    sources,  # (arity, op, feat, const) combined source arrays, each [S]
    starts: jax.Array,  # [NP] int32 — start of each piece in source coords
    lens: jax.Array,    # [NP] int32 — piece lengths (0 = skip)
    max_nodes: int,
) -> Tuple[TreeBatch, jax.Array]:
    """Build a tree from ordered source pieces.

    Returns ``(tree, ok)`` where ``ok`` is False when the total length
    exceeds ``max_nodes`` (caller must treat the output as garbage and
    reject the attempt, mirroring the reference's retry-on-constraint
    loop).
    """
    s_arity, s_op, s_feat, s_const = sources
    ends = jnp.cumsum(lens).astype(jnp.int32)          # [NP] exclusive ends
    begins = ends - lens                               # [NP] starts
    total = ends[-1]
    ok = total <= max_nodes
    k = jnp.arange(max_nodes, dtype=jnp.int32)
    # TPU-friendly piece resolution: membership matrix + masked sum in
    # place of searchsorted + gathers (both lower to slow scalar loops
    # on TPU; these are pure vector compares/reduces). Zero-length
    # pieces have begin == end and never match.
    in_piece = (k[:, None] >= begins) & (k[:, None] < ends)      # [L, NP]
    src = jnp.sum(
        jnp.where(in_piece, starts + (k[:, None] - begins), 0), axis=1
    )                                                            # [L]
    mask = k < total
    # one-hot contraction instead of a dynamic gather
    oh = src[:, None] == jnp.arange(s_arity.shape[0])            # [L, S]

    def take(field, fill):
        vals = jnp.sum(jnp.where(oh, field, 0), axis=1)
        return jnp.where(mask, vals, fill).astype(field.dtype)

    tree = TreeBatch(
        arity=take(s_arity, 0),
        op=take(s_op, 0),
        feat=take(s_feat, 0),
        const=take(s_const, 0.0),
        length=jnp.minimum(total, max_nodes).astype(jnp.int32),
    )
    return tree, ok


def splice_span(
    tree: TreeBatch,
    span_start: jax.Array,
    span_end: jax.Array,  # inclusive
    replacement_sources,
    repl_start: jax.Array,
    repl_len: jax.Array,
    max_nodes: int,
) -> Tuple[TreeBatch, jax.Array]:
    """Replace ``tree[span_start..span_end]`` with a span from another source.

    ``replacement_sources`` are combined source arrays that must already
    contain ``tree``'s own arrays first (offset 0) so prefix/suffix pieces
    resolve; ``repl_start`` is in combined coordinates.
    """
    starts = jnp.stack(
        [jnp.int32(0), repl_start, span_end + 1]
    )
    lens = jnp.stack(
        [span_start, repl_len, tree.length - (span_end + 1)]
    )
    return concat_pieces(replacement_sources, starts, lens, max_nodes)
