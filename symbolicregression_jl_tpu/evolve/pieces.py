"""Piece-concatenation gathers: structural tree edits as index arithmetic.

In postfix order every subtree is a contiguous slot range, so every
structural mutation of the reference (insert/delete/append/prepend/rotate/
crossover, /root/reference/src/MutationFunctions.jl) can be expressed as
"concatenate these source spans in this order" — one gather per field, no
pointer surgery, fully vmappable and jit-compatible with static shapes.

The generic helper takes up to NP pieces, each ``(start, len)`` into a
combined source array (possibly the concatenation of several trees plus a
scratch buffer of newly created nodes), and produces the output tree.
"""
# graftlint: assume-traced — pure device-kernel module; callers jit/vmap
# these functions from other modules, outside the module-local analysis.

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops.encoding import TreeBatch

__all__ = ["concat_pieces", "combine_sources", "tree_fields", "make_tree"]


def tree_fields(t: TreeBatch):
    return (t.arity, t.op, t.feat, t.const)


def make_tree(arity, op, feat, const, length) -> TreeBatch:
    return TreeBatch(arity=arity, op=op, feat=feat, const=const, length=length)


def combine_sources(*trees: TreeBatch):
    """Concatenate several unbatched trees' field arrays along the slot axis.

    Piece starts for tree ``i`` are offset by ``i * L``.
    """
    arity = jnp.concatenate([t.arity for t in trees])
    op = jnp.concatenate([t.op for t in trees])
    feat = jnp.concatenate([t.feat for t in trees])
    const = jnp.concatenate([t.const for t in trees])
    return arity, op, feat, const


def concat_pieces(
    sources,  # (arity, op, feat, const) combined source arrays, each [S]
    starts: jax.Array,  # [NP] int32 — start of each piece in source coords
    lens: jax.Array,    # [NP] int32 — piece lengths (0 = skip)
    max_nodes: int,
    int_matmul: bool = False,
) -> Tuple[TreeBatch, jax.Array]:
    """Build a tree from ordered source pieces.

    Returns ``(tree, ok)`` where ``ok`` is False when the total length
    exceeds ``max_nodes`` (caller must treat the output as garbage and
    reject the attempt, mirroring the reference's retry-on-constraint
    loop).
    """
    s_arity, s_op, s_feat, s_const = sources
    ends = jnp.cumsum(lens).astype(jnp.int32)          # [NP] exclusive ends
    begins = ends - lens                               # [NP] starts
    total = ends[-1]
    ok = total <= max_nodes
    k = jnp.arange(max_nodes, dtype=jnp.int32)
    # TPU-friendly piece resolution: membership matrix + masked sum in
    # place of searchsorted + gathers (both lower to slow scalar loops
    # on TPU; these are pure vector compares/reduces). Zero-length
    # pieces have begin == end and never match.
    in_piece = (k[:, None] >= begins) & (k[:, None] < ends)      # [L, NP]
    src = jnp.sum(
        jnp.where(in_piece, starts + (k[:, None] - begins), 0), axis=1
    )                                                            # [L]
    mask = k < total
    # one-hot contraction instead of a dynamic gather
    oh = src[:, None] == jnp.arange(s_arity.shape[0])            # [L, S]

    if int_matmul:
        # The three int fields ride ONE one-hot matmul (MXU, HIGHEST
        # precision — exact for these small ints, cf.
        # step._onehot_rows_i): under the mutation machinery's nested
        # vmap, the where+masked-sum lowering of the int takes gets a
        # pathological 5-D layout at SMALL batch sizes (~6% lane
        # utilization + a cross-lane s32 reduce) that dominated
        # per-cycle cost at reference-scale configs — 0.41 ms/cycle per
        # call site at 31x27, ~2/3 of the whole cycle; the matmul route
        # cuts the cycle 3.07 -> 0.98 ms (profiling/trace_machinery.py,
        # RESULTS.md round 5). At bench-scale batches the masked-sum
        # lowering is efficient and the matmul LOSES (~20% whole-bench)
        # — MutationContext picks per config. const always keeps the
        # masked-sum path: it is never the slow fusion, and the matmul
        # route would need a NaN/inf clamp that changes
        # overflowed-constant bits (cf. step._onehot_rows_f).
        # Always f32 regardless of the tree's const/eval dtype: a
        # bfloat16 matmul would round int values above 256 (e.g. feature
        # indices on wide datasets) before the contraction.
        ohf = oh.astype(jnp.float32)
        ints = jnp.stack([s_arity, s_op, s_feat], axis=1)        # [S, 3]
        iout = jnp.round(jnp.matmul(
            ohf, ints.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST))                # [L, 3]

        def take_i(col, field):
            return jnp.where(mask, iout[:, col].astype(field.dtype), 0)

        arity, op, feat = take_i(0, s_arity), take_i(1, s_op), take_i(
            2, s_feat)
    else:
        def take_sum(field):
            vals = jnp.sum(jnp.where(oh, field, 0), axis=1)
            return jnp.where(mask, vals, 0).astype(field.dtype)

        arity, op, feat = take_sum(s_arity), take_sum(s_op), take_sum(
            s_feat)

    cvals = jnp.sum(jnp.where(oh, s_const, 0.0), axis=1)
    tree = TreeBatch(
        arity=arity,
        op=op,
        feat=feat,
        const=jnp.where(mask, cvals, 0.0).astype(s_const.dtype),
        length=jnp.minimum(total, max_nodes).astype(jnp.int32),
    )
    return tree, ok


def splice_span(
    tree: TreeBatch,
    span_start: jax.Array,
    span_end: jax.Array,  # inclusive
    replacement_sources,
    repl_start: jax.Array,
    repl_len: jax.Array,
    max_nodes: int,
    int_matmul: bool = False,
) -> Tuple[TreeBatch, jax.Array]:
    """Replace ``tree[span_start..span_end]`` with a span from another source.

    ``replacement_sources`` are combined source arrays that must already
    contain ``tree``'s own arrays first (offset 0) so prefix/suffix pieces
    resolve; ``repl_start`` is in combined coordinates.
    """
    starts = jnp.stack(
        [jnp.int32(0), repl_start, span_end + 1]
    )
    lens = jnp.stack(
        [span_start, repl_len, tree.length - (span_end + 1)]
    )
    return concat_pieces(replacement_sources, starts, lens, max_nodes,
                         int_matmul=int_matmul)
