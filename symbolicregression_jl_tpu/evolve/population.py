"""Population state (device-resident) and random initialization.

The reference's `Population` (vector of PopMember,
/root/reference/src/Population.jl:15-18) becomes a struct-of-arrays with a
member axis; `PopMember` fields (tree, cost, loss, birth, complexity,
ref/parent lineage ids, src/PopMember.jl:11-21) are parallel arrays.
Leading axes stack islands (and outputs) for single-launch evolution.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.encoding import TreeBatch
from .mutation import MutationContext, gen_random_tree

__all__ = ["PopulationState", "init_population", "init_params", "zero_params"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PopulationState:
    trees: TreeBatch        # fields [..., P, L]
    cost: jax.Array         # [..., P]
    loss: jax.Array         # [..., P]
    complexity: jax.Array   # [..., P] int32
    birth: jax.Array        # [..., P] int32 (deterministic birth-order ticks,
                            # src/Utils.jl:14-24)
    ref: jax.Array          # [..., P] int32 lineage id
    parent: jax.Array       # [..., P] int32 parent lineage id
    # Per-member parameter banks [..., P, n_params, n_classes]
    # (ParametricExpression, /root/reference/src/ParametricExpression.jl:35-51);
    # zero-sized (n_params == 0) for plain expressions.
    params: jax.Array

    @property
    def pop_size(self) -> int:
        return self.cost.shape[-1]

    @property
    def n_params(self) -> int:
        return self.params.shape[-2]

    def member(self, idx) -> "PopulationState":
        """Gather a single member (or indexed subset) along the member axis.

        Template populations carry an extra subexpression axis on the
        trees ([..., P, K, L] with length [..., P, K]); the member axis
        is located relative to the cost shape either way.
        """
        extra = self.trees.arity.ndim - self.cost.ndim - 1  # 0, or 1 (template)
        take = lambda x: jnp.take(x, idx, axis=-1)
        take_tree = lambda x: jnp.take(x, idx, axis=-(2 + extra))
        take_len = lambda x: jnp.take(x, idx, axis=-(1 + extra))
        return PopulationState(
            trees=TreeBatch(
                arity=take_tree(self.trees.arity),
                op=take_tree(self.trees.op),
                feat=take_tree(self.trees.feat),
                const=take_tree(self.trees.const),
                length=take_len(self.trees.length),
            ),
            cost=take(self.cost),
            loss=take(self.loss),
            complexity=take(self.complexity),
            birth=take(self.birth),
            ref=take(self.ref),
            parent=take(self.parent),
            params=jnp.take(self.params, idx, axis=-3),
        )


def zero_params(batch_shape, n_params: int, n_classes: int, dtype) -> jax.Array:
    return jnp.zeros((*batch_shape, n_params, n_classes), dtype)


def init_params(key, batch_shape, n_params: int, n_classes: int, dtype) -> jax.Array:
    """randn-initialized parameter banks (extra_init_params,
    /root/reference/src/ParametricExpression.jl:35-51)."""
    if n_params == 0:
        return zero_params(batch_shape, n_params, n_classes, dtype)
    return jax.random.normal(key, (*batch_shape, n_params, n_classes), dtype)


def init_population(
    key: jax.Array,
    population_size: int,
    ctx: MutationContext,
    dtype,
    nlength: int = 3,
) -> TreeBatch:
    """Random trees via `gen_random_tree(nlength=3)` (src/Population.jl:35-61).

    Returns only the trees; costs are filled by the caller's eval pass.
    """
    keys = jax.random.split(key, population_size)
    return jax.vmap(lambda k: gen_random_tree(k, nlength, ctx, dtype))(keys)


def init_template_population(
    key: jax.Array,
    population_size: int,
    template,                 # models.template.TemplateStructure
    ctx: MutationContext,
    dtype,
    nlength: int = 3,
) -> TreeBatch:
    """Random template members [P, K, L] — each key generated with its
    own argument count (create_expression for TemplateExpression seeds
    each subexpression independently,
    /root/reference/src/TemplateExpression.jl:462-501)."""
    subs = []
    for k, nf in enumerate(template.num_features):
        ctx_k = ctx._replace(nfeatures=nf, n_params=0)
        kk = jax.random.fold_in(key, k)
        keys = jax.random.split(kk, population_size)
        subs.append(
            jax.vmap(lambda kx: gen_random_tree(kx, nlength, ctx_k, dtype))(keys)
        )
    return TreeBatch(
        arity=jnp.stack([t.arity for t in subs], axis=1),
        op=jnp.stack([t.op for t in subs], axis=1),
        feat=jnp.stack([t.feat for t in subs], axis=1),
        const=jnp.stack([t.const for t in subs], axis=1),
        length=jnp.stack([t.length for t in subs], axis=1),
    )
