"""Tournament selection (best_of_sample, src/Population.jl:109-180).

Sample `tournament_selection_n` members without replacement, adjust costs
by the adaptive-parsimony frequency factor ``cost * exp(scaling * freq)``,
then pick the k-th best where k follows the truncated geometric place
distribution ``p (1-p)^k`` (src/Population.jl:145-179).
"""
# graftlint: assume-traced — pure device-kernel module; callers jit/vmap
# these functions from other modules, outside the module-local analysis.

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.encoding import lane_take

__all__ = ["tournament_select"]


def tournament_select(
    key,
    cost: jax.Array,          # [P]
    complexity: jax.Array,    # [P] int32
    normalized_frequencies,   # [maxsize] (index 0 => complexity 1)
    *,
    tournament_n: int,
    p: float,
    use_frequency: bool,
    adaptive_parsimony_scaling: float,
    maxsize: int,
) -> jax.Array:
    """Return the selected member index."""
    P = cost.shape[0]
    k1, k2 = jax.random.split(key)
    picks = jax.random.permutation(k1, P)[:tournament_n]
    # lane_take everywhere: these [n]-from-[P] gathers are vmapped over
    # (island, slot) and XLA's per-lane gather lowering serialized them
    # into a visible per-cycle cost (see ops.encoding.lane_take).
    c = lane_take(cost, picks)
    if use_frequency:
        size = lane_take(complexity, picks)
        in_range = (size > 0) & (size <= maxsize)
        freq = jnp.where(
            in_range,
            lane_take(normalized_frequencies,
                      jnp.clip(size - 1, 0, maxsize - 1)),
            0.0,
        )
        c = c * jnp.exp(adaptive_parsimony_scaling * freq).astype(c.dtype)
    # NaN costs must never win a tournament:
    c = jnp.where(jnp.isnan(c), jnp.inf, c)
    if p >= 1.0:
        return lane_take(picks, jnp.argmin(c)[None])[0]
    ks = jnp.arange(tournament_n)
    place_weights = p * (1 - p) ** ks
    place = jax.random.categorical(k2, jnp.log(place_weights))
    order = jnp.argsort(c)
    return lane_take(picks, lane_take(order, place[None]))[0]
